"""MCP toolbox: stdio round trip through a real subprocess server, selector
trust boundary, agent integration."""

import asyncio
import sys
from pathlib import Path

import pytest

from calfkit_tpu.client import Client
from calfkit_tpu.engine import FunctionModelClient
from calfkit_tpu.mcp import MCPServerSpec, MCPSession, MCPToolboxNode, Toolbox
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models import ModelResponse, TextOutput, ToolCallOutput
from calfkit_tpu.nodes import Agent
from calfkit_tpu.worker import Worker

SERVER = [sys.executable, str(Path(__file__).parent / "_mcp_server.py")]


class TestMCPSession:
    async def test_initialize_list_call(self):
        session = MCPSession(MCPServerSpec(name="t", command=SERVER))
        await session.start()
        tools = await session.list_tools()
        assert {t["name"] for t in tools} == {"grow", "add", "shout"}
        assert await session.call_tool("add", {"a": 2, "b": 3}) == "5"
        assert await session.call_tool("shout", {"text": "hi"}) == "HI"
        with pytest.raises(Exception):
            await session.call_tool("missing", {})
        await session.stop()

    def test_spec_xor(self):
        with pytest.raises(ValueError):
            MCPServerSpec(name="bad")
        with pytest.raises(ValueError):
            MCPServerSpec(name="bad", command=["x"], url="http://y")


class TestToolboxNode:
    async def test_agent_uses_mcp_tool_through_mesh(self):
        toolbox = MCPToolboxNode(MCPServerSpec(name="calc", command=SERVER))
        turn = {"n": 0}

        def model(messages, params):
            turn["n"] += 1
            if turn["n"] == 1:
                # the namespaced tool name came from the capability view
                names = [t.name for t in params.tool_defs]
                assert "toolbox.calc__add" in names
                return ModelResponse(parts=[ToolCallOutput(
                    tool_call_id="c1", tool_name="toolbox.calc__add",
                    args={"a": 20, "b": 22})])
            # the tool result is in the request
            return ModelResponse(parts=[TextOutput(text="the answer is 42")])

        agent = Agent(
            "mathy", model=FunctionModelClient(model), tools=Toolbox("calc")
        )
        mesh = InMemoryMesh()
        async with Worker([agent, toolbox], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("mathy").execute("what is 20+22?", timeout=15)
            assert result.output == "the answer is 42"
            await client.close()

    async def test_include_trust_boundary(self):
        toolbox = MCPToolboxNode(MCPServerSpec(name="locked", command=SERVER))
        mesh = InMemoryMesh()
        async with Worker([toolbox], mesh=mesh, owns_transport=True) as worker:
            records = [toolbox.capability_record()]
            allowed = Toolbox("locked", include=["shout"]).resolve(records)
            assert [b.tool.name for b in allowed] == ["toolbox.locked__shout"]
            everything = Toolbox("locked").resolve(records)
            assert len(everything) == 3


class TestListChanged:
    async def test_tools_list_changed_refreshes_advert(self):
        """A server-side tools/list_changed notification re-lists off the
        receive loop and the NEW tool appears in the capability record
        (heartbeats re-derive the record, so the mesh view follows within
        one interval)."""
        toolbox = MCPToolboxNode(MCPServerSpec(name="grower", command=SERVER))
        await toolbox.start_session()
        try:
            before = {t.name for t in toolbox.capability_record().tools}
            assert "toolbox.grower__extra_shout" not in before

            result = await toolbox._session.call_tool("grow", {})
            assert "grown" in str(result)
            # the notification arrives async; the relist follows it
            for _ in range(100):
                names = {t.name for t in toolbox.capability_record().tools}
                if "toolbox.grower__extra_shout" in names:
                    break
                await asyncio.sleep(0.05)
            assert "toolbox.grower__extra_shout" in names

            # and the new tool is callable through the session
            doubled = await toolbox._session.call_tool(
                "extra_shout", {"text": "ab"}
            )
            assert "ABAB" in str(doubled)
        finally:
            await toolbox.stop_session()


class TestHTTPTransport:
    async def test_http_roundtrip_json_and_sse(self):
        """The streamable-HTTP path: initialize + tools/list as plain JSON,
        tools/call answered as an SSE event stream (both response shapes the
        spec allows)."""
        import json as _json
        import threading
        from http.server import BaseHTTPRequestHandler, HTTPServer

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                body = _json.loads(
                    self.rfile.read(int(self.headers["Content-Length"]))
                )
                method = body.get("method")
                rpc_id = body.get("id")
                if rpc_id is None:  # notification
                    self.send_response(202)
                    self.end_headers()
                    return
                if method == "initialize":
                    result = {
                        "protocolVersion": body["params"]["protocolVersion"],
                        "capabilities": {"tools": {}},
                        "serverInfo": {"name": "http-mcp", "version": "0"},
                    }
                elif method == "tools/list":
                    result = {"tools": [{
                        "name": "ping",
                        "description": "Pong.",
                        "inputSchema": {"type": "object", "properties": {}},
                    }]}
                elif method == "tools/call":
                    # answer as an SSE stream: the transport must dig the
                    # matching id out of the data: lines
                    payload = _json.dumps({
                        "jsonrpc": "2.0", "id": rpc_id,
                        "result": {"content": [{"type": "text",
                                                "text": "pong"}]},
                    })
                    blob = f"event: message\ndata: {payload}\n\n".encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Content-Length", str(len(blob)))
                    self.end_headers()
                    self.wfile.write(blob)
                    return
                else:
                    result = {}
                blob = _json.dumps(
                    {"jsonrpc": "2.0", "id": rpc_id, "result": result}
                ).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(blob)))
                self.end_headers()
                self.wfile.write(blob)

            def log_message(self, *a):  # quiet
                pass

        server = HTTPServer(("127.0.0.1", 0), Handler)
        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            url = f"http://127.0.0.1:{server.server_port}/mcp"
            session = MCPSession(MCPServerSpec(name="httpbox", url=url))
            await session.start()
            try:
                tools = await session.list_tools()
                assert [t["name"] for t in tools] == ["ping"]
                out = await session.call_tool("ping", {})
                assert "pong" in str(out)
            finally:
                await session.stop()
        finally:
            server.shutdown()
            thread.join(timeout=5)
