"""Handoff family: arbitration kernel, tool-def rendering, and end-to-end
precedence (reference analogs: tests/test_handoff_arbitration.py,
test_handoff_tool_def.py, test_handoff_precedence.py,
test_handoff_dispatch.py)."""

import pytest

from calfkit_tpu.client import Client
from calfkit_tpu.engine import FunctionModelClient, TestModelClient
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models import ModelResponse, TextOutput, ToolCallOutput
from calfkit_tpu.models.agents import AgentCard
from calfkit_tpu.nodes import Agent, agent_tool
from calfkit_tpu.peers import Handoff, Messaging
from calfkit_tpu.peers.handoff import (
    HANDOFF_TOOL,
    INVALID_TARGET,
    SUPERSEDED_STUB,
    arbitrate_handoff,
)
from calfkit_tpu.worker import Worker


def _call(cid: str, name: str, args) -> ToolCallOutput:
    return ToolCallOutput(tool_call_id=cid, tool_name=name, args=args)


def _handoff(cid: str, target) -> ToolCallOutput:
    return _call(cid, HANDOFF_TOOL, {"agent_name": target})


class TestArbitration:
    def test_first_valid_handoff_wins(self):
        decision = arbitrate_handoff(
            [_handoff("h1", "alpha"), _handoff("h2", "beta")],
            allowed_names={"alpha", "beta"},
        )
        assert decision.target == "alpha"
        assert decision.winner.tool_call_id == "h1"
        assert decision.stubbed == {"h2": SUPERSEDED_STUB}
        assert decision.rejected == {}

    def test_invalid_target_rejected_with_pinned_text(self):
        decision = arbitrate_handoff(
            [_handoff("h1", "ghost")], allowed_names={"alpha"}
        )
        assert decision.winner is None
        assert decision.rejected == {"h1": INVALID_TARGET.format(name="ghost")}

    def test_invalid_then_valid_still_hands_off(self):
        decision = arbitrate_handoff(
            [_handoff("h1", "ghost"), _handoff("h2", "alpha")],
            allowed_names={"alpha"},
        )
        assert decision.target == "alpha"
        assert decision.rejected["h1"]
        assert "h2" not in decision.stubbed

    def test_unparseable_args_treated_as_invalid(self):
        decision = arbitrate_handoff(
            [_call("h1", HANDOFF_TOOL, "{not json")], allowed_names={"a"}
        )
        assert decision.winner is None
        assert "h1" in decision.rejected

    def test_winning_handoff_stubs_sibling_non_handoff_calls(self):
        # whole-response arbitration: once a handoff wins, sibling TOOL
        # calls in the same turn are superseded too (the conversation is
        # leaving this agent)
        decision = arbitrate_handoff(
            [_call("t1", "search", {"q": "x"}), _handoff("h1", "alpha")],
            allowed_names={"alpha"},
        )
        assert decision.target == "alpha"
        assert decision.stubbed["t1"] == SUPERSEDED_STUB

    def test_no_handoff_calls_is_a_no_op(self):
        decision = arbitrate_handoff(
            [_call("t1", "search", {})], allowed_names={"alpha"}
        )
        assert decision.winner is None
        assert decision.stubbed == {} and decision.rejected == {}


class TestToolDef:
    CARDS = [
        AgentCard(name="alpha", description="does a", input_topic="agent.alpha.private.input"),
        AgentCard(name="beta", description="does b", input_topic="agent.beta.private.input"),
        AgentCard(name="me", description="self", input_topic="agent.me.private.input"),
    ]

    def test_curated_names_enum_excludes_self(self):
        tool = Handoff("alpha", "me").tool_def(self.CARDS, self_name="me")
        schema = tool.parameters_schema["properties"]["agent_name"]
        assert schema["enum"] == ["alpha"]  # self filtered even if curated

    def test_discover_lists_all_live_peers(self):
        tool = Handoff(discover=True).tool_def(self.CARDS, self_name="me")
        assert tool.parameters_schema["properties"]["agent_name"]["enum"] == [
            "alpha", "beta",
        ]
        # the directory is the model's routing surface
        assert "does a" in tool.description and "does b" in tool.description

    def test_empty_directory_degrades_to_plain_string(self):
        tool = Handoff(discover=True).tool_def([], self_name="me")
        assert "enum" not in tool.parameters_schema["properties"]["agent_name"]

    def test_curated_xor_discover_enforced(self):
        with pytest.raises(Exception):
            Handoff("alpha", discover=True)
        with pytest.raises(Exception):
            Handoff()  # neither names nor discover


class TestHandoffEndToEnd:
    async def test_losing_handoffs_and_tools_superseded(self):
        """One turn with [tool_call, handoff->b, handoff->c]: b answers the
        caller; the tool never runs; the losing handoff never reaches c."""
        tool_ran = []

        @agent_tool
        def side_effect(x: int) -> int:
            """Side effect.

            Args:
                x: X.
            """
            tool_ran.append(x)
            return x

        def chooser(messages, params):
            if not any(isinstance(m, ModelResponse) for m in messages):
                return ModelResponse(parts=[
                    _call("t1", "side_effect", {"x": 1}),
                    _handoff("h1", "winner"),
                    _handoff("h2", "loser"),
                ])
            return ModelResponse(parts=[TextOutput(text="fell through")])

        chooser_agent = Agent(
            "chooser",
            model=FunctionModelClient(chooser),
            tools=[side_effect],
            peers=[Handoff("winner", "loser")],
        )
        winner = Agent(
            "winner", model=TestModelClient(custom_output_text="winner answers"),
            description="w",
        )
        loser_calls = []

        def loser_model(messages, params):
            loser_calls.append(1)
            return ModelResponse(parts=[TextOutput(text="loser answers")])

        loser = Agent("loser", model=FunctionModelClient(loser_model), description="l")

        mesh = InMemoryMesh()
        team = [chooser_agent, winner, loser, side_effect]
        async with Worker(team, mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("chooser").execute("go", timeout=15)
            assert result.output == "winner answers"
            assert tool_ran == []      # superseded before dispatch
            assert loser_calls == []   # the losing handoff never dispatched
            await client.close()

    async def test_rejected_handoff_returns_to_model_as_retry(self):
        turns = []

        def model(messages, params):
            turns.append(len(messages))
            if len(turns) == 1:
                return ModelResponse(parts=[_handoff("h1", "ghost")])
            # the retry text came back; answer normally
            return ModelResponse(parts=[TextOutput(text="recovered")])

        agent = Agent(
            "retrier", model=FunctionModelClient(model),
            peers=[Handoff(discover=True)],
        )
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("retrier").execute("go", timeout=15)
            assert result.output == "recovered"
            assert len(turns) == 2
            await client.close()

    async def test_one_selector_per_peer_kind(self):
        with pytest.raises(Exception, match="one peer selector per kind"):
            Agent(
                "dup", model=TestModelClient(),
                peers=[Handoff("a"), Handoff("b")],
            )
        # distinct kinds are fine
        Agent(
            "ok", model=TestModelClient(),
            peers=[Handoff("a"), Messaging("b")],
        )
