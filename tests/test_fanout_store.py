"""The fan-out store: pure state machine + ktables impl invariants.

Reference analogs: tests/test_fanout_store.py, test_fanout_fold.py,
test_fanout_records.py — exactly-once fold semantics over at-least-once
delivery, provable without a broker.
"""

import pytest

from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models.fanout import (
    EnvelopeSnapshot,
    FanoutOpen,
    FanoutOutcome,
    FanoutState,
    SlotRef,
)
from calfkit_tpu.models.session_context import SessionContext, WorkflowState
from calfkit_tpu.nodes.fanout_store import (
    KtablesFanoutBatchStore,
    classify_sibling,
    fold_decision,
    record_outcome,
)


def _open(*slot_ids: str) -> FanoutOpen:
    return FanoutOpen(
        fanout_id="f1", slots=[SlotRef(slot_id=s) for s in slot_ids]
    )


def _state(*slot_ids: str) -> FanoutState:
    return FanoutState(open=_open(*slot_ids))


def _outcome(slot_id: str) -> FanoutOutcome:
    return FanoutOutcome(slot_id=slot_id)


class TestClassification:
    def test_expected_then_duplicate(self):
        state = _state("a", "b")
        assert classify_sibling(state, "a") == "expected"
        state = record_outcome(state, _outcome("a"))
        assert classify_sibling(state, "a") == "duplicate"
        assert classify_sibling(state, "b") == "expected"

    def test_unknown_slot_is_stray(self):
        assert classify_sibling(_state("a"), "zzz") == "stray"

    def test_closed_batch_is_closed(self):
        """A reply after close (state tombstoned -> load None) classifies
        ``closed`` — redelivery after a completed batch folds nothing."""
        assert classify_sibling(None, "a") == "closed"

    def test_fold_is_idempotent(self):
        """Recording the same outcome twice yields the same state — the
        at-least-once-delivery property."""
        state = _state("a", "b")
        once = record_outcome(state, _outcome("a"))
        twice = record_outcome(once, _outcome("a"))
        assert once == twice

    def test_record_does_not_mutate_input(self):
        state = _state("a")
        record_outcome(state, _outcome("a"))
        assert state.outcomes == {}  # pure transition


class TestFoldDecision:
    def test_parked_until_all_slots_folded(self):
        state = _state("a", "b", "c")
        for slot in ("a", "b"):
            state = record_outcome(state, _outcome(slot))
            assert fold_decision(state) == "parked"
        state = record_outcome(state, _outcome("c"))
        assert fold_decision(state) == "complete"

    def test_single_slot_batch_completes_immediately(self):
        state = record_outcome(_state("a"), _outcome("a"))
        assert fold_decision(state) == "complete"

    def test_stray_outcomes_do_not_complete_a_batch(self):
        """Extra outcomes for unknown slots never count toward completion."""
        state = record_outcome(_state("a", "b"), _outcome("zzz"))
        assert fold_decision(state) == "parked"


def _snapshot() -> EnvelopeSnapshot:
    return EnvelopeSnapshot(
        context=SessionContext(), workflow=WorkflowState()
    )


class TestKtablesStore:
    async def test_open_then_load_roundtrip(self):
        mesh = InMemoryMesh()
        await mesh.start()
        store = KtablesFanoutBatchStore(mesh, "agent.a")
        await store.start()
        await store.open("f1", _open("a", "b"), _snapshot())
        state = await store.load("f1")
        assert state is not None and state.open.slot_ids() == {"a", "b"}
        assert await store.load_snapshot("f1") is not None
        await store.stop()
        await mesh.stop()

    async def test_close_tombstones_both_tables(self):
        mesh = InMemoryMesh()
        await mesh.start()
        store = KtablesFanoutBatchStore(mesh, "agent.a")
        await store.start()
        await store.open("f1", _open("a"), _snapshot())
        await store.close("f1")
        assert await store.load("f1") is None
        assert await store.load_snapshot("f1") is None
        await store.stop()
        await mesh.stop()

    async def test_registration_implies_snapshot(self):
        """The write-order invariant observed from a SECOND store instance
        (another worker): any registered batch must have a restorable
        snapshot — basestate is written and acked before state."""
        mesh = InMemoryMesh()
        await mesh.start()
        writer_store = KtablesFanoutBatchStore(mesh, "agent.a")
        await writer_store.start()
        await writer_store.open("f1", _open("a"), _snapshot())

        observer = KtablesFanoutBatchStore(mesh, "agent.a")
        await observer.start()
        state = await observer.load("f1")
        assert state is not None
        snapshot = await observer.load_snapshot("f1")
        assert snapshot is not None  # registered => restorable
        await writer_store.stop()
        await observer.stop()
        await mesh.stop()

    async def test_save_persists_folds_across_instances(self):
        """A crash between folds loses nothing: a fresh store (new worker)
        sees every persisted outcome."""
        mesh = InMemoryMesh()
        await mesh.start()
        first = KtablesFanoutBatchStore(mesh, "agent.a")
        await first.start()
        await first.open("f1", _open("a", "b"), _snapshot())
        state = await first.load("f1")
        await first.save(record_outcome(state, _outcome("a")))
        await first.stop()  # "crash"

        second = KtablesFanoutBatchStore(mesh, "agent.a")
        await second.start()
        resumed = await second.load("f1")
        assert classify_sibling(resumed, "a") == "duplicate"
        assert classify_sibling(resumed, "b") == "expected"
        await second.stop()
        await mesh.stop()

    async def test_stores_are_isolated_per_node(self):
        mesh = InMemoryMesh()
        await mesh.start()
        a = KtablesFanoutBatchStore(mesh, "agent.a")
        b = KtablesFanoutBatchStore(mesh, "agent.b")
        await a.start()
        await b.start()
        await a.open("f1", _open("x"), _snapshot())
        assert await b.load("f1") is None  # different node, different tables
        await a.stop()
        await b.stop()
        await mesh.stop()
