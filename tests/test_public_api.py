"""The top-level import surface a reference user lands on.

Reference anchor: calfkit/__init__.py exports the whole user vocabulary
from the package root; this pin keeps ours equivalent (every name lazily
importable, no heavy deps at import time) so `from calfkit_tpu import X`
works for everything docs/migrating.md promises.
"""

from __future__ import annotations

import subprocess
import sys


class TestPublicSurface:
    def test_every_lazy_export_resolves(self):
        import calfkit_tpu

        for name in calfkit_tpu._LAZY:
            assert getattr(calfkit_tpu, name) is not None, name

    def test_core_vocabulary_present(self):
        import calfkit_tpu as ck

        # the names the migration guide promises, spot-checked by family
        for name in (
            "Client", "Worker", "Agent", "StatelessAgent", "agent_tool",
            "consumer", "Tools", "Toolbox", "Messaging", "Handoff",
            "InvocationHandle", "InvocationResult", "EventStream",
            "NodeFaultError", "ClientTimeoutError", "ErrorReport",
            "FaultTypes", "InMemoryMesh", "KafkaWireMesh",
            "ConnectionProfile", "JaxLocalModelClient", "OpenAIModelClient",
            "BedrockModelClient", "MistralModelClient",
        ):
            assert getattr(ck, name) is not None, name

    def test_unknown_name_raises_attribute_error(self):
        import calfkit_tpu

        try:
            calfkit_tpu.DefinitelyNotAThing
        except AttributeError as exc:
            assert "DefinitelyNotAThing" in str(exc)
        else:
            raise AssertionError("missing name resolved")

    def test_import_is_lazy(self):
        """`import calfkit_tpu` must not eagerly import any subsystem —
        CLI startup and pure-client processes stay light.  (This image's
        sitecustomize preloads jax into EVERY interpreter, so the pin is
        on calfkit_tpu's own submodules, not on jax.)"""
        code = (
            "import sys; import calfkit_tpu; "
            "heavy = [m for m in sys.modules if m.startswith("
            "('calfkit_tpu.inference', 'calfkit_tpu.engine', "
            "'calfkit_tpu.nodes', 'calfkit_tpu.client', "
            "'calfkit_tpu.providers', 'calfkit_tpu.mesh'))]; "
            "assert not heavy, heavy; print('lazy ok')"
        )
        out = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "lazy ok" in out.stdout
