"""ISSUE 2 acceptance: one run through the WHOLE traced pipeline.

Drives client → agent → tool → the real local JAX engine over the
in-memory mesh and asserts:

- a single trace_id (== the correlation id) yields ≥ 4 parent-linked
  spans covering dispatch, the agent turn, the tool call, and engine
  prefill/decode;
- the TTFT and inter-token histograms are non-empty in the
  ``metrics_text()`` Prometheus output;
- spans reached the compacted ``mesh.traces`` topic (the operator-surface
  read path), and ``ck trace``'s renderer draws the waterfall from them.
"""

from __future__ import annotations

import jax

jax.config.update("jax_platforms", "cpu")

from calfkit_tpu import protocol
from calfkit_tpu.client import Client
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models.messages import ToolCallOutput
from calfkit_tpu.models.records import SpanRecord
from calfkit_tpu.nodes import Agent, agent_tool
from calfkit_tpu.observability.trace import TRACER
from calfkit_tpu.worker import Worker


@agent_tool
def lookup_fact(topic: str) -> str:
    """Look up a fact.

    Args:
        topic: What to look up.
    """
    return f"fact about {topic}"


class _ScriptedToolParser:
    """Stateful tool_call_parser: the first model turn becomes a tool
    call, every later turn is a final text answer — turning the random
    debug model into a deterministic agent→tool→agent script while the
    REAL engine does the prefill/decode work being traced."""

    def __init__(self) -> None:
        self.turns = 0

    def __call__(self, text: str):
        self.turns += 1
        if self.turns == 1:
            return "", [
                ToolCallOutput(
                    tool_call_id="tc-1",
                    tool_name="lookup_fact",
                    args={"topic": "tracing"},
                )
            ]
        return "final answer", []


class TestTracedPipeline:
    async def test_trace_spans_and_latency_histograms(self):
        from calfkit_tpu.inference import JaxLocalModelClient
        from calfkit_tpu.inference.config import RuntimeConfig, preset
        from calfkit_tpu.observability.metrics import metrics_text

        model = JaxLocalModelClient(
            config=preset("debug", max_seq_len=1024),
            runtime=RuntimeConfig(
                max_batch_size=2, max_seq_len=1024, prefill_chunk=64,
                decode_steps_per_dispatch=4,
            ),
            tool_call_parser=_ScriptedToolParser(),
            max_new_tokens=8,
        )
        mesh = InMemoryMesh()
        agent = Agent("traced", model=model, tools=[lookup_fact])
        async with Worker([agent, lookup_fact], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("traced").start("trace me", timeout=45)
            trace_id = handle.correlation_id
            result = await handle.result()
            assert result.output == "final answer"
            await client.close()

            spans = TRACER.finished(trace_id)
            by_name: dict[str, list[SpanRecord]] = {}
            for span in spans:
                by_name.setdefault(span.name, []).append(span)

            # coverage: dispatch, agent turn, tool call, engine
            # prefill/decode all traced under ONE trace id
            assert "client.dispatch" in by_name
            assert "mesh.dispatch" in by_name
            assert "agent.turn" in by_name
            assert "tool.hop" in by_name
            assert "engine.generate" in by_name
            assert "engine.prefill" in by_name
            assert "engine.decode" in by_name
            # two model turns (initial + after the tool result)
            assert len(by_name["agent.turn"]) == 2
            assert len(by_name["engine.generate"]) == 2

            # parent linkage: ≥4 linked spans whose parents resolve
            # within the trace
            ids = {s.span_id for s in spans}
            linked = [
                s for s in spans
                if s.parent_span_id and s.parent_span_id in ids
            ]
            assert len(linked) >= 4
            # the chain is rooted at the client dispatch span
            roots = [s for s in spans if not s.parent_span_id]
            assert [r.name for r in roots] == ["client.dispatch"]
            # engine spans hang off an agent turn which hangs off a hop
            turn = by_name["agent.turn"][0]
            gen = next(
                s for s in by_name["engine.generate"]
                if s.parent_span_id == turn.span_id
            )
            assert gen.attrs["generated_tokens"] > 0
            prefill = next(
                s for s in by_name["engine.prefill"]
                if s.parent_span_id == gen.span_id
            )
            assert prefill.attrs["ttft_ms"] > 0

            # latency histograms are non-empty in the Prometheus output
            text = metrics_text()

            def count_of(metric: str) -> int:
                for line in text.splitlines():
                    if line.startswith(f"{metric}_count "):
                        return int(line.split()[-1])
                raise AssertionError(f"{metric} missing from exposition")

            assert count_of("calfkit_engine_ttft_ms") > 0
            assert count_of("calfkit_engine_inter_token_ms") > 0
            assert count_of("calfkit_engine_queue_wait_ms") > 0
            assert count_of("calfkit_engine_prefill_ms") > 0

            # the operator read path: spans reached the compacted topic
            # and the CLI renderer draws the waterfall from them
            from calfkit_tpu.cli.obs import _parse_spans, render_waterfall

            reader = mesh.table_reader(protocol.TRACES_TOPIC)
            await reader.start()
            topic_spans = _parse_spans(reader.items(), trace_id)
            await reader.stop()
            topic_names = {s.name for s in topic_spans}
            assert {
                "client.dispatch", "agent.hop", "tool.hop",
                "agent.turn", "engine.generate",
            } <= topic_names
            waterfall = render_waterfall(topic_spans)
            assert "agent.turn" in waterfall
            assert f"trace {trace_id}" in waterfall
        await model.stop()

    async def test_fault_marks_hop_span_error(self):
        """A faulting tool's hop span records status=error with the typed
        fault code — fail-open tracing still tells the truth."""

        @agent_tool
        def broken_tool(x: str) -> str:
            """Always explodes.

            Args:
                x: Ignored.
            """
            raise RuntimeError("kaboom")

        def scripted(messages, params):
            from calfkit_tpu.models import ModelResponse, TextOutput

            has_returns = any(
                getattr(part, "kind", "") in ("tool_return", "retry")
                for m in messages
                for part in getattr(m, "parts", [])
            )
            if has_returns:
                return ModelResponse(parts=[TextOutput(text="recovered")])
            return ModelResponse(parts=[
                ToolCallOutput(
                    tool_call_id="bt-1", tool_name="broken_tool",
                    args={"x": "y"},
                )
            ])

        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.nodes.agent import surface_to_model

        mesh = InMemoryMesh()
        agent = Agent(
            "fault_traced",
            model=FunctionModelClient(scripted),
            tools=[broken_tool],
            on_tool_error=lambda marker, ctx, report: surface_to_model(
                ctx, report
            ),
        )
        async with Worker([agent, broken_tool], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("fault_traced").start("go", timeout=30)
            trace_id = handle.correlation_id
            result = await handle.result()
            assert result.output == "recovered"
            await client.close()
        spans = TRACER.finished(trace_id)
        tool_hops = [s for s in spans if s.name == "tool.hop"]
        assert tool_hops and tool_hops[0].status == "error"
        assert tool_hops[0].attrs["error_type"]
