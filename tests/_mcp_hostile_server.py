"""A HOSTILE stdio MCP server for robustness tests: answers initialize
correctly, then responds to tool calls per a scripted misbehavior chosen
by argv[1].  The client must survive every mode without its read loop
dying or its pending futures hanging forever.

Modes:
- garbage-frames: interleaves non-JSON, non-object JSON, and unknown-id
  frames before every real response
- malformed-error: error member is a bare string; then a non-object
  result
- huge-line: emits a ~1 MiB response (legal — must NOT break framing)
- cursor-loop: tools/list pagination repeats the same cursor forever
- weird-content: tools/call returns non-list content / non-dict entries
"""

import json
import sys

MODE = sys.argv[1] if len(sys.argv) > 1 else "garbage-frames"


def send(obj):
    sys.stdout.write(json.dumps(obj) + "\n")
    sys.stdout.flush()


def send_raw(text):
    sys.stdout.write(text + "\n")
    sys.stdout.flush()


def reply(rpc_id, result):
    send({"jsonrpc": "2.0", "id": rpc_id, "result": result})


calls = 0
for line in sys.stdin:
    try:
        message = json.loads(line)
    except ValueError:
        continue
    method = message.get("method")
    rpc_id = message.get("id")
    if method == "initialize":
        reply(rpc_id, {"serverInfo": {"name": f"hostile-{MODE}"}})
        continue
    if rpc_id is None:
        continue  # notification
    if MODE == "garbage-frames":
        send_raw("this is not json at all {{{")
        send_raw(json.dumps([1, 2, 3]))
        send_raw(json.dumps("just a string"))
        send_raw(json.dumps(42))
        send({"jsonrpc": "2.0", "id": 999999, "result": {"stray": True}})
        if method == "tools/list":
            reply(rpc_id, {"tools": [{
                "name": "echo", "description": "echo",
                "inputSchema": {"type": "object", "properties": {}},
            }]})
        else:
            reply(rpc_id, {"content": [{"type": "text", "text": "survived"}]})
    elif MODE == "malformed-error":
        calls += 1
        if calls == 1:
            send({"jsonrpc": "2.0", "id": rpc_id, "error": "just a string"})
        else:
            send({"jsonrpc": "2.0", "id": rpc_id, "result": 42})
    elif MODE == "huge-line":
        reply(rpc_id, {"content": [{"type": "text", "text": "x" * (1 << 20)}]})
    elif MODE == "cursor-loop":
        reply(rpc_id, {"tools": [], "nextCursor": "same-cursor-forever"})
    elif MODE == "weird-content":
        calls += 1
        if calls == 1:
            reply(rpc_id, {"content": "not a list"})
        else:
            reply(rpc_id, {"content": [
                "not a dict", {"type": "text", "text": "ok"}, {"type": "image"},
            ]})
