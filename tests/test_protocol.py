"""Wire-protocol constants, routing grammar, keying."""

import pytest

from calfkit_tpu import protocol, routing
from calfkit_tpu.keying import partition_key


class TestHeaders:
    def test_decode_bytes_and_str(self):
        assert protocol.decode_header_str(b"call") == "call"
        assert protocol.decode_header_str("call") == "call"
        assert protocol.decode_header_str(None) is None
        assert protocol.decode_header_str(b"\xff\xfe") is None

    def test_header_map_drops_undecodable(self):
        out = protocol.header_map({"a": b"x", "b": b"\xff\xfe", "c": "y"})
        assert out == {"a": "x", "c": "y"}

    def test_emitter_roundtrip(self):
        hdr = protocol.emitter_header("agent", "weather")
        assert protocol.parse_emitter(hdr) == ("agent", "weather")
        assert protocol.parse_emitter(None) == (None, None)
        assert protocol.parse_emitter("nope") == (None, None)

    def test_envelope_filter(self):
        assert protocol.is_envelope({})
        assert protocol.is_envelope({protocol.HDR_WIRE: "envelope"})
        assert not protocol.is_envelope({protocol.HDR_WIRE: "step"})


class TestTopics:
    def test_topic_safety(self):
        assert protocol.is_topic_safe("agent.weather.private.input")
        assert not protocol.is_topic_safe("")
        assert not protocol.is_topic_safe(".")
        assert not protocol.is_topic_safe("..")
        assert not protocol.is_topic_safe("has space")
        assert not protocol.is_topic_safe("x" * 250)

    def test_layout(self):
        assert protocol.agent_input_topic("w") == "agent.w.private.input"
        assert protocol.agent_return_topic("w") == "agent.w.private.return"
        assert protocol.tool_input_topic("t") == "tool.t.input"
        with pytest.raises(ValueError):
            protocol.agent_input_topic("bad name")


class TestRouting:
    def test_validate(self):
        routing.validate_route_pattern("a.b.c")
        routing.validate_route_pattern("a.b.*")
        routing.validate_route_pattern("*")
        with pytest.raises(routing.RouteError):
            routing.validate_route_pattern("a.*.c")
        with pytest.raises(routing.RouteError):
            routing.validate_route_pattern("a..b")
        with pytest.raises(routing.RouteError):
            routing.validate_route("a.*")

    def test_matching(self):
        assert routing.route_matches("a.b", "a.b")
        assert not routing.route_matches("a.b", "a.b.c")
        assert routing.route_matches("a.*", "a.b.c")
        assert routing.route_matches("a.*", "a")
        assert not routing.route_matches("a.*", "ab")
        assert routing.route_matches("*", "anything.at.all")

    def test_chain_order_most_specific_first(self):
        patterns = ["*", "run.*", "run.step", "run"]
        assert routing.match_chain(patterns, "run.step") == ["run.step", "run.*", "*"]
        assert routing.match_chain(patterns, "run") == ["run", "run.*", "*"]


class TestKeying:
    def test_partition_key(self):
        assert partition_key("abc") == b"abc"
        with pytest.raises(ValueError):
            partition_key("")
