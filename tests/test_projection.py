"""POV projection attribution corners + the message-aware preambles.

Reference anchors: calfkit/nodes/_projection.py:88-326 and the VERDICT r1
item 9 corner list (interleaved foreign tool calls, retry parts from
foreign agents, transparent single-participant mode, surfaced briefings).
"""

from __future__ import annotations

from calfkit_tpu.engine.turn import FINAL_RESULT_TOOL
from calfkit_tpu.models.messages import (
    ModelRequest,
    ModelResponse,
    RetryPart,
    SystemPart,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    UserPart,
)
from calfkit_tpu.nodes.projection import (
    project,
    step_preamble,
    structured_output_preamble,
)
from calfkit_tpu.peers.handoff import HANDOFF_TOOL


def _resp(author, *parts):
    return ModelResponse(parts=list(parts), author=author)


class TestTransparentMode:
    def test_single_agent_history_passes_through_unprefixed(self):
        history = [
            ModelRequest(parts=[UserPart(content="hi", author="alice")]),
            _resp("me", TextOutput(text="hello")),
            ModelRequest(parts=[UserPart(content="more")]),
        ]
        out = project(history, "me")
        assert len(out) == 3
        # no prefixes anywhere (prompt-cache stability), authors stripped
        assert out[0].parts[0].content == "hi"
        assert out[0].parts[0].author is None
        assert out[1].author is None
        assert out[1].text() == "hello"

    def test_own_tool_exchange_stays_verbatim(self):
        history = [
            ModelRequest(parts=[UserPart(content="go")]),
            _resp("me", ToolCallOutput(tool_call_id="t1", tool_name="f", args={})),
            ModelRequest(parts=[
                ToolReturnPart(tool_call_id="t1", tool_name="f", content="r")
            ]),
        ]
        out = project(history, "me")
        assert out[1].tool_calls()[0].tool_call_id == "t1"
        assert out[2].parts[0].tool_call_id == "t1"


class TestMultiParticipant:
    def test_interleaved_foreign_tool_calls_stripped(self):
        """A foreign agent's ordinary tool calls AND their returns/retries
        vanish from my view, even interleaved with my own exchange."""
        history = [
            ModelRequest(parts=[UserPart(content="start")]),
            _resp("me", ToolCallOutput(tool_call_id="mine", tool_name="a", args={})),
            _resp("other", ToolCallOutput(tool_call_id="theirs", tool_name="b",
                                          args={"x": 1})),
            ModelRequest(parts=[
                ToolReturnPart(tool_call_id="theirs", tool_name="b", content="fb"),
                ToolReturnPart(tool_call_id="mine", tool_name="a", content="fa"),
            ]),
        ]
        out = project(history, "me")
        ids = [
            p.tool_call_id
            for m in out
            for p in m.parts
            if isinstance(p, ToolReturnPart)
        ]
        assert ids == ["mine"]  # foreign return stripped, order preserved
        # the foreign dispatch-only turn has no public surface → omitted
        assert not any(
            isinstance(m, ModelResponse) and m.author == "other" for m in out
        )
        assert not any(
            "theirs" in str(m.model_dump()) for m in out
        )  # the foreign id never leaks in any form

    def test_retry_part_from_foreign_agent_stripped(self):
        history = [
            _resp("other", ToolCallOutput(tool_call_id="ft", tool_name="x", args={})),
            _resp("me", ToolCallOutput(tool_call_id="mt", tool_name="y", args={})),
            ModelRequest(parts=[
                RetryPart(content="try again", tool_call_id="ft", tool_name="x"),
                RetryPart(content="mine failed", tool_call_id="mt", tool_name="y"),
            ]),
        ]
        out = project(history, "me")
        retries = [
            p for m in out for p in m.parts if isinstance(p, RetryPart)
        ]
        assert [r.tool_call_id for r in retries] == ["mt"]

    def test_foreign_final_result_and_handoff_args_surface(self):
        """A peer's structured answer and handoff briefing ARE its public
        surface; its ordinary tool calls are not."""
        history = [
            _resp("me", TextOutput(text="waiting")),
            _resp(
                "peer",
                TextOutput(text="done deliberating"),
                ToolCallOutput(tool_call_id="f1", tool_name=FINAL_RESULT_TOOL,
                               args={"answer": 42}),
                ToolCallOutput(tool_call_id="h1", tool_name=HANDOFF_TOOL,
                               args={"agent_name": "me", "message": "take over"}),
                ToolCallOutput(tool_call_id="x1", tool_name="internal_tool",
                               args={"secret": True}),
            ),
        ]
        out = project(history, "me")
        surfaced = [
            str(p.content)
            for m in out
            for p in m.parts
            if isinstance(p, UserPart)
        ]
        joined = "\n".join(surfaced)
        assert "<peer>" in joined
        assert "done deliberating" in joined
        assert '"answer":42' in joined
        assert "take over" in joined
        assert "secret" not in joined  # internal tools stay internal

    def test_multiple_named_humans_are_attributed(self):
        history = [
            ModelRequest(parts=[UserPart(content="hi", author="alice")]),
            ModelRequest(parts=[UserPart(content="yo", author="bob")]),
            _resp("me", TextOutput(text="hey both")),
        ]
        out = project(history, "me")
        assert out[0].parts[0].content == "<user:alice> hi"
        assert out[1].parts[0].content == "<user:bob> yo"

    def test_system_parts_survive_projection(self):
        history = [
            ModelRequest(parts=[SystemPart(content="be brief")]),
            _resp("other", TextOutput(text="chatty")),
        ]
        out = project(history, "me")
        assert any(
            isinstance(p, SystemPart) and p.content == "be brief"
            for m in out
            for p in m.parts
        )

    def test_input_never_mutated(self):
        history = [
            ModelRequest(parts=[UserPart(content="hi", author="alice")]),
            _resp("other", TextOutput(text="x")),
        ]
        snapshot = [m.model_dump() for m in history]
        project(history, "me")
        assert [m.model_dump() for m in history] == snapshot


class TestPreambles:
    def test_structured_preamble_only_with_final_result_call(self):
        with_call = [
            _resp(
                "me",
                TextOutput(text="here is my reasoning"),
                ToolCallOutput(tool_call_id="f", tool_name=FINAL_RESULT_TOOL,
                               args={"v": 1}),
            )
        ]
        assert structured_output_preamble(with_call) == "here is my reasoning"
        # prompted mode: the text IS the answer — no preamble
        prompted = [_resp("me", TextOutput(text='{"v": 1}'))]
        assert structured_output_preamble(prompted) == ""
        assert structured_output_preamble([]) == ""

    def test_step_preamble_is_final_response_only(self):
        messages = [
            _resp("me", TextOutput(text="first try (invalid)")),
            ModelRequest(parts=[RetryPart(content="retry", tool_call_id="f",
                                          tool_name=FINAL_RESULT_TOOL)]),
            _resp("me", TextOutput(text="second try")),
        ]
        assert step_preamble(messages) == "second try"
        assert step_preamble([]) == ""
