"""In-memory mesh + key-ordered dispatcher semantics."""

import asyncio

import pytest

from calfkit_tpu.mesh import InMemoryMesh, KeyOrderedDispatcher, Record


def rec(topic="t", key=None, value=b"v", **kw):
    return Record(topic=topic, key=key, value=value, **kw)


class TestDispatcher:
    async def test_per_key_serial_cross_key_parallel(self):
        order: list[str] = []
        gate = asyncio.Event()

        async def handler(record: Record):
            name = record.value.decode()
            if name == "a1":
                order.append("a1-start")
                await gate.wait()
                order.append("a1-end")
            else:
                order.append(name)
                if name == "b1":
                    gate.set()

        d = KeyOrderedDispatcher(handler, max_workers=4)
        d.start()
        # a1, a2 share a key -> serial; b1 is free to run between them
        await d.submit(rec(key=b"a", value=b"a1"))
        await d.submit(rec(key=b"a", value=b"a2"))
        await d.submit(rec(key=b"b", value=b"b1"))
        await d.stop()
        # b1 completed while a1 was parked (cross-key parallelism) …
        assert order.index("b1") < order.index("a1-end")
        # … and a2 strictly followed a1 (per-key serialization)
        assert order.index("a1-end") < order.index("a2")

    async def test_backpressure_bound_is_2n(self):
        entered = 0
        release = asyncio.Event()

        async def handler(record: Record):
            nonlocal entered
            entered += 1
            await release.wait()

        d = KeyOrderedDispatcher(handler, max_workers=2)  # bound = 4
        d.start()
        for i in range(4):
            await asyncio.wait_for(d.submit(rec(key=f"k{i}".encode())), timeout=1)
        with pytest.raises(asyncio.TimeoutError):
            await asyncio.wait_for(d.submit(rec(key=b"k9")), timeout=0.1)
        release.set()
        await d.stop()

    async def test_handler_error_does_not_kill_lane(self):
        seen: list[str] = []

        async def handler(record: Record):
            if record.value == b"boom":
                raise RuntimeError("boom")
            seen.append(record.value.decode())

        d = KeyOrderedDispatcher(handler, max_workers=2)
        d.start()
        await d.submit(rec(key=b"k", value=b"boom"))
        await d.submit(rec(key=b"k", value=b"after"))
        await d.stop()
        assert seen == ["after"]

    async def test_drain_waits_for_inflight(self):
        done: list[int] = []

        async def handler(record: Record):
            await asyncio.sleep(0.05)
            done.append(1)

        d = KeyOrderedDispatcher(handler, max_workers=2)
        d.start()
        for i in range(3):
            await d.submit(rec(key=f"{i}".encode()))
        await d.stop()
        assert len(done) == 3


class TestInMemoryMesh:
    async def test_group_delivery_and_ordering(self):
        mesh = InMemoryMesh()
        await mesh.start()
        got: list[tuple[str, str]] = []

        async def handler(record: Record):
            got.append((record.key.decode(), record.value.decode()))

        await mesh.subscribe(["t"], handler, group_id="g")
        for key in ("a", "b"):
            for i in range(5):
                await mesh.publish("t", f"{key}{i}".encode(), key=key.encode())
        await asyncio.sleep(0.1)
        await mesh.stop()
        assert len(got) == 10
        for key in ("a", "b"):
            vals = [v for k, v in got if k == key]
            assert vals == [f"{key}{i}" for i in range(5)]  # per-key order holds

    async def test_group_shares_work_across_members(self):
        mesh = InMemoryMesh(partitions=8)
        await mesh.start()
        got1, got2 = [], []

        async def h1(r):
            got1.append(r.value)

        async def h2(r):
            got2.append(r.value)

        await mesh.subscribe(["t"], h1, group_id="g")
        await mesh.subscribe(["t"], h2, group_id="g")
        for i in range(40):
            await mesh.publish("t", str(i).encode(), key=f"key{i}".encode())
        await asyncio.sleep(0.3)
        await mesh.stop()
        assert len(got1) + len(got2) == 40
        assert got1 and got2  # both members actually worked

    async def test_broadcast_tap_from_latest(self):
        mesh = InMemoryMesh()
        await mesh.start()
        await mesh.publish("t", b"before", key=b"k")
        got = []

        async def handler(r):
            got.append(r.value)

        await mesh.subscribe(["t"], handler, group_id=None, from_latest=True, ordered=False)
        await asyncio.sleep(0.05)
        await mesh.publish("t", b"after", key=b"k")
        await asyncio.sleep(0.1)
        await mesh.stop()
        assert got == [b"after"]

    async def test_two_groups_each_get_everything(self):
        mesh = InMemoryMesh()
        await mesh.start()
        a, b = [], []

        async def ha(r):
            a.append(r.value)

        async def hb(r):
            b.append(r.value)

        await mesh.subscribe(["t"], ha, group_id="g1")
        await mesh.subscribe(["t"], hb, group_id="g2")
        for i in range(5):
            await mesh.publish("t", str(i).encode(), key=b"k")
        await asyncio.sleep(0.1)
        await mesh.stop()
        assert len(a) == 5 and len(b) == 5

    async def test_oversized_message_rejected(self):
        mesh = InMemoryMesh(max_message_bytes=100)
        await mesh.start()
        with pytest.raises(ValueError, match="exceeds"):
            await mesh.publish("t", b"x" * 101)
        await mesh.stop()

    async def test_subscription_stop_rebalances(self):
        mesh = InMemoryMesh(partitions=4)
        await mesh.start()
        got1, got2 = [], []

        async def h1(r):
            got1.append(r.value)

        async def h2(r):
            got2.append(r.value)

        sub1 = await mesh.subscribe(["t"], h1, group_id="g")
        await mesh.subscribe(["t"], h2, group_id="g")
        await sub1.stop()
        for i in range(8):
            await mesh.publish("t", str(i).encode(), key=f"k{i}".encode())
        await asyncio.sleep(0.3)
        await mesh.stop()
        assert not got1 and len(got2) == 8  # survivor owns all partitions


class TestTables:
    async def test_put_get_tombstone(self):
        mesh = InMemoryMesh()
        await mesh.start()
        writer = mesh.table_writer("tbl")
        reader = mesh.table_reader("tbl")
        await reader.start()
        await writer.put("a", b"1")
        await writer.put("b", b"2")
        await writer.put("a", b"3")  # compaction: latest wins
        await reader.barrier()
        assert reader.get("a") == b"3"
        assert reader.items() == {"a": b"3", "b": b"2"}
        await writer.tombstone("a")
        await reader.barrier()
        assert reader.get("a") is None
        assert reader.items() == {"b": b"2"}
        await mesh.stop()
