"""Ragged unified prefill+decode waves (ISSUE 6).

The correctness contract under test:

- TOKEN-STREAM PARITY: ragged-on output is byte-identical to the
  bifurcated oracle (``ragged_waves=False``, same chunked config) across
  greedy / seeded-sampled / chunked-prefill-under-load / prefix-cache-hit
  / spec-on / overlap-on / stop-token-mid-block;
- KERNEL MATH: the ragged attention law (query j attends kv positions
  < min(kv_len, start + j + 1)) serves decode (q_len=1), prefill-chunk
  (q_len=chunk), and verify (q_len=k+1) rows identically to the
  per-kind reference paths, XLA and Pallas-interpret alike;
- ACCOUNTING: absorbed prefill rows count as dispatch participants
  (mean_batch_occupancy is the unified-wave fill metric), absorbed chunk
  tokens and unified dispatches surface through ``EngineStats`` /
  ``stats_snapshot()`` / the engine-stats record, and the budget knob
  actually bounds wave formation.
"""

import asyncio

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from calfkit_tpu.inference import model as M  # noqa: E402
from calfkit_tpu.inference import ragged as RG  # noqa: E402
from calfkit_tpu.inference.config import (  # noqa: E402
    RuntimeConfig,
    SpecConfig,
    preset,
)
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from calfkit_tpu.inference.sampler import SamplingParams  # noqa: E402

CFG = preset("debug")


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _rt(**over):
    kw = dict(
        max_batch_size=4, max_seq_len=128, prefill_chunk=16,
        decode_steps_per_dispatch=4, page_size=16, chunked_prefill=True,
    )
    kw.update(over)
    return RuntimeConfig(**kw)


async def _gen(engine, prompt, n, **kw):
    return [t async for t in engine.generate(prompt, max_new_tokens=n, **kw)]


async def _serve_all(params, runtime, jobs):
    engine = InferenceEngine(CFG, runtime, params=params)
    await engine.start()
    try:
        return await asyncio.gather(
            *[_gen(engine, p, n, **kw) for p, n, kw in jobs]
        ), engine
    finally:
        await engine.stop()


async def _parity(params, jobs, **rt_over):
    """The A/B harness: same jobs, ragged on vs off (the bifurcated
    oracle), streams must match byte-for-byte."""
    on, eng_on = await _serve_all(
        params, _rt(ragged_waves=True, **rt_over), jobs
    )
    off, eng_off = await _serve_all(
        params, _rt(ragged_waves=False, **rt_over), jobs
    )
    assert on == off, "ragged-on streams diverged from the bifurcated oracle"
    assert eng_on._ragged, "ragged lane never engaged"
    assert not eng_off._ragged
    assert eng_off.stats.prefill_absorbed_tokens == 0
    assert eng_off.stats.unified_dispatches == 0
    return on, eng_on


# --------------------------------------------------------------- kernel math
class TestRaggedAttentionMath:
    """The unified mask law vs the per-kind reference paths."""

    def _mixed(self, seed=0, B=3, K=2, G=4, hd=8, W=32, S=5):
        rng = np.random.default_rng(seed)
        q = jnp.asarray(rng.standard_normal((B, S, K * G, hd)), jnp.float32)
        kc = jnp.asarray(rng.standard_normal((B, K, W, hd)), jnp.float32)
        vc = jnp.asarray(rng.standard_normal((B, K, W, hd)), jnp.float32)
        return q, kc, vc

    def test_descriptor_build(self):
        rows = [
            RG.RaggedRow(RG.KIND_DECODE, start=7, q_len=1, kv_len=7),
            RG.RaggedRow(RG.KIND_PREFILL, start=16, q_len=16, kv_len=32),
            RG.RaggedRow(RG.KIND_VERIFY, start=9, q_len=4, kv_len=9),
        ]
        starts, q_lens, kv_lens = RG.build_descriptors(rows)
        assert starts == [7, 16, 9]
        assert q_lens == [1, 16, 4]
        assert kv_lens == [7, 32, 9]
        assert [r.kind_name for r in rows] == ["decode", "prefill", "verify"]
        assert rows[1].tokens() == 16

    def test_decode_row_matches_plain_attention(self):
        """q_len=1 at start=kv_len=lens reduces to the decode length mask."""
        q, kc, vc = self._mixed(S=1)
        lens = jnp.asarray([9, 30, 4], jnp.int32)
        got = M.ragged_attention_xla(q, kc, vc, lens, lens)
        # reference: attention_xla with explicit per-row positions
        want = M.attention_xla(q, kc, vc, (lens - 1)[:, None], lens)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_prefill_chunk_row_matches_causal_prefill(self):
        """q_len=S at start=offset with kv_len=offset+S IS the causal
        prefill mask over the scratch."""
        q, kc, vc = self._mixed()
        S = q.shape[1]
        starts = jnp.asarray([4, 0, 16], jnp.int32)
        got = M.ragged_attention_xla(q, kc, vc, starts, starts + S)
        pos = starts[:, None] + jnp.arange(S)[None, :]
        want = M.attention_xla(q, kc, vc, pos, starts + S)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_mixed_wave_one_call(self):
        """One call serves a batch mixing all three row kinds; each row
        equals its own per-kind reference."""
        q, kc, vc = self._mixed()
        S = q.shape[1]
        rows = [
            RG.RaggedRow(RG.KIND_DECODE, start=9, q_len=1, kv_len=9),
            RG.RaggedRow(RG.KIND_PREFILL, start=8, q_len=S, kv_len=8 + S),
            RG.RaggedRow(RG.KIND_VERIFY, start=12, q_len=S, kv_len=12),
        ]
        starts, q_lens, kv_lens = RG.build_descriptors(rows)
        got = M.ragged_attention_xla(
            q, kc, vc,
            jnp.asarray(starts, jnp.int32), jnp.asarray(kv_lens, jnp.int32),
        )
        for b, row in enumerate(rows):
            pos = row.start + jnp.arange(row.q_len)[None, :]
            want = M.attention_xla(
                q[b:b + 1, : row.q_len], kc[b:b + 1], vc[b:b + 1],
                pos, jnp.asarray([row.kv_len], jnp.int32),
            )
            np.testing.assert_allclose(
                np.asarray(got[b:b + 1, : row.q_len]), np.asarray(want),
                rtol=1e-5, atol=1e-5,
                err_msg=f"row kind {row.kind_name} diverged",
            )

    def test_pallas_ragged_matches_xla(self):
        from calfkit_tpu.inference.pallas_attention import (
            ragged_attention_pallas,
        )

        q, kc, vc = self._mixed()
        B, S, H, hd = q.shape
        K = kc.shape[1]
        G = H // K
        starts = jnp.asarray([4, 9, 0], jnp.int32)
        kv_lens = jnp.asarray([9, 9 + S, 5], jnp.int32)
        want = M.ragged_attention_xla(q, kc, vc, starts, kv_lens)
        qg = jnp.transpose(q.reshape(B, S, K, G, hd), (0, 2, 1, 3, 4))
        o, m, z = ragged_attention_pallas(
            qg, kc, vc, starts, kv_lens, interpret=True
        )
        got = jnp.transpose(
            o / jnp.maximum(z[..., None], 1e-30), (0, 2, 1, 3, 4)
        ).reshape(B, S, H, hd)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_pallas_ragged_paged_matches_xla(self):
        from calfkit_tpu.inference.pallas_attention import (
            ragged_attention_paged_pallas,
        )

        rng = np.random.default_rng(3)
        B, K, G, hd, S = 3, 2, 4, 8, 4
        H = K * G
        page, N, L, wp = 8, 13, 2, 4
        q = jnp.asarray(rng.standard_normal((B, S, H, hd)), jnp.float32)
        pool_k = jnp.asarray(
            rng.standard_normal((L, N, K, page, hd)), jnp.float32
        )
        pool_v = jnp.asarray(
            rng.standard_normal((L, N, K, page, hd)), jnp.float32
        )
        tables = jnp.asarray(rng.integers(1, N, (B, 6)), jnp.int32)
        starts = jnp.asarray([7, 0, 12], jnp.int32)
        kv_lens = jnp.asarray([7, S, 12 + S], jnp.int32)
        want = M.ragged_attention_paged_xla(
            q, pool_k[1], pool_v[1], tables, starts, kv_lens, wpages=wp
        )
        qg = jnp.transpose(q.reshape(B, S, K, G, hd), (0, 2, 1, 3, 4))
        o, m, z = ragged_attention_paged_pallas(
            qg, pool_k, pool_v, jnp.int32(1), tables, starts, kv_lens,
            wpages=wp, interpret=True,
        )
        got = jnp.transpose(
            o / jnp.maximum(z[..., None], 1e-30), (0, 2, 1, 3, 4)
        ).reshape(B, S, H, hd)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-4
        )

    def test_verify_pallas_single_call_matches_xla(self):
        """The spec-verify Pallas lane now rides ONE ragged-kernel call;
        it must match the XLA merged path."""
        from calfkit_tpu.inference.pallas_attention import (
            verify_attention_pallas,
        )

        rng = np.random.default_rng(7)
        B, K, G, hd, W, S = 2, 2, 4, 8, 32, 4
        H = K * G
        q = jnp.asarray(rng.standard_normal((B, S, H, hd)), jnp.float32)
        kc = jnp.asarray(rng.standard_normal((B, K, W, hd)), jnp.float32)
        vc = jnp.asarray(rng.standard_normal((B, K, W, hd)), jnp.float32)
        ring_k = jnp.asarray(rng.standard_normal((S, B, K, hd)), jnp.float32)
        ring_v = jnp.asarray(rng.standard_normal((S, B, K, hd)), jnp.float32)
        base = jnp.asarray([7, 12], jnp.int32)
        want = M._verify_merged_attention(q, kc, vc, ring_k, ring_v, base)
        got = verify_attention_pallas(
            q, kc, vc, ring_k, ring_v, base, interpret=True
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )


# ------------------------------------------------------------- budget math
class TestBudgetMath:
    def test_auto_budget_never_second_guesses_admission(self):
        budget = RG.token_budget(0, 32, 8, 512, 8)
        assert budget == 32 * 8 + 8 * 512
        # a full-width wave alongside a full decode batch always fits
        assert RG.fits_budget(budget, 32, 8, 8, 512)

    def test_explicit_budget_bounds_absorption_and_width(self):
        budget = RG.token_budget(96, 8, 8, 32, 8)
        assert budget == 96
        assert RG.fits_budget(budget, 4, 8, 2, 32)  # 32 + 64 <= 96
        assert not RG.fits_budget(budget, 4, 8, 3, 32)  # 32 + 96 > 96
        assert RG.wave_width_cap(budget, 4, 8, 32) == 2
        # the head always forms, even with zero slack
        assert RG.wave_width_cap(budget, 12, 8, 32) == 1

    async def test_budget_caps_wave_width_at_formation(self, params):
        """An explicit tight budget really narrows admission waves."""
        runtime = _rt(
            ragged_waves=True, max_prefill_wave=4,
            ragged_token_budget=16 + 4 * 4,  # one 16-token chunk row + decode
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            streams = await asyncio.gather(
                *[_gen(engine, [1 + i, 2], 4) for i in range(4)]
            )
        finally:
            await engine.stop()
        assert all(len(s) == 4 for s in streams)
        # width-capped waves: more waves of width 1 instead of one of 4
        assert engine.stats.prefix_hits == 0  # sanity: no reuse in play


# ----------------------------------------------------------- stream parity
class TestTokenStreamParity:
    async def test_greedy_varied_bounds(self, params):
        jobs = [
            ([1, 2, 3], 3, {}), ([4, 5], 5, {}), ([6, 7, 8, 9], 9, {}),
            ([10, 11], 8, {}), ([1, 2, 3], 12, {}),
        ]
        await _parity(params, jobs)

    async def test_greedy_paged(self, params):
        jobs = [([1, 2, 3], 7, {}), ([4, 5], 10, {}), ([6, 7], 5, {})]
        streams, eng = await _parity(params, jobs, kv_layout="paged")
        assert any(streams)

    async def test_seeded_sampled_parity(self, params):
        sp = SamplingParams(temperature=0.9, top_k=12)
        jobs = [
            ([1, 2, 3], 9, dict(sampling=sp, seed=7)),
            ([4, 5, 6], 6, dict(sampling=sp, seed=11)),
            ([7, 8], 11, dict(sampling=SamplingParams(temperature=0.6), seed=3)),
            ([9, 1], 7, {}),  # greedy row sharing the sampled batch
        ]
        streams, _ = await _parity(params, jobs)
        assert any(streams), "sampled workload produced no tokens"

    async def test_stop_token_mid_block(self, params):
        ref, _ = await _serve_all(
            params, _rt(ragged_waves=False), [([1, 2, 3], 12, {})]
        )
        stream = ref[0]
        stop = stream[5]  # lands mid-block at steps=4
        jobs = [
            ([1, 2, 3], 12, dict(stop_tokens=frozenset({stop}))),
            ([4, 5], 8, {}),
        ]
        streams, _ = await _parity(params, jobs)
        assert stop not in streams[0]
        assert streams[0] == stream[: stream.index(stop)]

    async def test_chunked_prefill_under_load(self, params):
        # more requests than slots: carries, waves, budget-capped
        # formation, and retirement-driven admission all interleave with
        # in-flight fused dispatches — multi-chunk prompts AND staggered
        # decode bounds, so retirements free slots while others still
        # decode and the follow-up waves get absorbed into live dispatches
        jobs = [
            (list(range(1 + i, 28 + i)), 4 + 3 * i, {}) for i in range(10)
        ]
        streams, eng = await _parity(params, jobs)
        assert eng.stats.prefill_absorbed_tokens > 0, (
            "under load, no prefill chunk ever rode a decode dispatch"
        )
        assert eng.stats.unified_dispatches > 0

    async def test_prefix_cache_hit_parity(self, params):
        shared = list(range(1, 33))  # two full 16-token pages
        jobs = [
            (shared + [40], 6, {}),
            (shared + [41], 6, {}),
            (shared + [42], 9, {}),
        ]
        streams, eng = await _parity(
            params, jobs, kv_layout="paged", prefix_cache=True,
        )
        assert eng.stats.prefix_hits >= 1

    async def test_spec_decode_parity(self, params):
        spec_jobs = [
            ([7, 7, 8, 9, 7, 7, 8] * 3, 10, {}),  # self-similar: drafter hits
            ([1, 2, 3], 6, {}),
        ]
        streams, eng = await _parity(
            params, spec_jobs, speculative=SpecConfig(k=3)
        )
        # spec stays lockstep: the wave rides the lane but no dispatch
        # fuses, so nothing may be double-counted as absorbed
        assert eng.stats.unified_dispatches == 0

    async def test_lockstep_config_degrades_to_bifurcated(self, params):
        """overlap_dispatch=False has no launch to fuse into: the flag
        stays set but the engine runs (and reports) bifurcated."""
        engine = InferenceEngine(
            CFG, _rt(ragged_waves=True, overlap_dispatch=False),
            params=params,
        )
        assert not engine._ragged
        await engine.start()
        try:
            assert len(await _gen(engine, [1, 2, 3], 6)) == 6
        finally:
            await engine.stop()
        assert engine.stats.unified_dispatches == 0


# -------------------------------------------------------------- accounting
class TestRaggedAccounting:
    async def test_occupancy_counts_absorbed_rows(self, params):
        """A dispatch that absorbed a chunk reports decode+chunk rows —
        occupancy with absorption must beat the same workload without."""
        jobs = [
            (list(range(1, 28)), 6 + 4 * i, {}) for i in range(6)
        ]
        on, eng_on = await _serve_all(
            params, _rt(ragged_waves=True, max_batch_size=4), jobs
        )
        off, eng_off = await _serve_all(
            params, _rt(ragged_waves=False, max_batch_size=4), jobs
        )
        assert on == off
        assert eng_on.stats.prefill_absorbed_tokens > 0
        assert eng_on.stats.mean_occupancy > eng_off.stats.mean_occupancy
        assert (
            eng_on.stats.mean_tokens_per_dispatch
            > eng_off.stats.mean_tokens_per_dispatch
        )

    async def test_snapshot_and_record_surface_ragged_keys(self, params):
        from calfkit_tpu.inference.client import JaxLocalModelClient
        from calfkit_tpu.models.records import EngineStatsRecord

        runtime = _rt(ragged_waves=True)
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            # oversubscribed + staggered bounds: later waves form while
            # earlier rows still decode, so absorption actually happens
            await asyncio.gather(
                *[
                    _gen(engine, list(range(1 + i, 28 + i)), 4 + 3 * i)
                    for i in range(8)
                ]
            )
        finally:
            await engine.stop()
        client = JaxLocalModelClient(config="debug", runtime=runtime)
        client._engine = engine
        snap = client.stats_snapshot()
        assert snap["ragged_waves"] is True
        assert snap["prefill_absorbed_tokens"] == (
            engine.stats.prefill_absorbed_tokens
        )
        assert snap["tokens_per_dispatch"] > 0
        record = EngineStatsRecord(node_id="n1", **snap)
        assert record.ragged_waves is True
        assert record.prefill_absorbed_tokens > 0
        # cold snapshot carries the same keys (zeros), effective gating
        cold = JaxLocalModelClient(config="debug", runtime=runtime)
        csnap = cold.stats_snapshot()
        assert csnap["ragged_waves"] is True
        assert csnap["prefill_absorbed_tokens"] == 0
        plain = JaxLocalModelClient(
            config="debug", runtime=RuntimeConfig(ragged_waves=True)
        )
        assert plain.stats_snapshot()["ragged_waves"] is False  # no chunk lane
        # EngineStats windowing covers the new counters
        cum, delta = engine.stats.snapshot_and_delta()
        assert "prefill_absorbed_tokens" in cum
        assert "unified_dispatches" in delta

    async def test_ck_stats_batch_occ_column(self, params):
        from calfkit_tpu.cli.obs import render_stats_table
        from calfkit_tpu.inference.client import JaxLocalModelClient
        from calfkit_tpu.models.records import EngineStatsRecord

        runtime = _rt(ragged_waves=True)
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            await _gen(engine, list(range(1, 28)), 6)
        finally:
            await engine.stop()
        client = JaxLocalModelClient(config="debug", runtime=runtime)
        client._engine = engine
        record = EngineStatsRecord(
            node_id="node-a", **client.stats_snapshot()
        )
        table = render_stats_table([record])
        assert "BATCH OCC" in table and "TOK/DISP" in table
        # the ragged marker rides the lifetime occupancy cell
        assert "*" in table

    async def test_flightrec_journals_ragged_waves(self, params):
        runtime = _rt(ragged_waves=True, flightrec_events=512)
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            await asyncio.gather(
                *[
                    _gen(engine, list(range(1 + i, 28 + i)), 4 + 3 * i)
                    for i in range(8)
                ]
            )
        finally:
            await engine.stop()
        from calfkit_tpu.observability import flightrec

        codes = [e[2] for e in engine._journal._ring if e is not None]
        assert flightrec.EV_RAGGED_WAVE in codes
