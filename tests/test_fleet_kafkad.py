"""Fleet chaos drills over the REAL Kafka wire (ISSUE 9 satellite).

The drain/stale/shed/kill drills in tests/test_chaos.py run on
``InMemoryMesh``; this file runs the same scenario shapes against the
in-repo ``kafkad`` broker through ``KafkaWireMesh`` — per-replica broker
connections (the true multi-process fleet shape), real consumer groups,
real compacted-table reads for the registry, CI's kafka-wire lane.

Stamps still ride the ``cancellation.wall_clock`` seam, so replica
staleness stays deterministic under the virtual clock even with a real
broker in the loop; only delivery latency is real.
"""

import asyncio

import pytest

from calfkit_tpu import protocol
from calfkit_tpu.client import Client
from calfkit_tpu.client.caller import RetryPolicy
from calfkit_tpu.exceptions import EngineOverloadedError
from calfkit_tpu.fleet import FailoverPolicy, FleetRouter
from calfkit_tpu.mesh.kafka_wire import (
    KafkaWireMesh,
    find_kafkad,
    spawn_kafkad,
)

from tests._chaos import (
    FleetTopology,
    ServingStubModel,
    settle,
    virtual_clock,
)

pytestmark = pytest.mark.skipif(
    find_kafkad() is None, reason="kafkad not built (make -C native)"
)

# real-broker deliveries take ms, not µs: give the bounded waits room
SETTLE = dict(ticks=1200, interval=0.01)


@pytest.fixture(scope="module")
def broker_port():
    proc = spawn_kafkad(0)
    yield proc.kafkad_port
    proc.terminate()
    proc.wait(timeout=5)


def _fleet(broker_port, models, **kw):
    """FleetTopology with one REAL broker connection per replica (each
    worker owns and stops its own)."""
    meshes = [
        KafkaWireMesh(f"127.0.0.1:{broker_port}") for _ in models
    ]
    return FleetTopology(meshes[0], models, meshes=meshes, **kw)


async def _routable(router, n):
    await router.start()
    await settle(
        lambda: len(router.registry.eligible("svc")) == n,
        message="fleet never became routable over the wire",
        **SETTLE,
    )


class TestFleetSoakOverKafka:
    async def test_drain_handoff(self, broker_port):
        """Drain one of two replicas: every subsequent call lands on the
        other, over real consumer groups and replica-addressed topics."""
        with virtual_clock():
            models = [ServingStubModel(text=f"r{i}") for i in range(2)]
            client_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
            await client_mesh.start()
            fleet = _fleet(broker_port, models)
            async with fleet:
                router = FleetRouter(
                    client_mesh, "least-loaded",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(client_mesh, router=router)
                await _routable(router, 2)
                low = fleet.index_of_lowest_key()
                first = await client.agent("svc").execute("warm", timeout=60)
                assert first.output == f"r{low}"
                fleet.workers[low].drain()
                await settle(
                    lambda: [
                        r.instance_id
                        for r in router.registry.eligible("svc")
                    ] == [fleet.instance_id(1 - low)],
                    message="drain never reached the registry",
                    **SETTLE,
                )
                for i in range(3):
                    result = await client.agent("svc").execute(
                        f"post-drain {i}", timeout=60
                    )
                    assert result.output == f"r{1 - low}"
                assert fleet.calls_delivered(low) == 1
                assert fleet.calls_delivered(1 - low) == 3
                await client.close()
            await client_mesh.stop()

    async def test_stale_exclusion_and_recovery(self, broker_port):
        """A wedged heartbeat goes stale under the virtual clock and the
        replica stops drawing traffic; one re-advert restores it."""
        with virtual_clock() as clock:
            models = [ServingStubModel(text=f"r{i}") for i in range(2)]
            client_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
            await client_mesh.start()
            fleet = _fleet(broker_port, models)
            async with fleet:
                router = FleetRouter(
                    client_mesh, "least-loaded",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(client_mesh, router=router)
                await _routable(router, 2)
                low = fleet.index_of_lowest_key()
                fleet.wedge_heartbeat(low)
                clock.advance(fleet.config.stale_after + 1)
                await settle(
                    lambda: [
                        r.instance_id
                        for r in router.registry.eligible("svc")
                    ] == [fleet.instance_id(1 - low)],
                    message="the wedged replica never went stale",
                    **SETTLE,
                )
                result = await client.agent("svc").execute(
                    "while-stale", timeout=60
                )
                assert result.output == f"r{1 - low}"
                await fleet.resume_heartbeat(low)
                await settle(
                    lambda: len(router.registry.eligible("svc")) == 2,
                    message="re-advert did not restore eligibility",
                    **SETTLE,
                )
                result = await client.agent("svc").execute("back", timeout=60)
                assert result.output == f"r{low}"
                await client.close()
            await client_mesh.stop()

    async def test_shed_retry_storm(self, broker_port):
        """Typed sheds from one replica are retried on the OTHER, with
        the shed source excluded — over the real wire, where the fault
        record's x-mesh-error-type has to round-trip the broker."""
        with virtual_clock():
            models = [ServingStubModel(text=f"r{i}") for i in range(2)]
            client_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
            await client_mesh.start()
            fleet = _fleet(broker_port, models)
            async with fleet:
                low = fleet.index_of_lowest_key()

                async def shed(messages, settings=None, params=None):
                    raise EngineOverloadedError(
                        "synthetic shed", lane="short", pending=9, limit=1
                    )

                models[low].request = shed
                router = FleetRouter(
                    client_mesh, "least-loaded",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(client_mesh, router=router)
                await _routable(router, 2)
                results = await asyncio.gather(*[
                    client.agent("svc").execute(
                        f"storm {i}", timeout=120,
                        retry=RetryPolicy(attempts=3, base_delay=0.01),
                    )
                    for i in range(4)
                ])
                assert all(r.output == f"r{1 - low}" for r in results)
                # every run touched the shedder at most once; every
                # retry landed on the survivor
                assert fleet.calls_delivered(1 - low) == 4
                await client.close()
            await client_mesh.stop()

    async def test_kill_mid_run_fails_over(self, broker_port):
        """The new ISSUE 9 drill on the real wire: hard-kill the placed
        replica mid-run; the supervised call re-dispatches to the
        survivor under the remaining deadline and completes."""

        class BlockedStubModel(ServingStubModel):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.release = asyncio.Event()

            async def request(self, messages, settings=None, params=None):
                await self.release.wait()
                return await super().request(messages, settings, params)

        with virtual_clock() as clock:
            models = [BlockedStubModel(text=f"r{i}") for i in range(2)]
            client_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
            await client_mesh.start()
            fleet = _fleet(broker_port, models)
            async with fleet:
                low = fleet.index_of_lowest_key()
                models[1 - low].release.set()  # only the victim blocks
                router = FleetRouter(
                    client_mesh, "least-loaded",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(
                    client_mesh, router=router,
                    failover=FailoverPolicy(
                        probe_interval=0.05, max_failovers=2
                    ),
                )
                await _routable(router, 2)
                call = asyncio.create_task(
                    client.agent("svc").execute("kill me", timeout=120)
                )
                await settle(
                    lambda: fleet.calls_delivered(low) == 1,
                    message="the call never reached the victim",
                    **SETTLE,
                )
                fleet.kill(low)
                clock.advance(fleet.config.stale_after + 1)
                result = await call
                assert result.output == f"r{1 - low}"
                assert fleet.calls_delivered(1 - low) == 1
                assert fleet.agents[1 - low]._failover_requests == 1
                models[low].release.set()  # clean teardown
                await client.close()
            await client_mesh.stop()


class TestOrphanReapOverKafka:
    async def test_orphan_reap_soak(self, broker_port):
        """Orphan-reap soak over the REAL wire (ISSUE 10): a LEASED
        client fire-and-forgets runs into a REAL engine through kafkad —
        beats on the real compacted ``mesh.caller_liveness`` table, the
        worker's liveness feed folding them back — then dies hard (beat
        task killed, no tombstone).  One virtual TTL later the engine
        has reaped every orphan: drained, zero leaked slots/pages,
        ORPHANS counted."""
        import time as _time

        jax = pytest.importorskip("jax")
        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from calfkit_tpu.inference import model as M
        from calfkit_tpu.inference.client import JaxLocalModelClient
        from calfkit_tpu.inference.config import RuntimeConfig, preset
        from calfkit_tpu.inference.engine import InferenceEngine
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        from tests._chaos import assert_engine_drained

        cfg = preset("debug")
        params = M.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
        runtime = RuntimeConfig(
            max_batch_size=4, max_seq_len=128, prefill_chunk=16,
            decode_steps_per_dispatch=1, page_size=16, kv_layout="paged",
        )
        engine = InferenceEngine(cfg, runtime, params=params)
        total_free = engine._page_alloc.free_pages

        def pace(point):
            if point == "dispatch":
                _time.sleep(0.01)

        engine._chaos = pace
        model = JaxLocalModelClient(
            config=cfg, runtime=runtime, engine=engine, max_new_tokens=100
        )
        with virtual_clock() as clock:
            worker_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
            client_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
            await client_mesh.start()
            agent = Agent("leased", model=model)
            async with Worker(
                [agent], mesh=worker_mesh, owns_transport=True
            ):
                ttl = 1.0
                client = Client.connect(client_mesh, lease_ttl=ttl)
                for i in range(3):
                    await client.agent("leased").send(f"orphan soak {i}")

                def submitted() -> int:
                    wave = (
                        len(engine._inflight["wave"])
                        if engine._inflight is not None else 0
                    )
                    return (
                        len(engine._active) + len(engine._pending)
                        + len(engine._carry) + len(engine._admitting)
                        + wave
                    )

                # ALL three sends must reach the engine before the
                # caller dies: a slow broker delivery arriving after the
                # reap would otherwise be counted (or not) by race
                await settle(
                    lambda: submitted() == 3,
                    message="the sends never all reached the engine",
                    ticks=3000, interval=0.01,  # first-use XLA compiles
                )
                # hard caller death over the real wire
                assert client._lease_task is not None
                client._lease_task.cancel()
                clock.advance(ttl + 0.5)
                await settle(
                    lambda: (
                        not engine._active
                        and not engine._pending
                        and not engine._carry
                        and engine._pend is None
                        and engine._inflight is None
                        and not engine._admitting
                        and len(engine._free) == runtime.max_batch_size
                        and engine._page_alloc.free_pages == total_free
                    ),
                    message="the engine never reaped the orphans",
                    **SETTLE,
                )
                assert_engine_drained(engine, total_free)
                assert engine.stats.orphaned_requests == 3
                await client.close()
            await engine.stop()
            await client_mesh.stop()
