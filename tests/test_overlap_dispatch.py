"""Overlapped execution: double-buffered decode dispatch (ISSUE 3).

The correctness contract under test:

- TOKEN-STREAM PARITY: overlap-on output is byte-identical to the
  lockstep reference across greedy / seeded-sampled / stop-token-mid-
  block / retirement-bound-inside-block / spec-decode-on /
  prefix-cache-hit / chunked-admission-under-load;
- ONE-DISPATCH-LATE RETIREMENT: a retiring row's slot and pages free
  only after the in-flight dispatch lands — exactly once, never early
  (shared prefix-cache pages keep their refcount until the landing);
- CANCELLATION MID-FLIGHT: an abandoned consumer gets nothing delivered
  after the cancel is reaped, and its resources free exactly once;
- the device-side retirement mask (``sampler.retire_mask_slots``)
  classifies stop tokens and generation bounds identically to the host
  authority (``_record_token``).
"""

import asyncio

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from calfkit_tpu.exceptions import (  # noqa: E402
    EngineOverloadedError,
    InferenceError,
)
from calfkit_tpu.inference import model as M  # noqa: E402
from calfkit_tpu.inference.config import (  # noqa: E402
    RuntimeConfig,
    SpecConfig,
    preset,
)
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from calfkit_tpu.inference.sampler import (  # noqa: E402
    SamplingParams,
    retire_mask_slots,
)

CFG = preset("debug")


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _rt(**over):
    kw = dict(
        max_batch_size=4, max_seq_len=128, prefill_chunk=16,
        decode_steps_per_dispatch=4, page_size=16,
    )
    kw.update(over)
    return RuntimeConfig(**kw)


async def _gen(engine, prompt, n, **kw):
    return [t async for t in engine.generate(prompt, max_new_tokens=n, **kw)]


async def _serve_all(params, runtime, jobs):
    """Run ``jobs`` = [(prompt, max_new, kwargs), ...] concurrently on a
    fresh engine; returns the per-job token streams."""
    engine = InferenceEngine(CFG, runtime, params=params)
    await engine.start()
    try:
        return await asyncio.gather(
            *[_gen(engine, p, n, **kw) for p, n, kw in jobs]
        ), engine
    finally:
        await engine.stop()


async def _parity(params, jobs, **rt_over):
    """The A/B harness: same jobs, overlap on vs off, streams must match."""
    on, eng_on = await _serve_all(
        params, _rt(overlap_dispatch=True, **rt_over), jobs
    )
    off, eng_off = await _serve_all(
        params, _rt(overlap_dispatch=False, **rt_over), jobs
    )
    assert on == off, "overlap-on streams diverged from the lockstep oracle"
    assert eng_off.stats.overlap_wasted_tokens == 0
    # one-dispatch-late tax stays within the documented bound
    assert eng_on.stats.overlap_wasted_tokens <= (
        len(jobs) * eng_on.runtime.decode_steps_per_dispatch
    )
    return on, eng_on


class TestRetireMaskMath:
    """sampler.retire_mask_slots against the host authority's semantics."""

    def _run(self, toks, stops, bound, active=None, emitted=None):
        toks = jnp.asarray(toks, jnp.int32)
        B = toks.shape[0]
        table = np.full((B, 4), -1, np.int32)
        for i, row in enumerate(stops):
            table[i, : len(row)] = row
        n_valid, done = retire_mask_slots(
            toks, jnp.asarray(table), jnp.asarray(bound, jnp.int32),
            jnp.ones((B,), bool) if active is None else jnp.asarray(active),
            emitted=None if emitted is None else jnp.asarray(emitted, jnp.int32),
        )
        return np.asarray(n_valid).tolist(), np.asarray(done).tolist()

    def test_no_stop_bound_beyond_block(self):
        n, d = self._run([[5, 6, 7, 8]], [[]], [10])
        assert (n, d) == ([4], [False])

    def test_bound_inside_block(self):
        n, d = self._run([[5, 6, 7, 8]], [[]], [2])
        assert (n, d) == ([2], [True])

    def test_bound_exactly_at_block_end_retires(self):
        n, d = self._run([[5, 6, 7, 8]], [[]], [4])
        assert (n, d) == ([4], [True])

    def test_stop_token_mid_block_excluded(self):
        # stop at position 2: deliver the two tokens before it
        n, d = self._run([[5, 6, 9, 8]], [[9]], [10])
        assert (n, d) == ([2], [True])

    def test_stop_at_first_position(self):
        n, d = self._run([[9, 6, 7, 8]], [[9]], [10])
        assert (n, d) == ([0], [True])

    def test_bound_beats_later_stop(self):
        # host loop retires at the bound before ever seeing the stop
        n, d = self._run([[5, 6, 7, 9]], [[9]], [2])
        assert (n, d) == ([2], [True])

    def test_inactive_rows_report_nothing(self):
        n, d = self._run(
            [[9, 6, 7, 8], [5, 6, 7, 8]], [[9], []], [10, 1],
            active=[False, False],
        )
        assert (n, d) == ([0, 0], [False, False])

    def test_emitted_limits_spec_padding(self):
        # padding zeros past emitted must not match a stop token 0: the
        # row neither truncates nor (crucially) retires on padding
        n, d = self._run([[5, 6, 0, 0]], [[0]], [10], emitted=[2])
        assert (n, d) == ([2], [False])
        # ... but a real 0 inside the emitted window still stops
        n, d = self._run([[5, 0, 6, 0]], [[0]], [10], emitted=[3])
        assert (n, d) == ([1], [True])

    def test_multiple_stop_tokens(self):
        n, d = self._run([[5, 6, 7, 8]], [[8, 6]], [10])
        assert (n, d) == ([1], [True])


class TestTokenStreamParity:
    async def test_greedy_dense_varied_bounds(self, params):
        # bounds 3/5/9 all land mid-block at steps=4 (retirement inside
        # a dispatch), 8 rides the exact block boundary
        jobs = [
            ([1, 2, 3], 3, {}), ([4, 5], 5, {}), ([6, 7, 8, 9], 9, {}),
            ([10, 11], 8, {}), ([1, 2, 3], 12, {}),
        ]
        await _parity(params, jobs)

    async def test_greedy_paged(self, params):
        jobs = [([1, 2, 3], 7, {}), ([4, 5], 10, {}), ([6, 7], 5, {})]
        await _parity(params, jobs, kv_layout="paged")

    async def test_seeded_sampled_parity(self, params):
        sp = SamplingParams(temperature=0.9, top_k=12)
        jobs = [
            ([1, 2, 3], 9, dict(sampling=sp, seed=7)),
            ([4, 5, 6], 6, dict(sampling=sp, seed=11)),
            ([7, 8], 11, dict(sampling=SamplingParams(temperature=0.6), seed=3)),
            ([9, 1], 7, {}),  # greedy row sharing the sampled batch
        ]
        streams, _ = await _parity(params, jobs)
        assert any(streams), "sampled workload produced no tokens"

    async def test_stop_token_mid_block(self, params):
        # find what greedy emits, then stop on a token observed mid-stream
        ref, _ = await _serve_all(
            params, _rt(overlap_dispatch=False), [([1, 2, 3], 12, {})]
        )
        stream = ref[0]
        stop = stream[5]  # lands mid-block at steps=4
        jobs = [
            ([1, 2, 3], 12, dict(stop_tokens=frozenset({stop}))),
            ([4, 5], 8, {}),
        ]
        streams, _ = await _parity(params, jobs)
        assert stop not in streams[0]  # the stop token is never delivered
        assert streams[0] == stream[: stream.index(stop)]

    async def test_spec_decode_parity(self, params):
        spec_jobs = [
            ([7, 7, 8, 9, 7, 7, 8], 10, {}),  # self-similar: drafter hits
            ([1, 2, 3], 6, {}),
        ]
        await _parity(params, spec_jobs, speculative=SpecConfig(k=3))

    async def test_chunked_admission_under_load(self, params):
        # more requests than slots: carries, waves, and retirement-driven
        # admission all interleave with in-flight dispatches
        jobs = [([1 + i, 2 + i], 4 + (i % 5), {}) for i in range(10)]
        await _parity(params, jobs, chunked_prefill=True)

    async def test_prefix_cache_hit_parity(self, params):
        shared = list(range(1, 33))  # two full 16-token pages
        jobs = [
            (shared + [40], 6, {}),
            (shared + [41], 6, {}),
            (shared + [42], 9, {}),
        ]
        await _parity(
            params, jobs,
            kv_layout="paged", chunked_prefill=True, prefix_cache=True,
        )


class TestLateRetirement:
    async def test_pages_freed_exactly_once_and_late(self, params):
        """Every page returns to the pool exactly once, and never while
        the dispatch that could still write it is in flight."""
        runtime = _rt(overlap_dispatch=True, kv_layout="paged")
        engine = InferenceEngine(CFG, runtime, params=params)
        freed_slots: list[int] = []
        real_free = engine._page_alloc.free

        def counting_free(slot):
            assert engine._pend is None or slot not in engine._pend["slot_set"], (
                "page reservation freed while its slot was still covered "
                "by an in-flight dispatch"
            )
            if engine._page_alloc.held_slots.get(slot):
                freed_slots.append(slot)
            real_free(slot)

        engine._page_alloc.free = counting_free
        total_free = engine._page_alloc.free_pages
        await engine.start()
        try:
            streams = await asyncio.gather(
                *[_gen(engine, [1 + i, 2], 5 + i) for i in range(4)]
            )
        finally:
            await engine.stop()
        assert all(len(s) == 5 + i for i, s in enumerate(streams))
        # four requests, four slots, no reuse: exactly one real free each
        assert len(freed_slots) == 4, f"frees: {freed_slots}"
        assert engine._page_alloc.free_pages == total_free
        assert engine.stats.overlap_wasted_tokens > 0  # late retirement ran

    async def test_prefix_refcounts_survive_late_retirement(self, params):
        """Shared prefix pages: refcounts never go negative, release is
        deferred past the in-flight dispatch, and the engine lands with
        every reference returned."""
        runtime = _rt(
            overlap_dispatch=True, kv_layout="paged",
            chunked_prefill=True, prefix_cache=True,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        prefix = engine._prefix
        real_release = prefix.release

        def checked_release(pages):
            # a double release (e.g. early free at retire AND the deferred
            # free at landing) would drive a refcount below zero here —
            # a newer dispatch for OTHER rows may legally be in flight
            for page in pages:
                assert prefix._refs[page] >= 1, (
                    f"page {page} released below zero refs"
                )
            # no in-flight dispatch may still COVER a row whose shared
            # pages these are: a retiring participant's release defers to
            # its landing, so any live in-flight row holding these pages
            # means an early release
            if engine._pend is not None:
                for slot, req in engine._pend["participants"]:
                    if engine._active.get(slot) is req:
                        assert not set(req.shared_pages) & set(pages), (
                            "shared pages released under a live in-flight "
                            "reader"
                        )
            real_release(pages)

        prefix.release = checked_release
        shared = list(range(1, 33))
        await engine.start()
        try:
            first = await _gen(engine, shared + [40], 6)
            assert len(first) == 6
            # second round hits the cache; short bounds retire mid-block
            streams = await asyncio.gather(
                *[_gen(engine, shared + [41 + i], 3 + i) for i in range(3)]
            )
        finally:
            await engine.stop()
        assert all(len(s) == 3 + i for i, s in enumerate(streams))
        assert engine.stats.prefix_hits >= 1
        # all references returned: every cached page sits at zero refs
        assert all(r == 0 for r in prefix._refs.values())

    async def test_deferred_release_happens_inside_flight_window(self, params):
        """The defer path actually engages: at least one retirement lands
        while a dispatch is in flight and routes through pend.deferred."""
        runtime = _rt(overlap_dispatch=True, kv_layout="paged")
        engine = InferenceEngine(CFG, runtime, params=params)
        deferred_seen = []
        real_land = engine._land_decode

        def spying_land(pend):
            deferred_seen.append(len(pend["deferred"]))
            return real_land(pend)

        engine._land_decode = spying_land
        await engine.start()
        try:
            await asyncio.gather(
                *[_gen(engine, [1 + i], 5) for i in range(3)]
            )
        finally:
            await engine.stop()
        assert any(n > 0 for n in deferred_seen), (
            "no retirement was deferred through an in-flight dispatch"
        )


class TestCancellationMidFlight:
    async def test_cancel_frees_once_and_delivers_nothing_after(self, params):
        runtime = _rt(overlap_dispatch=True, kv_layout="paged")
        engine = InferenceEngine(CFG, runtime, params=params)
        total_free = engine._page_alloc.free_pages
        await engine.start()
        try:
            agen = engine.generate([1, 2, 3], max_new_tokens=64)
            got = []
            async for token in agen:
                got.append(token)
                if len(got) >= 2:
                    break
            assert len(engine._active) == 1
            request = next(iter(engine._active.values()))
            await agen.aclose()  # cancel with a dispatch in flight
            # let the scheduler reap + drain the in-flight dispatch
            for _ in range(50):
                await asyncio.sleep(0.02)
                if engine._pend is None and not engine._active:
                    break
            assert not engine._active
            assert engine._pend is None
            assert engine._page_alloc.free_pages == total_free
            assert len(engine._free) == runtime.max_batch_size
            # a block already in flight at close time may legally deliver
            # (the cancel wasn't reaped yet); once the reap + drain have
            # run, NOTHING more may reach the closed queue
            while not request.out.empty():
                request.out.get_nowait()
            # bounded soak for a late thread-side delivery: the engine is
            # already drained above (pend None, active empty), so any
            # illegal delivery would have to land within a few ticks of
            # the reap — a long real-clock nap here was pure tax (ISSUE
            # 11 drive-by: residual real-sleep waits on tier-1)
            for _ in range(25):
                await asyncio.sleep(0.002)
            assert request.out.empty(), (
                "delivery to a cancelled consumer after the reap"
            )
            # the engine still serves
            follow_up = await _gen(engine, [4, 5], 4)
            assert len(follow_up) == 4
        finally:
            await engine.stop()


class TestStopTableCap:
    async def test_oversized_stop_set_faults_with_overlap(self, params):
        runtime = _rt(overlap_dispatch=True, max_stop_tokens=2)
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            with pytest.raises(InferenceError, match="max_stop_tokens"):
                await _gen(engine, [1, 2], 4, stop_tokens=frozenset({5, 6, 7}))
            # within the cap still serves
            assert len(await _gen(engine, [1, 2], 4,
                                  stop_tokens=frozenset({500, 501}))) == 4
        finally:
            await engine.stop()

    async def test_lockstep_keeps_arbitrary_stop_sets(self, params):
        runtime = _rt(overlap_dispatch=False, max_stop_tokens=2)
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            stream = await _gen(
                engine, [1, 2], 4, stop_tokens=frozenset(range(300, 310))
            )
            assert len(stream) <= 4
        finally:
            await engine.stop()


class TestOverlapTelemetry:
    async def test_gap_histogram_and_waste_surface(self, params):
        from calfkit_tpu.inference.client import JaxLocalModelClient

        runtime = _rt(overlap_dispatch=True)
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            await asyncio.gather(*[_gen(engine, [1 + i], 6) for i in range(3)])
        finally:
            await engine.stop()
        # launches with a dispatch in flight observe a zero-gap sample
        assert engine.latency["dispatch_gap_ms"].count > 0
        # the client snapshot surfaces the new keys (live branch)
        client = JaxLocalModelClient(config="debug", runtime=runtime)
        client._engine = engine
        snap = client.stats_snapshot()
        assert snap["overlap_dispatch"] is True
        assert snap["overlap_wasted_tokens"] == (
            engine.stats.overlap_wasted_tokens
        )
        assert "dispatch_gap_p99" in snap["latency_ms"]
        # cold snapshot carries the same keys (zeros)
        cold = JaxLocalModelClient(config="debug", runtime=runtime)
        assert cold.stats_snapshot()["overlap_wasted_tokens"] == 0
        # EngineStats windowing covers the new counter
        cum, delta = engine.stats.snapshot_and_delta()
        assert "overlap_wasted_tokens" in cum
        assert "overlap_wasted_tokens" in delta


class TestQueuedCancellation:
    """ISSUE 5 satellite: cancellation of STILL-QUEUED entries, and the
    reap's ordering against a concurrent admission wave — the parity
    matrix above covers active-slot cancels only."""

    @pytest.mark.parametrize("overlap", [True, False])
    async def test_cancel_queued_request_vs_concurrent_admission(
        self, params, overlap
    ):
        from tests._chaos import assert_engine_drained, settle

        runtime = _rt(
            max_batch_size=2, kv_layout="paged", overlap_dispatch=overlap
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        total_free = engine._page_alloc.free_pages
        await engine.start()
        try:
            # fill both slots with long-ish streams, then queue two more
            active = [
                asyncio.create_task(_gen(engine, [1 + i], 24))
                for i in range(2)
            ]
            await settle(lambda: len(engine._active) == 2)
            queued = [
                asyncio.create_task(_gen(engine, [10 + i], 24))
                for i in range(2)
            ]
            await settle(
                lambda: len(engine._pending) + len(engine._carry) == 2
            )
            # abandon both queued consumers while the actives keep the
            # engine mid-wave; the reap must drop the queued entries
            # without disturbing admission of fresh work
            for task in queued:
                task.cancel()
            fresh = asyncio.create_task(_gen(engine, [20], 8))
            for task in queued:
                with pytest.raises(asyncio.CancelledError):
                    await task
            # actives complete in full, the fresh submit admits and
            # completes — cancelled queue entries never held resources
            assert [len(s) for s in await asyncio.gather(*active)] == [24, 24]
            assert len(await fresh) == 8
            await settle(
                lambda: not engine._active and engine._pend is None
            )
            assert_engine_drained(engine, total_free)
            assert engine.stats.cancelled_requests == 2
        finally:
            await engine.stop()

    @pytest.mark.parametrize("overlap", [True, False])
    async def test_cancel_mid_chunked_admission_under_load(
        self, params, overlap
    ):
        """Cancel ONE member of a chunked-admission wave while its
        prefill chunks are still landing: the corpse is shed at
        activation, the surviving member streams in full, and every
        page the corpse reserved returns to the pool."""
        from tests._chaos import assert_engine_drained, settle

        runtime = _rt(
            max_batch_size=2, kv_layout="paged", chunked_prefill=True,
            prefill_chunk=16, overlap_dispatch=overlap,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        total_free = engine._page_alloc.free_pages
        await engine.start()
        try:
            # same bucket (64): both join one admission wave of 4 chunks
            doomed = asyncio.create_task(
                _gen(engine, list(range(1, 60)), 16)
            )
            survivor = asyncio.create_task(
                _gen(engine, list(range(100, 158)), 16)
            )
            await settle(
                lambda: engine._inflight is not None
                and len(engine._inflight["wave"]) == 2,
                message="chunked admission wave never formed",
            )
            doomed.cancel()
            with pytest.raises(asyncio.CancelledError):
                await doomed
            assert len(await survivor) == 16
            await settle(
                lambda: not engine._active and engine._pend is None
                and engine._inflight is None
            )
            assert_engine_drained(engine, total_free)
            # the engine still admits chunked waves afterwards
            assert len(await _gen(engine, list(range(50)), 8)) == 8
        finally:
            await engine.stop()


class TestShedExpireParity:
    """The shed and expire paths must behave identically under the
    overlapped and lockstep schedulers: same typed errors, same
    counters, byte-identical streams for the admitted survivors."""

    async def _oversubscribe(self, params, overlap):
        runtime = _rt(
            max_batch_size=2, max_pending=2, overlap_dispatch=overlap
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            results = await asyncio.gather(
                *[_gen(engine, [1 + i], 8) for i in range(8)],
                return_exceptions=True,
            )
        finally:
            await engine.stop()
        served = {
            i: r for i, r in enumerate(results) if isinstance(r, list)
        }
        shed = {
            i for i, r in enumerate(results)
            if isinstance(r, EngineOverloadedError)
        }
        return served, shed, engine.stats

    async def test_shed_parity_overlap_vs_lockstep(self, params):
        served_on, shed_on, stats_on = await self._oversubscribe(
            params, True
        )
        served_off, shed_off, stats_off = await self._oversubscribe(
            params, False
        )
        assert shed_on == shed_off, "shed sets diverged across schedulers"
        assert shed_on, "oversubscription never shed"
        assert served_on == served_off, (
            "admitted survivors' streams diverged from the lockstep oracle"
        )
        assert stats_on.shed_requests == stats_off.shed_requests == len(
            shed_on
        )

    @pytest.mark.parametrize("overlap", [True, False])
    async def test_expire_parity_active_and_queued(self, params, overlap):
        from calfkit_tpu.exceptions import DeadlineExceededError
        from tests._chaos import assert_engine_drained, settle, virtual_clock

        with virtual_clock() as clock:
            runtime = _rt(
                max_batch_size=1, kv_layout="paged",
                overlap_dispatch=overlap,
            )
            engine = InferenceEngine(CFG, runtime, params=params)
            total_free = engine._page_alloc.free_pages
            await engine.start()
            try:
                active = asyncio.create_task(
                    _gen(engine, [1, 2], 64, deadline=clock.now + 5)
                )
                await settle(lambda: engine._active)
                queued = asyncio.create_task(
                    _gen(engine, [3, 4], 64, deadline=clock.now + 5)
                )
                await settle(
                    lambda: len(engine._pending) + len(engine._carry) == 1
                )
                clock.advance(10)
                with pytest.raises(DeadlineExceededError):
                    await active
                with pytest.raises(DeadlineExceededError):
                    await queued
                await settle(
                    lambda: not engine._active and engine._pend is None
                )
                assert_engine_drained(engine, total_free)
                assert engine.stats.expired_requests == 2
                assert engine.stats.cancelled_requests == 0
                # un-deadlined work still serves under the same scheduler
                assert len(await _gen(engine, [9], 8)) == 8
            finally:
                await engine.stop()
