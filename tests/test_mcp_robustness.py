"""MCP transport vs a HOSTILE stdio server (r5 adversarial depth on the
JSON-RPC seam, mirroring the wire-codec fuzz philosophy: one bad frame
must never kill the session, hang pending requests, or spin forever).

Regression pins: non-object JSON frames used to crash the read loop
(every request then hung to timeout); the 64 KiB asyncio default stream
limit used to break framing on any large tool result; a repeating
pagination cursor used to loop list_tools forever.
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

import pytest

from calfkit_tpu.mcp import MCPServerSpec
from calfkit_tpu.mcp.transport import MCPError, MCPSession

HOSTILE = str(Path(__file__).parent / "_mcp_hostile_server.py")


def _session(mode: str, timeout: float = 10.0) -> MCPSession:
    return MCPSession(
        MCPServerSpec(name=f"hostile-{mode}",
                      command=[sys.executable, HOSTILE, mode]),
        request_timeout=timeout,
    )


class TestHostileFrames:
    async def test_garbage_frames_do_not_kill_the_read_loop(self):
        session = _session("garbage-frames")
        await session.start()
        try:
            # repeated requests keep working through interleaved garbage
            for _ in range(3):
                tools = await session.list_tools()
                assert [t["name"] for t in tools] == ["echo"]
            out = await session.call_tool("echo", {})
            assert out == "survived"
        finally:
            await session.stop()

    async def test_malformed_error_and_result_are_typed(self):
        session = _session("malformed-error")
        await session.start()
        try:
            with pytest.raises(MCPError, match="just a string"):
                await session.call_tool("x", {})
            with pytest.raises(MCPError, match="non-object result"):
                await session.call_tool("x", {})
        finally:
            await session.stop()

    async def test_large_tool_result_survives(self):
        """A ~1 MiB response is LEGAL — the old 64 KiB asyncio stream
        limit broke framing and killed the session."""
        session = _session("huge-line")
        await session.start()
        try:
            out = await session.call_tool("big", {})
            assert len(out) == 1 << 20
        finally:
            await session.stop()

    async def test_cursor_loop_terminates_typed(self):
        session = _session("cursor-loop")
        await session.start()
        try:
            with pytest.raises(MCPError, match="did not terminate"):
                await asyncio.wait_for(session.list_tools(), timeout=30)
        finally:
            await session.stop()

    async def test_dead_session_fails_fast_and_typed(self):
        """Once the server is gone, requests must raise MCPError
        immediately — not park a future for the full 30 s timeout."""
        session = _session("garbage-frames")
        await session.start()
        try:
            session._proc.kill()
            await session._proc.wait()
            # let the reader observe EOF and mark the session dead
            deadline = asyncio.get_running_loop().time() + 5
            while session._dead is None:
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("reader never marked session dead")
                await asyncio.sleep(0.05)
            started = asyncio.get_running_loop().time()
            with pytest.raises(MCPError, match="session dead"):
                await session.call_tool("echo", {})
            assert asyncio.get_running_loop().time() - started < 1.0
        finally:
            await session.stop()

    async def test_weird_content_shapes_do_not_crash(self):
        session = _session("weird-content")
        await session.start()
        try:
            assert await session.call_tool("x", {}) == ""  # non-list content
            assert await session.call_tool("x", {}) == "ok"  # mixed entries
        finally:
            await session.stop()
