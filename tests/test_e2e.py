"""End-to-end slice: Client + Worker + Agent + ToolNode on the in-memory mesh
(BASELINE config 1 analog) plus fault/timeout surfaces at the client."""

import asyncio

import pytest
from pydantic import BaseModel

from calfkit_tpu.client import Client
from calfkit_tpu.engine import EchoModelClient, FunctionModelClient, TestModelClient
from calfkit_tpu.exceptions import ClientTimeoutError, NodeFaultError
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models import FaultTypes, ModelResponse, TextOutput, ToolCallOutput
from calfkit_tpu.models.node_result import InvocationResult
from calfkit_tpu.nodes import Agent, agent_tool
from calfkit_tpu.worker import Worker


@agent_tool
def get_weather(city: str) -> dict:
    """Get current weather.

    Args:
        city: City name.
    """
    return {"city": city, "conditions": "sunny", "temp_c": 21.5}


class TestQuickstart:
    async def test_single_tool_single_turn(self):
        mesh = InMemoryMesh()
        agent = Agent(
            "weather",
            model=TestModelClient(custom_output_text="It is sunny in SF, 21.5C"),
            instructions="Weather assistant.",
            tools=[get_weather],
        )
        async with Worker([agent, get_weather], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("weather").execute(
                "Weather in SF?", timeout=10
            )
            assert result.output == "It is sunny in SF, 21.5C"
            # conversation state came back: user msg, tool call, tool return,
            # final answer
            roles = [m.role for m in result.state.message_history]
            assert roles == ["request", "response", "request", "response"]
            await client.close()

    async def test_streaming_steps_then_result(self):
        mesh = InMemoryMesh()
        agent = Agent(
            "streamer",
            model=TestModelClient(custom_output_text="done"),
            tools=[get_weather],
        )
        async with Worker([agent, get_weather], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("streamer").start("go", timeout=10)
            events = [e async for e in handle.stream()]
            kinds = [e.step.kind for e in events if hasattr(e, "step")]
            assert "tool_call" in kinds and "tool_result" in kinds
            final = events[-1]
            assert isinstance(final, InvocationResult) and final.output == "done"
            await client.close()

    async def test_structured_output(self):
        class Weather(BaseModel):
            city: str
            temp_c: float

        def scripted(messages, params):
            assert params.output_tool is not None
            return ModelResponse(parts=[
                ToolCallOutput(tool_call_id="1", tool_name="final_result",
                               args={"city": "SF", "temp_c": 18.5})
            ])

        mesh = InMemoryMesh()
        agent = Agent(
            "typed", model=FunctionModelClient(scripted), output_type=Weather
        )
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("typed", output_type=Weather).execute(
                "weather?", timeout=10
            )
            assert result.output.city == "SF" and result.output.temp_c == 18.5
            await client.close()

    async def test_multi_turn_with_history(self):
        mesh = InMemoryMesh()
        agent = Agent("chat", model=EchoModelClient())
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            gateway = client.agent("chat")
            r1 = await gateway.execute("first", timeout=10)
            assert r1.output == "echo: first"
            r2 = await gateway.execute(
                "second", message_history=r1.state.message_history, timeout=10
            )
            assert r2.output == "echo: second"
            assert len(r2.state.message_history) == 4  # both turns retained
            await client.close()

    async def test_parallel_tool_calls_fanout(self):
        @agent_tool
        def city_temp(city: str) -> float:
            """Temperature lookup.

            Args:
                city: City name.
            """
            return {"sf": 18.0, "nyc": 25.0}.get(city.lower(), 20.0)

        turn = {"n": 0}

        def scripted(messages, params):
            turn["n"] += 1
            if turn["n"] == 1:
                return ModelResponse(parts=[
                    ToolCallOutput(tool_call_id="a", tool_name="city_temp",
                                   args={"city": "SF"}),
                    ToolCallOutput(tool_call_id="b", tool_name="city_temp",
                                   args={"city": "NYC"}),
                ])
            return ModelResponse(parts=[TextOutput(text="SF 18, NYC 25")])

        mesh = InMemoryMesh()
        agent = Agent(
            "multi", model=FunctionModelClient(scripted), tools=[city_temp]
        )
        async with Worker([agent, city_temp], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("multi").execute("temps?", timeout=10)
            assert result.output == "SF 18, NYC 25"
            await client.close()


class TestClientSurfaces:
    async def test_fault_raises_typed_error(self):
        @agent_tool
        def bomb() -> str:
            raise RuntimeError("tool exploded")

        def scripted(messages, params):
            return ModelResponse(parts=[
                ToolCallOutput(tool_call_id="x", tool_name="bomb", args={})
            ])

        mesh = InMemoryMesh()
        agent = Agent("bomber", model=FunctionModelClient(scripted), tools=[bomb])
        async with Worker([agent, bomb], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            with pytest.raises(NodeFaultError) as exc_info:
                await client.agent("bomber").execute("go", timeout=10)
            report = exc_info.value.report
            assert report.error_type == FaultTypes.CALLEE_FAULT
            assert "tool exploded" in report.root_cause().message
            await client.close()

    async def test_timeout(self):
        mesh = InMemoryMesh()
        await mesh.start()  # no worker: nobody will reply
        client = Client.connect(mesh)
        with pytest.raises(ClientTimeoutError):
            await client.agent("nobody").execute("hello", timeout=0.3)
        await client.close()
        await mesh.stop()

    async def test_send_fire_and_forget(self):
        mesh = InMemoryMesh()
        agent = Agent("fire", model=EchoModelClient())
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            cid = await client.agent("fire").send("hello")
            assert isinstance(cid, str) and len(cid) == 32
            await asyncio.sleep(0.2)  # run completes without a listener
            await client.close()

    async def test_firehose_sees_all_runs(self):
        mesh = InMemoryMesh()
        agent = Agent(
            "noisy", model=TestModelClient(custom_output_text="ok"),
            tools=[get_weather],
        )
        async with Worker([agent, get_weather], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            stream = client.events()
            await client.agent("noisy").execute("a", timeout=10)
            await client.agent("noisy").execute("b", timeout=10)
            await asyncio.sleep(0.2)
            stream.close()
            events = [e async for e in stream]  # close() ends iteration
            cids = {e.correlation_id for e in events}
            assert len(cids) == 2  # events from both runs hit the firehose
            await client.close()


class TestWorkerLifecycle:
    async def test_single_use(self):
        mesh = InMemoryMesh()
        worker = Worker([Agent("once", model=EchoModelClient())], mesh=mesh)
        await worker.start()
        await worker.stop()
        from calfkit_tpu.exceptions import LifecycleConfigError

        with pytest.raises(LifecycleConfigError):
            await worker.start()
        await mesh.stop()

    async def test_resource_brackets_and_rollback(self):
        mesh = InMemoryMesh()
        log = []
        worker = Worker([Agent("r", model=EchoModelClient())], mesh=mesh)

        @worker.resource
        async def db():
            log.append("db-up")
            yield {"conn": 1}
            log.append("db-down")

        @worker.on_startup
        def hello():
            log.append("startup")

        @worker.after_shutdown
        def bye():
            log.append("after-shutdown")

        await worker.start()
        assert worker.resources["db"] == {"conn": 1}
        await worker.stop()
        assert log == ["startup", "db-up", "after-shutdown", "db-down"]
        await mesh.stop()

    async def test_boot_failure_rolls_back(self):
        mesh = InMemoryMesh()
        log = []
        worker = Worker([Agent("rb", model=EchoModelClient())], mesh=mesh)

        @worker.resource
        async def res():
            log.append("up")
            yield
            log.append("down")

        @worker.after_startup
        def explode():
            raise RuntimeError("boot failed")

        with pytest.raises(RuntimeError):
            await worker.start()
        assert log == ["up", "down"]  # resource torn down by rollback
        await mesh.stop()


class _PacedModel:
    """Streams chunks with real delays — deterministic liveness probe."""

    model_name = "paced"

    async def request(self, messages, settings=None, params=None):
        from calfkit_tpu.engine.model_client import ResponseDone

        async for event in self.request_stream(messages, settings, params):
            if isinstance(event, ResponseDone):
                return event.response

    async def request_stream(self, messages, settings=None, params=None):
        from calfkit_tpu.engine.model_client import ResponseDone, TextDelta

        text = ""
        for i in range(5):
            await asyncio.sleep(0.08)
            chunk = f"chunk-{i} of the answer... "
            text += chunk
            yield TextDelta(chunk)
        yield ResponseDone(ModelResponse(parts=[TextOutput(text=text)]))


class TestTokenStreaming:
    async def test_tokens_arrive_live_before_the_result(self):
        """stream_tokens=True: TokenSteps must reach the client WHILE the
        model generates — wall-clock ahead of the terminal result
        (BASELINE config 3)."""
        import time as _time

        mesh = InMemoryMesh()
        agent = Agent("paced", model=_PacedModel(), stream_tokens=True)
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            t0 = _time.perf_counter()
            handle = await client.agent("paced").start("go", timeout=30)
            arrivals, final_ms = [], None
            async for event in handle.stream():
                if hasattr(event, "step") and event.step.kind == "token":
                    arrivals.append((_time.perf_counter() - t0) * 1000)
                elif isinstance(event, InvocationResult):
                    final_ms = (_time.perf_counter() - t0) * 1000
            assert len(arrivals) >= 2
            # the first token record landed ~4 chunks before the result
            assert final_ms - arrivals[0] >= 150
            await client.close()

    async def test_local_jax_model_streams_token_records(self):
        """The real local-inference path publishes token records before the
        terminal steps (cadence is content-dependent)."""
        import jax
        jax.config.update("jax_platforms", "cpu")
        from calfkit_tpu.inference import JaxLocalModelClient
        from calfkit_tpu.inference.config import RuntimeConfig, preset

        model = JaxLocalModelClient(
            config=preset("debug"),
            runtime=RuntimeConfig(max_batch_size=2, max_seq_len=256,
                                  prefill_chunk=32, decode_steps_per_dispatch=4),
            max_new_tokens=48,
        )
        mesh = InMemoryMesh()
        agent = Agent("streamer_local", model=model, stream_tokens=True)
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("streamer_local").start(
                "tell me things", timeout=120
            )
            kinds = []
            async for event in handle.stream():
                if hasattr(event, "step"):
                    kinds.append(event.step.kind)
            assert "token" in kinds
            # token records precede the hop's terminal steps
            assert kinds.index("token") < kinds.index("agent_message")
            await client.close()
        await model.stop()


class TestMeshUrls:
    async def test_client_connect_accepts_url_and_env(self, monkeypatch):
        from calfkit_tpu.mesh.tcp import find_meshd, spawn_meshd

        if find_meshd() is None:
            pytest.skip("meshd not built")
        proc = spawn_meshd(19884)
        try:
            from calfkit_tpu.mesh.urls import mesh_from_url

            agent = Agent("urly", model=TestModelClient(custom_output_text="via-url"))
            worker_mesh = mesh_from_url("tcp://127.0.0.1:19884")
            await worker_mesh.start()
            async with Worker([agent], mesh=worker_mesh):
                client = Client.connect("tcp://127.0.0.1:19884")
                result = await client.agent("urly").execute("go", timeout=20)
                assert result.output == "via-url"
                await client.close()
                # env-var resolution
                monkeypatch.setenv("CALFKIT_MESH_URL", "tcp://127.0.0.1:19884")
                env_client = Client.connect()
                result2 = await env_client.agent("urly").execute("again", timeout=20)
                assert result2.output == "via-url"
                await env_client.close()
            await worker_mesh.stop()
        finally:
            proc.terminate()
            proc.wait(timeout=5)

    def test_connect_without_mesh_or_env_is_loud(self, monkeypatch):
        monkeypatch.delenv("CALFKIT_MESH_URL", raising=False)
        with pytest.raises(ValueError, match="CALFKIT_MESH_URL"):
            Client.connect()

    def test_bad_scheme_is_loud(self):
        with pytest.raises(ValueError, match="unsupported mesh url"):
            Client.connect("carrier-pigeon://coop")

    def test_memory_url_rejected_for_clients(self):
        """memory:// from a URL is an isolated world — a client there can
        only time out; reject loudly instead."""
        with pytest.raises(ValueError, match="isolated"):
            Client.connect("memory://")

    async def test_url_client_close_stops_owned_mesh(self):
        from calfkit_tpu.mesh.tcp import find_meshd, spawn_meshd

        if find_meshd() is None:
            pytest.skip("meshd not built")
        proc = spawn_meshd(19886)
        try:
            client = Client.connect("tcp://127.0.0.1:19886")
            await client._ensure_started()
            assert client.mesh._started
            await client.close()
            assert not client.mesh._started  # owned transport stopped
        finally:
            proc.terminate()
            proc.wait(timeout=5)

    async def test_worker_accepts_url_and_owns_transport(self):
        from calfkit_tpu.mesh.tcp import find_meshd, spawn_meshd

        if find_meshd() is None:
            pytest.skip("meshd not built")
        proc = spawn_meshd(19887)
        try:
            agent = Agent("wurl", model=TestModelClient(custom_output_text="wu"))
            worker = Worker([agent], mesh="tcp://127.0.0.1:19887")
            assert worker.owns_transport  # built from url => owned
            await worker.start()
            client = Client.connect("tcp://127.0.0.1:19887")
            result = await client.agent("wurl").execute("x", timeout=20)
            assert result.output == "wu"
            await client.close()
            await worker.stop()
            assert not worker.mesh._started  # owned transport stopped
        finally:
            proc.terminate()
            proc.wait(timeout=5)


class TestConfig5Scale:
    """BASELINE config 5 exercised END-TO-END (VERDICT r3 item 7): 128
    concurrent streams through the FULL product path — client → mesh →
    agent → engine — on the virtual mesh, with one long-context request
    interleaved through the sp ring-prefill lane.  The engine-level
    128-stream test (test_inference.py) proves the scheduler; this proves
    the whole serving stack at that width."""

    async def test_128_streams_full_agent_path_with_long_interleaved(self):
        import jax

        jax.config.update("jax_platforms", "cpu")
        from calfkit_tpu.inference import JaxLocalModelClient
        from calfkit_tpu.inference.config import RuntimeConfig, preset

        B = 16  # slot pool; 128 streams oversubscribe it 8x
        model = JaxLocalModelClient(
            config=preset("debug"),
            runtime=RuntimeConfig(
                max_batch_size=B, max_seq_len=128, prefill_chunk=16,
                decode_steps_per_dispatch=4, kv_layout="paged", page_size=16,
                num_kv_pages=4 * B + 1, long_context=True, long_new_cap=8,
                # the client's max_new_tokens=12 exceeds the lane cap: this
                # serving path explicitly negotiates clamping (the engine
                # faults by default rather than silently shrinking budgets)
                long_clamp_new_tokens=True,
            ),
            max_new_tokens=12,
        )
        agent = Agent("scale_agent", model=model)
        mesh = InMemoryMesh()
        # worker concurrency must exceed the slot pool or the dispatcher
        # (default 8 lanes) caps concurrent runs below the batch and the
        # engine can never fill — config 5's width is end-to-end, not just
        # an engine property
        async with Worker(
            [agent], mesh=mesh, owns_transport=True, max_workers=64
        ):
            client = Client.connect(mesh)

            async def short(i: int) -> str:
                result = await client.agent("scale_agent").execute(
                    f"req {i} " + "x" * (i % 23), timeout=600
                )
                return result.output

            async def long_one() -> str:
                # ByteTokenizer: ~1 token/byte — 200+ chars exceeds
                # max_seq_len=128 and routes through the sp long lane
                result = await client.agent("scale_agent").execute(
                    "long " + "y" * 220, timeout=600
                )
                return result.output

            results = await asyncio.gather(
                long_one(), *[short(i) for i in range(128)]
            )
            assert len(results) == 129
            assert all(isinstance(r, str) for r in results)
            await client.close()

        engine = model._engine
        # the long request went through the sequence-parallel lane
        assert engine.stats.long_requests == 1
        # steady state dominated: 128 streams over 16 slots keep the batch
        # full once ramped (config-5's continuous-batching claim)
        assert engine.stats.mean_occupancy > 0.5, engine.stats.mean_occupancy
        hist = engine.stats.occupancy_hist
        assert hist[3] >= sum(hist) / 2, hist
        # no leaks anywhere after the storm
        assert not engine._active and not engine._pending and not engine._carry
        assert engine._page_alloc.free_pages == 4 * B
        assert sorted(engine._free) == list(range(B))
        await model.stop()
