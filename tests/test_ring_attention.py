"""Ring attention / sequence-parallel prefill: numerical parity on the
virtual 8-device CPU mesh (the repo's multi-chip test contract)."""

from __future__ import annotations

import pytest

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from calfkit_tpu.inference import model as M
from calfkit_tpu.inference.config import preset
from calfkit_tpu.inference.ring_attention import (
    prefill_sequence_parallel,
    ring_attention,
    single_device_causal_attention,
)


def _sp_mesh(n: int) -> Mesh:
    devices = np.array(jax.devices()[:n])
    return Mesh(devices, ("sp",))


class TestRingAttention:
    @pytest.mark.parametrize("sp", [2, 4, 8])
    def test_matches_single_device(self, sp):
        mesh = _sp_mesh(sp)
        B, S, H, K, hd = 2, 64, 4, 2, 16
        ks = jax.random.split(jax.random.key(0), 3)
        q = jax.random.normal(ks[0], (B, S, H, hd), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, K, hd), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, K, hd), jnp.float32)
        want = single_device_causal_attention(q, k, v)
        got = ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
        )

    def test_mha_no_grouping(self):
        mesh = _sp_mesh(4)
        B, S, H, hd = 1, 32, 4, 8
        ks = jax.random.split(jax.random.key(1), 3)
        q = jax.random.normal(ks[0], (B, S, H, hd), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, H, hd), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, H, hd), jnp.float32)
        want = single_device_causal_attention(q, k, v)
        got = ring_attention(q, k, v, mesh)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
        )

    def test_rejects_indivisible_sequence(self):
        mesh = _sp_mesh(8)
        q = jnp.zeros((1, 30, 4, 8))
        with pytest.raises(ValueError, match="divide"):
            ring_attention(q, q[:, :, :2], q[:, :, :2], mesh)


class TestSequenceParallelPrefill:
    def test_matches_single_device_forward(self):
        """The whole sp-sharded prefill — embeddings, rope, ring attention,
        MLP, logits, KV — must match the plain forward."""
        config = preset(
            "debug", n_layers=2, n_heads=4, n_kv_heads=2, d_model=64,
            d_ff=128, max_seq_len=64,
        )
        params = M.init_params(config, jax.random.key(2), dtype=jnp.float32)
        B, S = 2, 64
        tokens = jax.random.randint(jax.random.key(3), (B, S), 0, config.vocab_size)

        # reference: plain single-device forward over a scratch cache
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        cache = M.make_empty_cache(config, B, S, dtype=jnp.float32)
        logits, (k_ref, v_ref) = M.forward(
            params, config, tokens, positions, cache,
            jnp.full((B,), S, jnp.int32),
        )
        want_last = logits[:, -1]

        mesh = _sp_mesh(8)
        got_last, (k_sp, v_sp) = prefill_sequence_parallel(
            params, config, tokens, mesh
        )
        np.testing.assert_allclose(
            np.asarray(got_last), np.asarray(want_last), atol=2e-4, rtol=2e-4
        )
        np.testing.assert_allclose(
            np.asarray(k_sp), np.asarray(k_ref), atol=1e-5, rtol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(v_sp), np.asarray(v_ref), atol=1e-5, rtol=1e-5
        )

    def test_kv_stays_sequence_sharded(self):
        """The produced cache must remain sharded over sp (context-parallel
        decode / resharding is the caller's choice, not forced here)."""
        config = preset(
            "debug", n_layers=1, n_heads=4, n_kv_heads=2, d_model=64,
            d_ff=128, max_seq_len=64,
        )
        params = M.init_params(config, jax.random.key(4), dtype=jnp.float32)
        mesh = _sp_mesh(8)
        tokens = jnp.ones((1, 64), jnp.int32)
        _, (k_sp, _) = prefill_sequence_parallel(params, config, tokens, mesh)
        sharding = k_sp.sharding
        # the S axis (index 3 of [L, B, K, S, hd]) is the sharded one
        assert "sp" in str(sharding.spec)


class TestRaggedLengths:
    def test_ragged_seq_lens_match_dense(self):
        """Padded rows must ignore pad positions (review r2: validity mask)."""
        mesh = _sp_mesh(4)
        B, S, H, K, hd = 3, 64, 4, 2, 16
        ks = jax.random.split(jax.random.key(9), 3)
        q = jax.random.normal(ks[0], (B, S, H, hd), jnp.float32)
        k = jax.random.normal(ks[1], (B, S, K, hd), jnp.float32)
        v = jax.random.normal(ks[2], (B, S, K, hd), jnp.float32)
        lens = jnp.array([64, 37, 5])
        want = single_device_causal_attention(q, k, v, seq_lens=lens)
        got = ring_attention(q, k, v, mesh, seq_lens=lens)
        for b in range(B):
            n = int(lens[b])
            np.testing.assert_allclose(
                np.asarray(got)[b, :n], np.asarray(want)[b, :n],
                atol=1e-5, rtol=1e-5,
            )

    def test_prefill_ragged_last_logits(self):
        """last_logits reads each row's LAST VALID position, and valid KV
        matches the dense forward."""
        config = preset(
            "debug", n_layers=2, n_heads=4, n_kv_heads=2, d_model=64,
            d_ff=128, max_seq_len=64,
        )
        params = M.init_params(config, jax.random.key(5), dtype=jnp.float32)
        B, S = 2, 64
        tokens = jax.random.randint(jax.random.key(6), (B, S), 0,
                                    config.vocab_size)
        lens = jnp.array([64, 40])

        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        cache = M.make_empty_cache(config, B, S, dtype=jnp.float32)
        logits, (k_ref, _) = M.forward(
            params, config, tokens, positions, cache, lens
        )
        want = jnp.take_along_axis(
            logits, jnp.clip(lens - 1, 0, S - 1)[:, None, None], axis=1
        )[:, 0]

        mesh = _sp_mesh(8)
        got, (k_sp, _) = prefill_sequence_parallel(
            params, config, tokens, mesh, seq_lens=lens
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
        )
        for b in range(B):
            n = int(lens[b])
            np.testing.assert_allclose(
                np.asarray(k_sp)[:, b, :, :n], np.asarray(k_ref)[:, b, :, :n],
                atol=1e-5, rtol=1e-5,
            )


class TestContextParallelDecode:
    def test_cp_attention_matches_dense_source(self):
        """Per-shard partials + global merge == dense attention stats."""
        from calfkit_tpu.inference.model import logsumexp_merge
        from calfkit_tpu.inference.ring_attention import (
            context_parallel_attention,
        )

        mesh = _sp_mesh(4)
        B, S, H, K, hd = 2, 64, 4, 2, 16
        ks = jax.random.split(jax.random.key(12), 3)
        q = jax.random.normal(ks[0], (B, 1, H, hd), jnp.float32)
        kp = jax.random.normal(ks[1], (B, K, S, hd), jnp.float32)
        vp = jax.random.normal(ks[2], (B, K, S, hd), jnp.float32)
        lens = jnp.array([64, 30])
        o, m, z = context_parallel_attention(q, kp, vp, lens, mesh)
        got = (o / z).reshape(B, 1, H, hd)
        want = M.attention_xla(
            q, kp, vp, lens[:, None] - 1, lens  # query at last position
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=1e-5, rtol=1e-5
        )

    def test_ring_prefill_then_cp_decode_matches_dense(self):
        """The full long-context path: ring prefill (sharded KV) -> greedy
        decode THROUGH the sharded prefix == dense prefill + dense decode."""
        from calfkit_tpu.inference.ring_attention import (
            decode_with_sharded_prefix,
        )

        config = preset(
            "debug", n_layers=2, n_heads=4, n_kv_heads=2, d_model=64,
            d_ff=128, max_seq_len=96,
        )
        params = M.init_params(config, jax.random.key(13), dtype=jnp.float32)
        B, S, STEPS = 2, 64, 6
        tokens = jax.random.randint(jax.random.key(14), (B, S), 0,
                                    config.vocab_size)

        # dense reference: prefill + incremental single-device decode
        positions = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
        cache = M.make_empty_cache(config, B, 96, dtype=jnp.float32)
        logits, cache = M.forward(
            params, config, tokens, positions, cache,
            jnp.full((B,), S, jnp.int32),
        )
        token = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want = []
        for t in range(STEPS):
            lens = jnp.full((B,), S + t + 1, jnp.int32)
            lg, cache = M.forward(
                params, config, token[:, None],
                jnp.full((B, 1), S + t, jnp.int32), cache, lens,
            )
            token = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
            want.append(token)
        want = jnp.stack(want, axis=1)

        # sharded path: ring prefill -> cp decode, no resharding anywhere
        mesh = _sp_mesh(8)
        last_logits, prefix = prefill_sequence_parallel(
            params, config, tokens, mesh
        )
        first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
        got = decode_with_sharded_prefix(
            params, config, first, prefix, jnp.full((B,), S, jnp.int32),
            mesh, STEPS,
        )
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
