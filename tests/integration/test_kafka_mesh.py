"""Kafka-lane integration tests (reference analog: tests/integration/ run
with ``-m kafka`` against a real broker — Makefile `test-kafka`).

Deselected by default (pyproject addopts).  Run with:

    CALFKIT_TEST_KAFKA_BOOTSTRAP=localhost:9092 python -m pytest -m kafka tests/integration

Requires aiokafka + a Kafka-compatible broker (e.g. Redpanda).  These mirror
the offline-lane suites over the real transport: round trips, durable
fan-out, key ordering, control plane, step streaming.
"""

import asyncio
import os

import pytest

pytestmark = pytest.mark.kafka

BOOTSTRAP = os.environ.get("CALFKIT_TEST_KAFKA_BOOTSTRAP", "localhost:9092")


def _kafka_available() -> bool:
    try:
        import aiokafka  # noqa: F401

        return True
    except ImportError:
        return False


if not _kafka_available():  # pragma: no cover - depends on environment
    pytest.skip("aiokafka not installed", allow_module_level=True)


@pytest.fixture
async def mesh():
    from calfkit_tpu.mesh.kafka import KafkaMesh

    mesh = KafkaMesh(BOOTSTRAP)
    await mesh.start()
    yield mesh
    await mesh.stop()


class TestKafkaRoundTrips:
    async def test_pubsub_key_ordering(self, mesh):
        got = []

        async def handler(record):
            got.append(record.value)

        await mesh.subscribe(["ck.test.ord"], handler, group_id="g-ord")
        for i in range(10):
            await mesh.publish("ck.test.ord", f"v{i}".encode(), key=b"k")
        for _ in range(100):
            if len(got) == 10:
                break
            await asyncio.sleep(0.1)
        assert got == [f"v{i}".encode() for i in range(10)]

    async def test_table_barrier_read_your_writes(self, mesh):
        writer = mesh.table_writer("ck.test.tbl")
        reader = mesh.table_reader("ck.test.tbl")
        await reader.start()
        await writer.put("a", b"1")
        await reader.barrier()
        assert reader.get("a") == b"1"
        await writer.tombstone("a")
        await reader.barrier()
        assert reader.get("a") is None

    async def test_quickstart_over_kafka(self, mesh):
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool(name="kafka_probe")
        def kafka_probe(x: int) -> int:
            """Probe.

            Args:
                x: Value.
            """
            return x + 1

        agent = Agent(
            "kafka_agent",
            model=TestModelClient(custom_output_text="over kafka"),
            tools=[kafka_probe],
        )
        worker = Worker([agent, kafka_probe], mesh=mesh)
        await worker.start()
        try:
            client = Client.connect(mesh)
            result = await client.agent("kafka_agent").execute("go", timeout=30)
            assert result.output == "over kafka"
            await client.close()
        finally:
            await worker.stop()

    async def test_durable_fanout_over_kafka(self, mesh):
        """The fan-out fold/close machine over real compacted topics."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.models import ModelResponse, TextOutput, ToolCallOutput
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool(name="kfan")
        def kfan(i: int) -> int:
            """Fan.

            Args:
                i: Index.
            """
            return i * 10

        turn = {"n": 0}

        def scripted(messages, params):
            turn["n"] += 1
            if turn["n"] == 1:
                return ModelResponse(parts=[
                    ToolCallOutput(tool_call_id=f"t{i}", tool_name="kfan",
                                   args={"i": i})
                    for i in range(3)
                ])
            return ModelResponse(parts=[TextOutput(text="folded")])

        agent = Agent("kfanner", model=FunctionModelClient(scripted), tools=[kfan])
        worker = Worker([agent, kfan], mesh=mesh)
        await worker.start()
        try:
            client = Client.connect(mesh)
            result = await client.agent("kfanner").execute("fan", timeout=60)
            assert result.output == "folded"
            await client.close()
        finally:
            await worker.stop()
