"""KafkaWireMesh against a REAL external Kafka-compatible broker.

The in-image lane runs the same client against the in-repo kafkad
(tests/test_kafka_wire.py, tests/test_transport_contract.py).  This file
points the identical wire client at a real cluster when one is provided:

    CALF_TEST_KAFKA_BOOTSTRAP=localhost:9092 \
        python -m pytest -m kafka tests/integration/test_kafka_wire_live.py

This needs NO extra
Python dependency — the client is the in-repo wire implementation.
"""

import asyncio
import os
import uuid

import pytest

pytestmark = pytest.mark.kafka

BOOTSTRAP = os.environ.get("CALF_TEST_KAFKA_BOOTSTRAP")

if not BOOTSTRAP:  # pragma: no cover - depends on environment
    pytest.skip(
        "set CALF_TEST_KAFKA_BOOTSTRAP to run against a real broker",
        allow_module_level=True,
    )


# NOTE: no async fixtures — the repo has no pytest-asyncio plugin (the
# conftest hook drives async TEST FUNCTIONS only), so each test builds
# and tears down its mesh inline.
import contextlib


@contextlib.asynccontextmanager
async def _mesh():
    from calfkit_tpu.mesh.kafka_wire import KafkaWireMesh

    mesh = KafkaWireMesh(BOOTSTRAP)
    await mesh.start()
    try:
        yield mesh
    finally:
        await mesh.stop()


async def test_publish_subscribe_round_trip():
    async with _mesh() as mesh:
        await _run_round_trip(mesh)


async def _run_round_trip(mesh):
    topic = f"wire-live-{uuid.uuid4().hex[:8]}"
    await mesh.ensure_topics([topic])
    got = []

    async def handler(rec):
        got.append((rec.key, rec.value, rec.headers))

    sub = await mesh.subscribe([topic], handler, group_id="wire-live-g")
    await mesh.publish(topic, b"v1", key=b"k1", headers={"h": "x"})
    for _ in range(200):
        if got:
            break
        await asyncio.sleep(0.05)
    assert got == [(b"k1", b"v1", {"h": "x"})]
    await sub.stop()


async def test_agent_round_trip_over_real_broker():
    from calfkit_tpu.client import Client
    from calfkit_tpu.engine import TestModelClient
    from calfkit_tpu.mesh.kafka_wire import KafkaWireMesh
    from calfkit_tpu.nodes import Agent
    from calfkit_tpu.worker import Worker

    async with _mesh() as mesh:
        client_mesh = KafkaWireMesh(BOOTSTRAP)
        await client_mesh.start()
        agent = Agent(
            f"wire_live_{uuid.uuid4().hex[:6]}",
            model=TestModelClient(custom_output_text="over-real-kafka"),
        )
        async with Worker([agent], mesh=mesh, owns_transport=False):
            client = Client.connect(client_mesh)
            result = await client.agent(agent.name).execute("go", timeout=60)
            assert result.output == "over-real-kafka"
            await client.close()
        await client_mesh.stop()
