"""Unit coverage for the observability subsystem (ISSUE 2): the metrics
registry + Prometheus exposition, trace contexts/spans/ring buffer, the
dispatcher lane telemetry, the stats-snapshot key-set contract, the
EngineStats windowing story, the asyncio metrics endpoint, and the CLI
renderers."""

from __future__ import annotations

import asyncio
import json

import pytest

from calfkit_tpu import protocol
from calfkit_tpu.models.records import EngineStatsRecord, SpanRecord
from calfkit_tpu.observability.metrics import (
    MetricsRegistry,
    metrics_text,
)
from calfkit_tpu.observability.trace import (
    TRACER,
    TraceContext,
    Tracer,
    collect_spans,
    current_context,
    release_spans,
)


class TestMetricsRegistry:
    def test_counter_gauge_roundtrip(self):
        reg = MetricsRegistry()
        c = reg.counter("jobs_total", "jobs")
        c.inc()
        c.inc(4)
        c.inc(-3)  # counters are monotonic: dropped, not raised
        g = reg.gauge("depth", "queue depth")
        g.set(7)
        assert c.value == 5
        assert g.value == 7
        text = reg.render()
        assert "# TYPE jobs_total counter" in text
        assert "jobs_total 5" in text
        assert "# TYPE depth gauge" in text
        assert "depth 7" in text

    def test_get_or_create_shares_instruments(self):
        reg = MetricsRegistry()
        a = reg.counter("shared")
        b = reg.counter("shared")
        assert a is b
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("shared")

    def test_histogram_buckets_and_exposition(self):
        reg = MetricsRegistry()
        h = reg.histogram("lat_ms", "latency", buckets=(1.0, 10.0, 100.0))
        for v in (0.5, 5.0, 5.0, 50.0, 5000.0):
            h.observe(v)
        assert h.count == 5
        assert h.sum == pytest.approx(5060.5)
        text = h.render()
        # cumulative per-bucket counts + the +Inf catch-all
        assert 'lat_ms_bucket{le="1"} 1' in text
        assert 'lat_ms_bucket{le="10"} 3' in text
        assert 'lat_ms_bucket{le="100"} 4' in text
        assert 'lat_ms_bucket{le="+Inf"} 5' in text
        assert "lat_ms_count 5" in text

    def test_histogram_percentile_is_bucket_upper_bound(self):
        reg = MetricsRegistry()
        h = reg.histogram("p_ms", buckets=(1.0, 10.0, 100.0))
        assert h.percentile(0.99) == 0.0  # empty: defined, not a crash
        for _ in range(99):
            h.observe(5.0)
        h.observe(5000.0)
        assert h.percentile(0.5) == 10.0
        assert h.percentile(0.99) == 10.0
        assert h.percentile(1.0) == 100.0  # +Inf clamps to the last bound

    def test_snapshot_and_delta_windows(self):
        reg = MetricsRegistry()
        h = reg.histogram("w_ms", buckets=(10.0,))
        c = reg.counter("w_total")
        h.observe(5.0)
        c.inc(3)
        cum, delta = h.snapshot_and_delta()
        assert cum["count"] == 1 and delta["count"] == 1
        h.observe(50.0)
        cum, delta = h.snapshot_and_delta()
        assert cum["count"] == 2
        assert delta["count"] == 1
        assert delta["counts"] == [0, 1]
        assert c.snapshot_and_delta() == (3, 3)
        assert c.snapshot_and_delta() == (3, 0)

    def test_disabled_registry_records_nothing(self):
        reg = MetricsRegistry()
        h = reg.histogram("off_ms")
        reg.set_enabled(False)
        h.observe(5.0)
        reg.counter("off_total").inc()
        assert h.count == 0
        assert reg.counter("off_total").value == 0
        reg.set_enabled(True)
        h.observe(5.0)
        assert h.count == 1

    def test_bad_values_never_raise(self):
        reg = MetricsRegistry()
        reg.histogram("bad_ms").observe("nan-soup")  # type: ignore[arg-type]
        reg.counter("bad_total").inc("many")  # type: ignore[arg-type]
        reg.gauge("bad_gauge").set(object())  # type: ignore[arg-type]

    def test_metrics_text_process_registry(self):
        # the process registry carries the engine/dispatch instruments:
        # rendering must always be valid exposition, never raise
        text = metrics_text()
        assert isinstance(text, str)


class TestTraceContext:
    def test_header_round_trip(self):
        ctx = TraceContext(trace_id="t1", span_id="s1")
        headers = ctx.headers()
        assert headers == {
            protocol.HDR_TRACE: "t1", protocol.HDR_SPAN: "s1"
        }
        back = TraceContext.from_headers(headers)
        assert back is not None
        assert (back.trace_id, back.span_id) == ("t1", "s1")

    def test_missing_headers_tolerated(self):
        assert TraceContext.from_headers({}) is None
        assert TraceContext.from_headers({protocol.HDR_SPAN: "s"}) is None
        # trace without span: legal (root-emitted records)
        ctx = TraceContext.from_headers({protocol.HDR_TRACE: "t"})
        assert ctx is not None and ctx.span_id == ""

    def test_bytes_header_values_via_header_map(self):
        raw = {
            protocol.HDR_TRACE: b"t-bytes",
            protocol.HDR_SPAN: b"s-bytes",
            "x-junk": b"\xff\xfe",  # undecodable: dropped by header_map
        }
        ctx = TraceContext.from_headers(protocol.header_map(raw))
        assert ctx is not None
        assert ctx.trace_id == "t-bytes" and ctx.span_id == "s-bytes"


class TestTracer:
    def test_span_parenting_and_ring(self):
        tracer = Tracer()
        root = tracer.start_span("root", trace_id="trace-A", kind="client")
        child = tracer.start_span("child", parent=root.context, kind="agent")
        grandchild = tracer.start_span("gc", parent=child.context)
        grandchild.end()
        child.end(status="error", error_type="boom")
        root.end()
        spans = tracer.finished("trace-A")
        assert [s.name for s in spans] == ["gc", "child", "root"]
        by_name = {s.name: s for s in spans}
        assert by_name["child"].parent_span_id == root.context.span_id
        assert by_name["gc"].parent_span_id == child.context.span_id
        assert by_name["root"].parent_span_id is None
        assert by_name["child"].status == "error"
        assert by_name["child"].attrs["error_type"] == "boom"
        assert by_name["root"].duration_ms >= 0.0

    def test_end_is_idempotent(self):
        tracer = Tracer()
        span = tracer.start_span("once", trace_id="t")
        assert span.end() is not None
        assert span.end() is None
        assert len(tracer.finished("t")) == 1

    def test_ring_is_bounded(self):
        tracer = Tracer(ring_size=4)
        for i in range(10):
            tracer.start_span(f"s{i}", trace_id="t").end()
        names = [s.name for s in tracer.finished("t")]
        assert names == ["s6", "s7", "s8", "s9"]

    def test_sink_collects_spans_finished_under_it(self):
        tracer = Tracer()
        tracer.start_span("before", trace_id="t").end()
        sink, token = collect_spans()
        tracer.start_span("inside", trace_id="t").end()
        release_spans(token)
        tracer.start_span("after", trace_id="t").end()
        assert [s.name for s in sink] == ["inside"]

    def test_disabled_tracer_exports_nothing(self):
        tracer = Tracer()
        tracer.set_enabled(False)
        tracer.start_span("ghost", trace_id="t").end()
        assert tracer.finished("t") == []
        tracer.set_enabled(True)

    def test_span_record_wire_round_trip(self):
        record = SpanRecord(
            trace_id="t", span_id="s", name="n", kind="engine",
            start_s=123.0, duration_ms=4.5, attrs={"x": 1},
        )
        back = SpanRecord.from_wire(record.to_wire())
        assert back == record
        assert record.span_key() == "t/s"


class TestDispatcherTelemetry:
    async def test_traced_record_gets_dispatch_span(self):
        from calfkit_tpu.mesh.dispatch import KeyOrderedDispatcher
        from calfkit_tpu.mesh.transport import Record

        handled = []

        async def handler(record):
            handled.append(record.topic)

        dispatcher = KeyOrderedDispatcher(handler, max_workers=2)
        dispatcher.start()
        ctx = TraceContext(trace_id="disp-trace", span_id="parent-span")
        await dispatcher.submit(
            Record(topic="traced", value=b"x", key=b"k", headers=ctx.headers())
        )
        await dispatcher.submit(
            Record(topic="untraced", value=b"x", key=b"k")
        )
        await dispatcher.stop()
        assert sorted(handled) == ["traced", "untraced"]
        spans = TRACER.finished("disp-trace")
        assert len(spans) == 1
        assert spans[0].name == "mesh.dispatch"
        assert spans[0].parent_span_id == "parent-span"
        assert spans[0].attrs["topic"] == "traced"
        assert "queue_wait_ms" in spans[0].attrs


class TestStatsSnapshotContract:
    def test_cold_snapshot_has_live_key_set(self):
        """Satellite 1: a cold engine's snapshot carries the same keys as
        the live branch (zeros), so control-plane consumers never KeyError."""
        from calfkit_tpu.inference.client import JaxLocalModelClient
        from calfkit_tpu.inference.config import RuntimeConfig

        client = JaxLocalModelClient(
            config="debug",
            runtime=RuntimeConfig(max_batch_size=3, kv_layout="dense"),
        )
        cold = client.stats_snapshot()
        expected = {
            "model_name", "platform", "tokens_per_second", "mean_occupancy",
            "active_requests", "free_slots", "max_batch_size", "kv_layout",
            "prefill_tokens", "decode_tokens", "decode_dispatches",
        }
        assert expected <= set(cold)
        assert cold["free_slots"] == 3
        assert cold["decode_tokens"] == 0
        # the record model accepts it without loss
        record = EngineStatsRecord(node_id="agent.x", **cold)
        assert record.max_batch_size == 3


class TestEngineStatsWindowing:
    def test_snapshot_and_delta_reports_interval_rates(self):
        from calfkit_tpu.inference.engine import EngineStats

        stats = EngineStats()
        stats.decode_tokens = 100
        stats.decode_time_s = 2.0
        stats.decode_dispatches = 10
        stats.occupancy_sum = 5.0
        stats.occupancy_hist[3] = 10
        cum, delta = stats.snapshot_and_delta()
        assert cum["decode_tokens"] == 100
        assert delta["decode_tokens"] == 100
        assert delta["tokens_per_second"] == 50.0
        assert delta["interval_s"] is None  # first window: since birth
        stats.decode_tokens = 160
        stats.decode_time_s = 2.5
        stats.decode_dispatches = 12
        stats.occupancy_sum = 6.5
        stats.occupancy_hist[3] = 12
        cum, delta = stats.snapshot_and_delta()
        assert cum["decode_tokens"] == 160
        assert delta["decode_tokens"] == 60
        assert delta["tokens_per_second"] == 120.0  # 60 tok / 0.5 s
        assert delta["occupancy_hist"] == [0, 0, 0, 2]
        assert delta["mean_occupancy"] == 0.75
        assert delta["interval_s"] is not None


class TestGaugeSetFn:
    def test_computed_gauge_reads_fn_at_scrape(self):
        reg = MetricsRegistry()
        g = reg.gauge("staleness_s")
        g.set(1.0)
        ticks = iter((5.0, 7.0))
        g.set_fn(lambda: next(ticks))
        assert g.value == 5.0
        assert "staleness_s 7" in g.render()
        g.set_fn(None)
        assert g.value == 1.0  # back to the last set()

    def test_broken_fn_falls_back_to_last_set(self):
        reg = MetricsRegistry()
        g = reg.gauge("flaky")
        g.set(3.0)
        g.set_fn(lambda: 1 / 0)
        assert g.value == 3.0
        assert "flaky 3" in g.render()


class TestDispatcherDepthGauges:
    async def test_depth_and_in_flight_track_saturation(self):
        from calfkit_tpu.mesh.dispatch import (
            _IN_FLIGHT,
            _LANE_DEPTH_MAX,
            _QUEUE_DEPTH,
            KeyOrderedDispatcher,
        )
        from calfkit_tpu.mesh.transport import Record

        gate = asyncio.Event()

        async def handler(record):
            await gate.wait()

        dispatcher = KeyOrderedDispatcher(handler, max_workers=2)
        dispatcher.start()
        # one key → one lane: records serialize behind the blocked handler
        for i in range(3):
            await dispatcher.submit(
                Record(topic="t", value=b"x", key=b"same-key")
            )
        await asyncio.sleep(0.05)  # lane picked up the first record
        assert _IN_FLIGHT.value == 3
        # 1 in the handler, 2 still queued in its lane
        assert _QUEUE_DEPTH.value == 2
        assert _LANE_DEPTH_MAX.value == 2
        gate.set()
        await dispatcher.stop()
        # a stopped dispatcher never pins its counts into the exposition
        assert _IN_FLIGHT.value == 0
        assert _QUEUE_DEPTH.value == 0
        assert _LANE_DEPTH_MAX.value == 0


class TestHeartbeatStaleness:
    async def test_staleness_climbs_from_last_publish(self):
        from calfkit_tpu.controlplane.config import ControlPlaneConfig
        from calfkit_tpu.controlplane.publisher import (
            _HB_STALENESS,
            Advert,
            ControlPlanePublisher,
        )
        from calfkit_tpu.mesh import InMemoryMesh

        mesh = InMemoryMesh()
        await mesh.start()
        publisher = ControlPlanePublisher(
            mesh,
            [Advert(topic="mesh.agents", node_name="a", node_kind="agent",
                    instance_id="i1", payload={"name": "a"})],
            ControlPlaneConfig(heartbeat_interval=30.0),
        )
        try:
            await publisher.start()
            # scrape-time computed: grows with wall time since the beat
            first = _HB_STALENESS.value
            assert 0.0 <= first < 5.0
            await asyncio.sleep(0.05)
            assert _HB_STALENESS.value > first
        finally:
            await publisher.stop()
            await mesh.stop()


class TestMetricsServer:
    async def test_serves_metrics_and_health(self):
        from calfkit_tpu.observability.http import MetricsServer

        reg = MetricsRegistry()
        reg.counter("served_total", "requests served").inc(3)

        async def get(port: int, path: str) -> tuple[str, str]:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(
                f"GET {path} HTTP/1.0\r\nHost: x\r\n\r\n".encode()
            )
            await writer.drain()
            raw = await reader.read(65536)
            writer.close()
            head, _, body = raw.decode().partition("\r\n\r\n")
            return head.splitlines()[0], body

        async with MetricsServer(port=0, registry=reg) as server:
            status, body = await get(server.port, "/metrics")
            assert status == "HTTP/1.0 200 OK"
            assert "served_total 3" in body
            status, body = await get(server.port, "/healthz")
            assert status == "HTTP/1.0 200 OK" and body == "ok\n"
            status, _ = await get(server.port, "/nope")
            assert status == "HTTP/1.0 404 Not Found"

    async def test_healthz_is_liveness_readyz_is_readiness(self):
        """Satellite: /healthz stays 200 unconditionally (liveness); the
        readiness question moves to /readyz, which is 503 until a
        registered probe says the node can actually serve."""
        from calfkit_tpu.observability.http import MetricsServer

        async def get(port: int, path: str) -> tuple[str, str]:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            await writer.drain()
            raw = await reader.read(65536)
            writer.close()
            head, _, body = raw.decode().partition("\r\n\r\n")
            return head.splitlines()[0], body

        ready = {"ok": False}
        async with MetricsServer(port=0) as server:
            # no probe registered: alive, but never "ready by default"
            status, body = await get(server.port, "/healthz")
            assert status == "HTTP/1.0 200 OK"
            status, body = await get(server.port, "/readyz")
            assert status == "HTTP/1.0 503 Service Unavailable"
            assert "no readiness probe" in body

            server.set_readiness(
                lambda: (ready["ok"], "engine weights + dispatch lanes")
            )
            status, body = await get(server.port, "/readyz")
            assert status == "HTTP/1.0 503 Service Unavailable"
            assert "engine weights" in body
            status, _ = await get(server.port, "/healthz")
            assert status == "HTTP/1.0 200 OK"  # liveness unaffected

            ready["ok"] = True
            status, body = await get(server.port, "/readyz")
            assert status == "HTTP/1.0 200 OK"
            assert body.startswith("ready")

            # a probe that raises reads as unready, never as a 500
            server.set_readiness(lambda: 1 / 0)
            status, body = await get(server.port, "/readyz")
            assert status == "HTTP/1.0 503 Service Unavailable"
            assert "probe error" in body

    async def test_flightrec_endpoint_dumps_registered_journals(self):
        from calfkit_tpu.observability.flightrec import (
            EV_SUBMIT,
            FlightRecorder,
        )
        from calfkit_tpu.observability.http import MetricsServer

        journal = FlightRecorder(8, label="http-test")
        journal.append(EV_SUBMIT, "req-http", -1, 5, 6)

        async def get(port: int, path: str) -> tuple[str, str]:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            await writer.drain()
            raw = await reader.read(262144)
            writer.close()
            head, _, body = raw.decode().partition("\r\n\r\n")
            return head.splitlines()[0], body

        async with MetricsServer(port=0) as server:
            status, body = await get(server.port, "/flightrec")
        assert status == "HTTP/1.0 200 OK"
        ours = [
            json.loads(line)
            for line in body.splitlines()
            if "req-http" in line or '"label": "http-test"' in line
        ]
        assert any(o.get("corr") == "req-http" for o in ours)
        assert journal.counts()["dumped"] == 1


class TestReadinessProbes:
    def test_model_client_ready_tracks_engine_lifecycle(self):
        from calfkit_tpu.inference.client import JaxLocalModelClient

        client = JaxLocalModelClient(config="debug")
        ok, reason = client.ready()
        assert not ok and "not built" in reason

    async def test_worker_ready_tracks_serving_state(self):
        from calfkit_tpu.engine.testing import EchoModelClient
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        agent = Agent("probe", model=EchoModelClient())
        worker = Worker([agent], mesh=InMemoryMesh(), owns_transport=True)
        ok, reason = worker.ready()
        assert not ok and "new" in reason
        await worker.start()
        try:
            assert worker.ready() == (True, "serving")
        finally:
            await worker.stop()
        assert worker.ready()[0] is False


class TestCliRenderers:
    def _spans(self) -> list[SpanRecord]:
        return [
            SpanRecord(
                trace_id="t", span_id="a", name="client.dispatch",
                kind="client", emitter="client/c1", start_s=100.0,
                duration_ms=10.0,
            ),
            SpanRecord(
                trace_id="t", span_id="b", parent_span_id="a",
                name="agent.hop", kind="agent", emitter="agent/planner",
                start_s=100.002, duration_ms=400.0,
            ),
            SpanRecord(
                trace_id="t", span_id="c", parent_span_id="b",
                name="engine.generate", kind="engine",
                emitter="engine/debug", start_s=100.01, duration_ms=350.0,
                status="error",
            ),
        ]

    def test_waterfall_orders_and_indents(self):
        from calfkit_tpu.cli.obs import render_waterfall

        out = render_waterfall(self._spans())
        lines = out.splitlines()
        assert "3 spans" in lines[0]
        assert "client.dispatch" in lines[1]
        assert "  agent.hop" in lines[2]  # depth 1
        assert "    engine.generate" in lines[3]  # depth 2
        assert "!error" in lines[3]
        assert render_waterfall([]) == "no spans"

    def test_waterfall_survives_orphan_parents(self):
        from calfkit_tpu.cli.obs import render_waterfall

        spans = [
            SpanRecord(trace_id="t", span_id="x", parent_span_id="gone",
                       name="orphan", start_s=1.0, duration_ms=1.0)
        ]
        assert "orphan" in render_waterfall(spans)

    def test_stats_table(self):
        from calfkit_tpu.cli.obs import render_stats_table

        records = [
            EngineStatsRecord(
                node_id="agent.planner", model_name="debug",
                tokens_per_second=1843.2, mean_occupancy=0.74,
                active_requests=11, free_slots=5, max_batch_size=16,
                decode_tokens=918230,
                latency_ms={"ttft_p50": 250.0, "ttft_p99": 1000.0},
                flightrec={"appended": 5000, "dropped": 904, "dumped": 1},
            )
        ]
        out = render_stats_table(records)
        assert "agent.planner" in out
        assert "1843.2" in out
        assert "11/16" in out
        assert "250/1000" in out
        # ring overflow is observable, not silent: appended/dropped column
        assert "FREC APP/DROP" in out
        assert "5000/904" in out
        assert "no live engines" in render_stats_table([])
        # a pre-flightrec record renders "-", not a KeyError
        records[0] = records[0].model_copy(update={"flightrec": None})
        assert "5000/904" not in render_stats_table(records)

    def test_span_parsing_filters_and_tolerates_garbage(self):
        from calfkit_tpu.cli.obs import _parse_spans

        good = SpanRecord(trace_id="t", span_id="s", name="n")
        items = {
            "t/s": good.to_wire(),
            "t/bad": b"not-json",
            "other/s": SpanRecord(
                trace_id="other", span_id="s", name="x"
            ).to_wire(),
        }
        spans = _parse_spans(items, "t")
        assert [s.name for s in spans] == ["n"]
