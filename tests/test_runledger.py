"""Run-scoped observability suite (ISSUE 17).

Four pure-law groups and one end-to-end acceptance drill:

- the ``x-mesh-run`` header laws (round trip; corrupt → un-linked run,
  never a shared bogus id — the PR 5 degrade-never-fault law);
- :class:`RunLedger` unit laws (idempotent begin, LRU cap,
  first-signal-wins outcome writes, token accounting, derived counters);
- the pure SLO rollup fold (window filtering, nearest-rank percentiles,
  error-budget burn, orphan classification) and the worker-side
  :class:`RunWindowStore` fail-open fold;
- the ``ck run`` / ``ck slo`` render functions (no mesh required);
- THE acceptance scenario: a replica hard-killed mid-stream fails over,
  and the ONE logical run's ledger lists both attempts with typed
  outcomes, exports to ``mesh.runs``, and renders as one stitched
  cross-replica timeline.
"""

import pytest

from calfkit_tpu import protocol
from calfkit_tpu.cli.obs import (
    _parse_run_record,
    _parse_run_spans,
    _parse_slo,
    render_run_timeline,
    render_slo_table,
)
from calfkit_tpu.models.records import (
    RunAttemptRecord,
    RunRecord,
    SloRollupRecord,
    SpanRecord,
)
from calfkit_tpu.observability.runledger import (
    RunLedger,
    RunWindowStore,
    rollup_window,
    run_percentile,
)


# ------------------------------------------------------------ header laws
class TestRunHeaderLaws:
    def test_round_trip(self):
        value = protocol.format_run("a1b2c3d4e5f60718", 0)
        assert value == "a1b2c3d4e5f60718:0"
        assert protocol.parse_run(value) == ("a1b2c3d4e5f60718", 0)
        assert protocol.parse_run(value.encode()) == ("a1b2c3d4e5f60718", 0)
        # attempts survive multi-digit and the id may contain colons
        # only via rpartition (ids are hex, but the parser must not care)
        assert protocol.parse_run("a:b:7") == ("a:b", 7)

    @pytest.mark.parametrize(
        "raw",
        [
            None,
            b"",
            "",
            "no-separator",
            "run:1.5",  # int(), not float(): floats are not counters
            "run:nan",
            "run:inf",
            "run:-1",  # negative attempts are corruption
            "run:",  # empty attempt
            ":3",  # empty run id
            b"\xff\xfe\xfd",  # undecodable utf-8
        ],
    )
    def test_corrupt_degrades_to_unlinked(self, raw):
        assert protocol.parse_run(raw) is None

    def test_run_header_is_forwarded_authority(self):
        # the header is in the protocol authority list, so hop-by-hop
        # normalization keeps it (contrast: ad-hoc headers get dropped)
        assert protocol.HDR_RUN in protocol.ALL_HEADERS


# ------------------------------------------------------------- ledger unit
class TestRunLedgerUnit:
    def test_begin_idempotent_and_attempt_recording(self):
        ledger = RunLedger()
        ledger.begin_run("r1", agent="svc", client_id="c1", started_at=10.0)
        ledger.note_attempt(
            "r1", attempt_no=0, correlation_id="corr0", kind="first",
            placement="svc@i0", agent="svc", started_at=10.0,
        )
        # a resumed supervisor pass re-begins: recorded attempts survive
        ledger.begin_run("r1", agent="svc", client_id="c1", started_at=11.0)
        record = ledger.run_report("r1")
        assert record is not None
        assert record.started_at == 10.0
        assert [a.correlation_id for a in record.attempts] == ["corr0"]
        # unknown runs: report None, appends are no-ops (never a fault)
        assert ledger.run_report("missing") is None
        ledger.note_attempt("missing", attempt_no=0, correlation_id="x")
        ledger.note_outcome("missing", "x", outcome="ok")

    def test_first_signal_wins(self):
        """A zombie's late terminal must not overwrite the supervisor's
        ``superseded`` verdict — and vice versa: whichever signal landed
        first is what the caller experienced."""
        ledger = RunLedger()
        ledger.begin_run("r1", agent="svc")
        ledger.note_attempt("r1", attempt_no=0, correlation_id="corr0")
        ledger.note_outcome(
            "r1", "corr0", outcome="superseded", error_type="dead:stale",
            finished_at=12.0,
        )
        ledger.note_outcome(
            "r1", "corr0", outcome="ok", finished_at=13.0
        )  # the zombie's late reply: dropped
        [attempt] = ledger.run_report("r1").attempts
        assert attempt.outcome == "superseded"
        assert attempt.error_type == "dead:stale"
        assert attempt.finished_at == 12.0

    def test_tokens_and_derived_counters(self):
        ledger = RunLedger()
        ledger.begin_run("r1", agent="svc", started_at=1.0)
        ledger.note_attempt(
            "r1", attempt_no=0, correlation_id="c0", kind="first"
        )
        ledger.note_attempt(
            "r1", attempt_no=1, correlation_id="c1", kind="failover"
        )
        ledger.note_attempt(
            "r1", attempt_no=2, correlation_id="c2", kind="resume"
        )
        ledger.add_tokens("r1", "c0", 2)
        ledger.add_tokens("r1", "c2", 3)
        ledger.note_outcome(
            "r1", "c0", outcome="shed", error_type="mesh.overloaded"
        )
        ledger.note_outcome("r1", "c1", outcome="superseded")
        ledger.note_outcome("r1", "c2", outcome="ok")
        ledger.finish_run("r1", outcome="ok", finished_at=4.0)
        record = ledger.run_report("r1")
        assert record.outcome == "ok"
        assert record.sheds == 1
        assert record.failovers == 1
        assert record.resumes == 1
        assert record.hedges == 0
        assert record.tokens_delivered == 5
        assert [a.tokens_delivered for a in record.attempts] == [2, 0, 3]

    def test_lru_cap_evicts_oldest(self):
        ledger = RunLedger(cap=2)
        for i in range(3):
            ledger.begin_run(f"r{i}", agent="svc")
        assert ledger.run_ids() == ["r1", "r2"]
        assert ledger.run_report("r0") is None

    def test_finished_records_excludes_pending(self):
        ledger = RunLedger()
        ledger.begin_run("open", agent="svc")
        ledger.begin_run("done", agent="svc")
        ledger.finish_run("done", outcome="fault", error_type="X")
        records = ledger.finished_records()
        assert [r.run_id for r in records] == ["done"]
        assert records[0].error_type == "X"


# ------------------------------------------------------------ rollup laws
class TestRollupLaws:
    def test_nearest_rank_percentile(self):
        assert run_percentile([], 0.95) == 0.0
        values = [float(v) for v in range(1, 11)]
        assert run_percentile(values, 0.50) == 6.0
        assert run_percentile(values, 0.95) == 10.0
        assert run_percentile(values, 0.0) == 1.0

    def _entry(self, *, finished_at, started_at=0.0, outcome="ok", **kw):
        entry = {
            "started_at": started_at,
            "finished_at": finished_at,
            "outcome": outcome,
            "error_type": "",
            "attempts": 1,
            "sheds": 0,
            "failovers": 0,
        }
        entry.update(kw)
        return entry

    def test_window_filters_and_ratio(self):
        entries = [
            self._entry(started_at=90.0, finished_at=100.0),
            self._entry(
                started_at=95.0, finished_at=101.0, outcome="fault",
                error_type="mesh.orphaned", attempts=3, failovers=2,
            ),
            # outside the window: ignored entirely
            self._entry(started_at=1.0, finished_at=2.0, outcome="fault"),
        ]
        rollup = rollup_window(
            entries, agent="svc", window_end=101.0, window_s=10.0,
            target=0.9,
        )
        assert rollup.runs == 2
        assert rollup.completed == 1
        assert rollup.completion_ratio == 0.5
        assert rollup.orphan_rate == 0.5
        assert rollup.failover_rate == 0.5
        assert rollup.attempts == 4
        assert rollup.attempt_amplification == 2.0
        assert rollup.e2e_p50_s == pytest.approx(10.0)
        # burn: failing 50% of runs against a 10% budget = 5x burn
        assert rollup.error_budget_burn == pytest.approx(5.0)

    def test_empty_window_is_healthy(self):
        rollup = rollup_window(
            [], agent="svc", window_end=100.0, window_s=10.0
        )
        assert rollup.runs == 0
        assert rollup.completion_ratio == 1.0
        assert rollup.error_budget_burn == 0.0

    def test_window_store_fold_fail_open(self):
        store = RunWindowStore(cap=2)
        good = RunRecord(
            run_id="r1", agent="svc", started_at=1.0, finished_at=2.0,
            outcome="ok",
            attempts=[
                RunAttemptRecord(attempt_no=0, correlation_id="c0"),
            ],
        )
        store.fold(b"r1", good.to_wire())
        store.fold(b"junk", b"\x00not json")  # dropped, never raises
        store.fold(b"tomb", None)  # tombstone: skipped
        pending = RunRecord(run_id="r2", agent="svc", outcome="pending")
        store.fold(b"r2", pending.to_wire())  # pending: skipped
        agentless = RunRecord(run_id="r3", outcome="ok")
        store.fold(b"r3", agentless.to_wire())  # no agent: skipped
        assert store.agents() == ["svc"]
        rollup = store.rollup_for("svc", window_end=5.0, window_s=10.0)
        assert rollup.runs == 1 and rollup.completed == 1
        # the per-agent deque cap holds no matter how many runs fold
        for i in range(5):
            more = good.model_copy(update={"run_id": f"m{i}"})
            store.fold(f"m{i}", more.to_wire())
        assert store.rollup_for("svc", window_end=5.0, window_s=10.0).runs == 2


# ---------------------------------------------------------------- renders
class TestRunRenderers:
    def _record(self):
        return RunRecord(
            run_id="a" * 32, agent="svc", client_id="c1",
            started_at=100.0, finished_at=100.5, outcome="ok",
            attempts=[
                RunAttemptRecord(
                    attempt_no=0, correlation_id="corr0", kind="first",
                    placement="svc@i0", agent="svc", started_at=100.0,
                    finished_at=100.2, outcome="superseded",
                    error_type="dead:stale",
                ),
                RunAttemptRecord(
                    attempt_no=1, correlation_id="corr1", kind="failover",
                    placement="svc@i1", agent="svc", started_at=100.2,
                    finished_at=100.5, outcome="ok", tokens_delivered=4,
                ),
            ],
            failovers=1, tokens_delivered=4,
        )

    def test_run_timeline_stitches_attempts(self):
        spans = [
            SpanRecord(
                trace_id="corr0", span_id="s0", name="agent.svc",
                kind="agent", emitter="agent/svc", start_s=100.0,
                duration_ms=200.0, status="cancelled",
            ),
            SpanRecord(
                trace_id="corr1", span_id="s1", name="agent.svc",
                kind="agent", emitter="agent/svc", start_s=100.2,
                duration_ms=300.0,
            ),
        ]
        out = render_run_timeline(
            self._record(), spans,
            {"corr1": [{"t_s": 100.25, "event": "ADMIT", "seq": 1}]},
        )
        # one header + both attempts, each with its placement and typed
        # outcome, spans positioned on the RUN window, flightrec joined
        assert "1 failover(s)" in out
        assert "attempt 0 [first]" in out and "svc@i0" in out
        assert "superseded(dead:stale)" in out
        assert "attempt 1 [failover]" in out and "svc@i1" in out
        assert "flightrec ADMIT" in out
        assert "500.0 ms end-to-end" in out

    def test_run_timeline_without_spans_or_flightrec(self):
        # the stitch is best-effort: a run record alone still renders
        out = render_run_timeline(self._record(), [], None)
        assert "attempt 0" in out and "attempt 1" in out

    def test_parse_helpers(self):
        record = self._record()
        items = {record.run_id: record.to_wire(), "other": b"junk"}
        assert _parse_run_record(items, record.run_id) is not None
        assert _parse_run_record(items, "missing") is None
        assert _parse_run_record({"x": b"\x00"}, "x") is None
        span = SpanRecord(trace_id="corr0", span_id="s0")
        spans = _parse_run_spans(
            {"corr0/s0": span.to_wire(), "zzz/s1": span.to_wire(),
             "corr0/bad": b"\x00"},
            ["corr0", "corr1"],
        )
        assert [s.span_id for s in spans] == ["s0"]

    def test_slo_table(self):
        from calfkit_tpu.models.records import (
            ControlPlaneRecord,
            ControlPlaneStamp,
        )

        rollup = SloRollupRecord(
            agent="svc", node_id="i0", runs=40, completed=39,
            completion_ratio=0.975, e2e_p50_s=0.4, e2e_p95_s=0.9,
            e2e_p99_s=1.2, attempts=44, attempt_amplification=1.1,
            failover_rate=0.05, error_budget_burn=25.0, window_end=50.0,
        )
        wrapped = ControlPlaneRecord(
            stamp=ControlPlaneStamp(
                node_name="svc", node_kind="agent", instance_id="i0",
                heartbeat_at=50.0,
            ),
            record=rollup.model_dump(),
        )
        records = _parse_slo({"svc@i0": wrapped.to_wire(), "bad": b"\x00"})
        assert len(records) == 1
        out = render_slo_table(records)
        assert "0.9750" in out and "0.40/0.90/1.20" in out
        assert "25.00" in out
        assert "no SLO rollups" in render_slo_table([])


# ------------------------------------------------------------- end to end
class TestRunLedgerE2E:
    async def test_failover_run_has_one_ledger_two_attempts(self):
        """THE ISSUE 17 acceptance drill: hard-kill a replica mid-stream
        under failover supervision.  The caller sees one contiguous
        answer; the run LEDGER sees one run with two attempts — the
        victim's typed non-ok terminal and the survivor's ``ok`` — the
        record exports to ``mesh.runs``, and the CLI parse + stitch
        renders both placements in one timeline."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.fleet import FailoverPolicy, FleetRouter
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.models.node_result import InvocationResult
        from tests._chaos import (
            FleetTopology,
            StreamingStubModel,
            settle,
            virtual_clock,
        )

        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            models = [
                StreamingStubModel(text="alpha beta gamma")
                for _ in range(2)
            ]
            async with FleetTopology(
                mesh, models, agent_kwargs={"stream_tokens": True}
            ) as fleet:
                low = fleet.index_of_lowest_key()
                models[1 - low].release.set()  # only the victim pauses
                router = FleetRouter(
                    mesh, "least-loaded",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(
                    mesh, router=router,
                    failover=FailoverPolicy(
                        probe_interval=0.02, max_failovers=2
                    ),
                )
                await router.start()
                await settle(
                    lambda: len(router.registry.eligible("svc")) == 2,
                    message="fleet never became routable",
                )
                tokens = []
                result = None
                killed = False
                async for item in client.agent("svc").stream(
                    "tell me a story", timeout=60
                ):
                    if isinstance(item, InvocationResult):
                        result = item
                        continue
                    if getattr(item.step, "kind", "") != "token":
                        continue
                    tokens.append(item.step.text)
                    if not killed:
                        killed = True
                        fleet.kill(low)
                        clock.advance(fleet.config.stale_after + 1)
                assert killed and result is not None
                assert "".join(tokens) == result.output

                # ---- the ledger half: ONE run, both attempts, typed
                [run_id] = client.run_ledger.run_ids()
                record = client.run_ledger.run_report(run_id)
                assert record.outcome == "ok"
                assert len(record.attempts) == 2
                first, second = sorted(
                    record.attempts, key=lambda a: a.attempt_no
                )
                assert first.kind == "first"
                # the victim's terminal is typed non-ok (the supervisor's
                # superseded verdict or the cancel's terminal — whichever
                # signal landed first)
                assert first.outcome in ("superseded", "cancelled")
                # tokens were already delivered when the replica died,
                # so the re-dispatch is a decode-from-offset RESUME in
                # the ledger's kind vocabulary (the wire mark stays
                # "failover" — that header's vocabulary is placement
                # accounting, the ledger's is run history)
                assert second.kind == "resume"
                assert second.outcome == "ok"
                # distinct placements = the stitch spans both replicas
                assert first.placement != second.placement
                assert first.correlation_id != second.correlation_id
                # delivered-token accounting survives the replayed
                # prefix dedupe: total == what the caller actually saw
                assert record.tokens_delivered == len(tokens)
                assert record.resumes == 1

                await client.close()  # drains the mesh.runs export

                # ---- the export + CLI half: parse off the compacted
                # table and render the stitched timeline
                reader = mesh.table_reader(protocol.RUNS_TOPIC)
                published = _parse_run_record(reader.items(), run_id)
                assert published is not None
                assert published.outcome == "ok"
                assert len(published.attempts) == 2
                out = render_run_timeline(published, [])
                assert first.placement in out
                assert second.placement in out
                assert "attempt 0 [first]" in out
                assert "attempt 1 [resume]" in out
            await mesh.stop()

    async def test_bare_start_closes_run_on_terminal(self):
        """A bare ``start()`` (no execute()/stream() supervisor) owns the
        run it mints: the attempt's terminal closes the run and exports
        it to ``mesh.runs`` — an un-supervised run must not sit
        ``pending`` forever (the quickstart idiom is start()+result())."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker
        from calfkit_tpu.sim.stubs import ServingStubModel
        from tests._chaos import virtual_clock

        with virtual_clock():
            mesh = InMemoryMesh()
            async with Worker(
                [Agent("svc", model=ServingStubModel(text="done"))],
                mesh=mesh,
            ):
                client = Client.connect(mesh)
                handle = await client.agent("svc").start("hi", timeout=30)
                await handle.result()
                report = handle.run_report()
                assert report is not None
                assert report.outcome == "ok"
                [attempt] = report.attempts
                assert attempt.kind == "first"
                assert attempt.outcome == "ok"
                await client.close()  # drains the mesh.runs export
                reader = mesh.table_reader(protocol.RUNS_TOPIC)
                assert _parse_run_record(reader.items(), handle.run_id)
            await mesh.stop()

    async def test_execute_fault_closes_run_typed(self):
        """A run that ends in a typed fault closes the ledger with that
        type — and a shed attempt is marked ``shed``, not ``fault``."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.exceptions import NodeFaultError
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker
        from tests._chaos import virtual_clock

        class Exploder:
            async def request(self, messages, settings=None, params=None):
                raise RuntimeError("boom")

        with virtual_clock():
            mesh = InMemoryMesh()
            async with Worker(
                [Agent("svc", model=Exploder())], mesh=mesh
            ):
                client = Client.connect(mesh)
                with pytest.raises(NodeFaultError):
                    await client.agent("svc").execute("hi", timeout=30)
                records = client.run_ledger.finished_records()
                assert len(records) == 1
                assert records[0].outcome == "fault"
                assert records[0].error_type  # typed, never empty
                [attempt] = records[0].attempts
                assert attempt.outcome == "fault"
                await client.close()
            await mesh.stop()
