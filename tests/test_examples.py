"""Every example must actually run — as a subprocess, exactly as documented.

The reference ships examples as living documentation; here they are kept
living by CI.  Each run uses the in-memory mesh and deterministic models
(no broker, no weights, no network) — except ``local_serving``, which
deliberately runs the REAL inference engine on the debug preset with
random weights (its assertion is about prefix-cache stats, not output
content).
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

EXAMPLES = [
    ("quickstart", "examples/quickstart/weather_agent.py", "RESULT"),
    ("help_desk", "examples/help_desk/run.py", "[phase 2] Security here"),
    ("newsroom", "examples/newsroom/run.py", "FINAL (from the writer"),
    ("expense_approval", "examples/expense_approval/run.py",
     "team_lead -> director -> vp"),
    ("launch_review", "examples/launch_review/run.py", "Launch review: GO"),
    ("multi_agent_panel", "examples/multi_agent_panel/run.py", "--- round 2"),
    ("streaming", "examples/streaming/run.py", "RESULT: Itinerary"),
    ("structured_fanout", "examples/structured_fanout/trip_planner.py",
     "PLAN: Lisbon"),
    ("quickstart_mcp", "examples/quickstart_mcp/run.py", "From the docs:"),
    ("topic_provisioning", "examples/topic_provisioning.py",
     "second pass: ok"),
    ("rpc_worker", "examples/rpc_worker.py", "HELLO MESH RPC"),
    ("kafka_mesh", "examples/kafka_mesh.py", "RESULT over kafka:"),
    ("local_serving", "examples/local_serving/agent_on_engine.py",
     "prefix cache reused"),
]


@pytest.mark.parametrize(
    "script,expect", [(s, e) for _, s, e in EXAMPLES],
    ids=[name for name, _, _ in EXAMPLES],
)
def test_example_runs(script: str, expect: str):
    if "kafka" in script:
        from calfkit_tpu.mesh.kafka_wire import find_kafkad

        if find_kafkad() is None:
            pytest.skip("kafkad not built (make -C native)")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, script)],
        capture_output=True,
        text=True,
        timeout=120,
        cwd=REPO,
    )
    assert proc.returncode == 0, f"{script} failed:\n{proc.stdout}\n{proc.stderr}"
    assert expect in proc.stdout, (
        f"{script} missing expected output {expect!r}:\n{proc.stdout}"
    )
