"""The chat REPL driven in-process: prompts in, rendered steps out, history
threading across turns (reference analogs: tests/test_chat_cli.py,
test_chat_session.py, test_picker.py)."""

import builtins

import pytest

from calfkit_tpu.cli.chat import repl
from calfkit_tpu.client import Client
from calfkit_tpu.engine import FunctionModelClient, TestModelClient
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models import ModelResponse, TextOutput
from calfkit_tpu.nodes import Agent, agent_tool
from calfkit_tpu.worker import Worker


@pytest.fixture
def scripted_input(monkeypatch):
    """Feed the REPL a list of prompts, then EOF."""

    def feed(*prompts: str):
        it = iter(prompts)

        def fake_input(_prompt: str = "") -> str:
            try:
                return next(it)
            except StopIteration:
                raise EOFError

        monkeypatch.setattr(builtins, "input", fake_input)

    return feed


class TestRepl:
    async def test_turn_renders_answer_and_steps(self, scripted_input, capsys):
        @agent_tool
        def lookup(q: str) -> str:
            """Lookup.

            Args:
                q: Query.
            """
            return "found it"

        agent = Agent(
            "chatty",
            model=TestModelClient(custom_output_text="here you go"),
            tools=[lookup],
        )
        mesh = InMemoryMesh()
        async with Worker([agent, lookup], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            scripted_input("find me a thing")
            await repl(client, "chatty")
            await client.close()
        out = capsys.readouterr().out
        assert "chatty> here you go" in out
        assert "lookup" in out          # the tool step rendered
        assert "bye" in out             # EOF exits cleanly

    async def test_history_threads_across_turns(self, scripted_input, capsys):
        seen_counts = []

        def model(messages, params):
            seen_counts.append(len(messages))
            return ModelResponse(parts=[TextOutput(text=f"turn {len(seen_counts)}")])

        agent = Agent("memory", model=FunctionModelClient(model))
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            scripted_input("first", "second")
            await repl(client, "memory")
            await client.close()
        # turn 2's model saw turn 1's exchange (history grew)
        assert len(seen_counts) == 2
        assert seen_counts[1] > seen_counts[0]
        out = capsys.readouterr().out
        assert "turn 1" in out and "turn 2" in out

    async def test_blank_lines_do_not_invoke(self, scripted_input, capsys):
        calls = []

        def model(messages, params):
            calls.append(1)
            return ModelResponse(parts=[TextOutput(text="hi")])

        agent = Agent("quiet", model=FunctionModelClient(model))
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            scripted_input("", "   ", "real question")
            await repl(client, "quiet")
            await client.close()
        assert len(calls) == 1
