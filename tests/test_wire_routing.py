"""Per-partition leader routing in the wire client (VERDICT r4 item 4).

kafkad is single-node, so the spread-leader paths are exercised here
against an in-test TWO-broker fake cluster speaking the wire format:
metadata names different leaders per partition, produce/fetch must land
on the right broker, NOT_LEADER answers must trigger refresh-and-retry,
and group APIs must ride the coordinator.
"""

from __future__ import annotations

import asyncio
import struct

import pytest

from calfkit_tpu.mesh.kafka_wire import (
    ERR_NOT_LEADER,
    KafkaWireClient,
    KafkaWireError,
    encode_record_batch,
)


class _FakeBroker:
    """Minimal wire-speaking broker: Metadata v1, Produce v3, Fetch v4,
    FindCoordinator v0, Heartbeat v1.  The CLUSTER decides who leads
    which partition; each broker answers produce/fetch only for the
    partitions it currently leads (NOT_LEADER otherwise) and records
    every produce it accepted."""

    def __init__(self, cluster: "_FakeCluster", node_id: int):
        self.cluster = cluster
        self.node_id = node_id
        self.port = 0
        self.produced: list[tuple[str, int, bytes]] = []
        self.heartbeats = 0
        self._server: asyncio.AbstractServer | None = None

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._serve, "127.0.0.1", 0
        )
        self.port = self._server.sockets[0].getsockname()[1]

    async def stop(self) -> None:
        if self._server:
            self._server.close()
            await self._server.wait_closed()

    async def _serve(self, reader, writer) -> None:
        try:
            while True:
                szbuf = await reader.readexactly(4)
                (size,) = struct.unpack(">i", szbuf)
                blob = await reader.readexactly(size)
                api, _ver, corr = struct.unpack(">hhi", blob[:8])
                (cid_len,) = struct.unpack(">h", blob[8:10])
                body = blob[10 + max(0, cid_len):]
                out = struct.pack(">i", corr) + self._handle(api, body)
                writer.write(struct.pack(">i", len(out)) + out)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    # ----------------------------------------------------------- encoding
    @staticmethod
    def _s(text: str) -> bytes:
        raw = text.encode()
        return struct.pack(">h", len(raw)) + raw

    def _handle(self, api: int, body: bytes) -> bytes:
        if api == 3:
            return self._metadata()
        if api == 0:
            return self._produce(body)
        if api == 1:
            return self._fetch(body)
        if api == 10:
            coord = self.cluster.coordinator
            return (struct.pack(">hi", 0, coord.node_id)
                    + self._s("127.0.0.1") + struct.pack(">i", coord.port))
        if api == 12:
            self.heartbeats += 1
            code = 0 if self is self.cluster.coordinator else 16
            return struct.pack(">ih", 0, code)
        raise AssertionError(f"fake broker got api {api}")

    def _metadata(self) -> bytes:
        out = struct.pack(">i", len(self.cluster.brokers))
        for broker in self.cluster.brokers:
            out += (struct.pack(">i", broker.node_id) + self._s("127.0.0.1")
                    + struct.pack(">i", broker.port) + struct.pack(">h", -1))
        out += struct.pack(">i", 0)  # controller
        topics: dict[str, dict[int, int]] = {}
        for (topic, part), node in self.cluster.leaders.items():
            topics.setdefault(topic, {})[part] = node
        out += struct.pack(">i", len(topics))
        for topic, parts in topics.items():
            out += struct.pack(">h", 0) + self._s(topic) + b"\x00"
            out += struct.pack(">i", len(parts))
            for part, node in parts.items():
                out += struct.pack(">hii", 0, part, node)
                out += struct.pack(">ii", 0, 0)  # replicas, isr
        return out

    def _produce(self, body: bytes) -> bytes:
        r_off = 0
        # skip transactional_id(-1 string), acks, timeout, topic count(=1)
        r_off += 2 + 2 + 4 + 4
        (tlen,) = struct.unpack_from(">h", body, r_off)
        r_off += 2
        topic = body[r_off:r_off + tlen].decode()
        r_off += tlen + 4  # partition count (=1)
        (part,) = struct.unpack_from(">i", body, r_off)
        r_off += 4
        (blen,) = struct.unpack_from(">i", body, r_off)
        r_off += 4
        batch = body[r_off:r_off + blen]
        if self.cluster.leaders.get((topic, part)) == self.node_id:
            self.produced.append((topic, part, batch))
            err, base = 0, len(self.produced) - 1
        else:
            err, base = ERR_NOT_LEADER, -1
        return (struct.pack(">i", 1) + self._s(topic) + struct.pack(">i", 1)
                + struct.pack(">ih", part, err)
                + struct.pack(">qq", base, -1))

    def _fetch(self, body: bytes) -> bytes:
        off = 4 + 4 + 4 + 4 + 1  # replica, max_wait, min_bytes, max_bytes, isolation
        (ntopics,) = struct.unpack_from(">i", body, off)
        off += 4
        wants: list[tuple[str, int]] = []
        for _ in range(ntopics):
            (tlen,) = struct.unpack_from(">h", body, off)
            off += 2
            topic = body[off:off + tlen].decode()
            off += tlen
            (nparts,) = struct.unpack_from(">i", body, off)
            off += 4
            for _ in range(nparts):
                (part,) = struct.unpack_from(">i", body, off)
                off += 4 + 8 + 4  # partition, offset, max_bytes
                wants.append((topic, part))
        out = struct.pack(">i", 0)  # throttle
        by_topic: dict[str, list[int]] = {}
        for topic, part in wants:
            by_topic.setdefault(topic, []).append(part)
        out += struct.pack(">i", len(by_topic))
        for topic, parts in by_topic.items():
            out += self._s(topic) + struct.pack(">i", len(parts))
            for part in parts:
                lead_here = self.cluster.leaders.get((topic, part)) == self.node_id
                err = 0 if lead_here else ERR_NOT_LEADER
                blob = b""
                if lead_here:
                    blob = b"".join(
                        batch for t, p, batch in self.produced
                        if t == topic and p == part
                    )
                out += struct.pack(">ih", part, err)
                out += struct.pack(">qq", 1, 1)  # hwm, last stable
                out += struct.pack(">i", 0)      # aborted
                out += struct.pack(">i", len(blob)) + blob
        return out


class _FakeCluster:
    def __init__(self):
        self.brokers = [_FakeBroker(self, 0), _FakeBroker(self, 1)]
        self.leaders: dict[tuple[str, int], int] = {}
        self.coordinator: _FakeBroker = self.brokers[1]

    async def __aenter__(self):
        for broker in self.brokers:
            await broker.start()
        return self

    async def __aexit__(self, *exc):
        for broker in self.brokers:
            await broker.stop()


class TestLeaderRouting:
    def test_produce_routes_to_each_partition_leader(self):
        async def run() -> None:
            async with _FakeCluster() as cluster:
                cluster.leaders = {("t", 0): 0, ("t", 1): 1}
                client = KafkaWireClient("127.0.0.1", cluster.brokers[0].port)
                try:
                    await client.metadata(["t"])
                    batch = encode_record_batch([(b"k", b"v", [])], 1)
                    await client.produce("t", 0, batch)
                    await client.produce("t", 1, batch)
                    assert [p for _t, p, _b in cluster.brokers[0].produced] == [0]
                    assert [p for _t, p, _b in cluster.brokers[1].produced] == [1]
                finally:
                    await client.close()

        asyncio.run(run())

    def test_fetch_fans_out_to_leaders_and_merges(self):
        async def run() -> None:
            async with _FakeCluster() as cluster:
                cluster.leaders = {("t", 0): 0, ("t", 1): 1}
                client = KafkaWireClient("127.0.0.1", cluster.brokers[0].port)
                try:
                    await client.metadata(["t"])
                    batch = encode_record_batch([(b"k", b"v", [])], 1)
                    await client.produce("t", 0, batch)
                    await client.produce("t", 1, batch)
                    results = await client.fetch([("t", 0, 0), ("t", 1, 0)])
                    got = {(t, p): (err, blob) for t, p, err, blob in results}
                    assert got[("t", 0)][0] == 0 and got[("t", 0)][1]
                    assert got[("t", 1)][0] == 0 and got[("t", 1)][1]
                finally:
                    await client.close()

        asyncio.run(run())

    def test_leader_move_triggers_refresh_and_retry(self):
        """Leadership moves AFTER the client cached it: the stale broker
        answers NOT_LEADER, the client must re-learn and succeed without
        surfacing an error."""

        async def run() -> None:
            async with _FakeCluster() as cluster:
                cluster.leaders = {("t", 0): 0}
                client = KafkaWireClient("127.0.0.1", cluster.brokers[0].port)
                try:
                    await client.metadata(["t"])
                    batch = encode_record_batch([(b"k", b"v", [])], 1)
                    await client.produce("t", 0, batch)
                    cluster.leaders[("t", 0)] = 1  # leadership moves
                    await client.produce("t", 0, batch)  # must NOT raise
                    assert len(cluster.brokers[1].produced) == 1
                finally:
                    await client.close()

        asyncio.run(run())

    def test_fetch_not_leader_refreshes_routing(self):
        async def run() -> None:
            async with _FakeCluster() as cluster:
                cluster.leaders = {("t", 0): 0}
                client = KafkaWireClient("127.0.0.1", cluster.brokers[0].port)
                try:
                    await client.metadata(["t"])
                    cluster.leaders[("t", 0)] = 1
                    first = await client.fetch([("t", 0, 0)])
                    assert first[0][2] == ERR_NOT_LEADER  # surfaced once...
                    second = await client.fetch([("t", 0, 0)])
                    assert second[0][2] == 0  # ...then routed correctly
                finally:
                    await client.close()

        asyncio.run(run())

    def test_unrouted_produce_refreshes_and_succeeds(self):
        async def run() -> None:
            async with _FakeCluster() as cluster:
                # metadata deliberately NOT fetched; partition led by 1
                # but bootstrap is broker 0 and metadata refresh still
                # reports broker 1 → retry succeeds
                cluster.leaders = {("t", 0): 1}
                client = KafkaWireClient("127.0.0.1", cluster.brokers[0].port)
                try:
                    batch = encode_record_batch([(b"k", b"v", [])], 1)
                    await client.produce("t", 0, batch)
                    assert len(cluster.brokers[1].produced) == 1
                finally:
                    await client.close()

        asyncio.run(run())


class TestCoordinatorRouting:
    def test_group_apis_ride_the_coordinator(self):
        async def run() -> None:
            async with _FakeCluster() as cluster:
                cluster.leaders = {("t", 0): 0}
                client = KafkaWireClient("127.0.0.1", cluster.brokers[0].port)
                try:
                    await client.ensure_coordinator("g")
                    code = await client.heartbeat("g", 1, "m")
                    assert code == 0  # answered by the coordinator itself
                    assert cluster.brokers[1].heartbeats == 1
                    assert cluster.brokers[0].heartbeats == 0
                finally:
                    await client.close()

        asyncio.run(run())

    def test_not_coordinator_is_refreshable(self):
        async def run() -> None:
            async with _FakeCluster() as cluster:
                cluster.leaders = {("t", 0): 0}
                client = KafkaWireClient("127.0.0.1", cluster.brokers[0].port)
                try:
                    await client.ensure_coordinator("g")
                    cluster.coordinator = cluster.brokers[0]  # moves
                    code = await client.heartbeat("g", 1, "m")
                    assert code == 16  # NOT_COORDINATOR surfaced
                    client.forget_coordinator()
                    await client.ensure_coordinator("g")
                    assert await client.heartbeat("g", 1, "m") == 0
                finally:
                    await client.close()

        asyncio.run(run())
