"""The real-chip lane (the reference's `live` analog, SURVEY §4).

Run with:  CALFKIT_TESTS_TPU=1 python -m pytest tests/test_tpu_live.py -m tpu -q

Deselected by default; each test is bounded and uses the persistent XLA
cache so reruns start hot.  Remote-tunnel caveats (from the repo's
environment notes): ``block_until_ready`` does not actually sync — every
timing forces an ``np.asarray`` fetch — and per-dispatch overhead is
~74-200 ms, so measurements amortize over many steps per dispatch.
"""

from __future__ import annotations

import os
import time

import pytest

pytestmark = pytest.mark.tpu

from tests._env import tpu_lane_enabled

requires_tpu_env = pytest.mark.skipif(
    not tpu_lane_enabled(),
    reason="set CALFKIT_TESTS_TPU=1 (conftest otherwise forces the CPU platform)",
)


def _chip():
    import jax

    devices = jax.devices()
    if devices[0].platform == "cpu":
        pytest.skip("no accelerator visible")
    return devices


@requires_tpu_env
class TestChipSmoke:
    def test_matmul_alive(self):
        import jax
        import jax.numpy as jnp
        import numpy as np

        _chip()
        x = jnp.ones((256, 256), jnp.bfloat16)
        y = jnp.float32(x @ x)
        assert float(np.asarray(y).sum()) == pytest.approx(256**3, rel=1e-3)

    async def test_engine_generates_on_chip(self):
        import numpy as np

        from calfkit_tpu.inference.config import RuntimeConfig, preset
        from calfkit_tpu.inference.engine import InferenceEngine

        _chip()
        engine = InferenceEngine(
            preset("debug"),
            RuntimeConfig(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=8),
        )
        await engine.start()
        out = [t async for t in engine.generate([1, 5, 9], max_new_tokens=16)]
        assert len(out) == 16
        again = [t async for t in engine.generate([1, 5, 9], max_new_tokens=16)]
        assert again == out  # greedy determinism on the accelerator
        await engine.stop()

    async def test_paged_matches_dense_on_chip(self):
        from calfkit_tpu.inference.config import RuntimeConfig, preset
        from calfkit_tpu.inference.engine import InferenceEngine

        _chip()
        kw = dict(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                  decode_steps_per_dispatch=8, page_size=16)
        dense = InferenceEngine(preset("debug"), RuntimeConfig(**kw), seed=3)
        paged = InferenceEngine(
            preset("debug"), RuntimeConfig(kv_layout="paged", **kw), seed=3
        )
        await dense.start()
        await paged.start()
        prompt = list(range(2, 30))
        want = [t async for t in dense.generate(prompt, max_new_tokens=16)]
        got = [t async for t in paged.generate(prompt, max_new_tokens=16)]
        assert got == want
        await dense.stop()
        await paged.stop()

    def test_pallas_decode_kernel_on_chip(self):
        """The dense Pallas kernel compiles + matches XLA on hardware, and
        its per-call time is recorded (the profile that decides 'auto')."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from calfkit_tpu.inference.model import _merged_decode_attention
        from calfkit_tpu.inference.pallas_attention import (
            merged_decode_attention_pallas,
        )

        _chip()
        B, K, G, hd, W, T = 8, 4, 8, 64, 1024, 8
        ks = jax.random.split(jax.random.key(11), 5)
        q = jax.random.normal(ks[0], (B, 1, K * G, hd), jnp.bfloat16)
        kc = jax.random.normal(ks[1], (B, K, W, hd), jnp.bfloat16)
        vc = jax.random.normal(ks[2], (B, K, W, hd), jnp.bfloat16)
        rk = jax.random.normal(ks[3], (T, B, K, hd), jnp.bfloat16)
        rv = jax.random.normal(ks[4], (T, B, K, hd), jnp.bfloat16)
        lens = jnp.full((B,), W - 7, jnp.int32)
        t = jnp.int32(3)

        ref = _merged_decode_attention(q, kc, vc, rk, rv, lens, t)
        out = merged_decode_attention_pallas(q, kc, vc, rk, rv, lens, t)
        np.testing.assert_allclose(
            np.asarray(jnp.float32(ref)), np.asarray(jnp.float32(out)),
            atol=2e-2, rtol=2e-2,
        )

        def timed(fn, n=20):
            np.asarray(jnp.float32(fn()).sum())  # warm
            start = time.perf_counter()
            for _ in range(n):
                np.asarray(jnp.float32(fn()).sum())  # forced fetch per call
            return (time.perf_counter() - start) / n * 1000.0

        xla_ms = timed(lambda: _merged_decode_attention(q, kc, vc, rk, rv, lens, t))
        pallas_ms = timed(
            lambda: merged_decode_attention_pallas(q, kc, vc, rk, rv, lens, t)
        )
        print(f"\ndecode attention B={B} W={W}: xla {xla_ms:.2f} ms/call, "
              f"pallas {pallas_ms:.2f} ms/call")


@requires_tpu_env
class TestRound4FeaturesOnChip:
    """Round-4 features under real hardware: the kafka-wire mesh carrying
    a chip-backed engine, the artifact-driven attention auto-flip, and
    the long-context sp lane on the accelerator."""

    async def test_agent_on_chip_over_kafka_wire(self):
        """client → kafkad (real Kafka wire) → worker → engine ON CHIP →
        streamed reply: the full production shape, all native pieces."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.inference import JaxLocalModelClient
        from calfkit_tpu.inference.config import RuntimeConfig, preset
        from calfkit_tpu.mesh.kafka_wire import (
            KafkaWireMesh,
            find_kafkad,
            spawn_kafkad,
        )
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        _chip()
        if find_kafkad() is None:
            pytest.skip("kafkad not built")
        proc = spawn_kafkad(0)
        try:
            mesh = KafkaWireMesh(f"127.0.0.1:{proc.kafkad_port}")
            client_mesh = KafkaWireMesh(f"127.0.0.1:{proc.kafkad_port}")
            await client_mesh.start()
            model = JaxLocalModelClient(
                config=preset("debug"),
                runtime=RuntimeConfig(
                    max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                    decode_steps_per_dispatch=8,
                ),
                max_new_tokens=12,
            )
            agent = Agent("chip_kafka_agent", model=model)
            async with Worker([agent], mesh=mesh, owns_transport=True):
                client = Client.connect(client_mesh)
                result = await client.agent("chip_kafka_agent").execute(
                    "hello from the wire", timeout=600
                )
                assert isinstance(result.output, str)
                await client.close()
            await client_mesh.stop()
            await model.stop()
        finally:
            proc.terminate()
            proc.wait(timeout=5)

    async def test_attn_auto_flip_serves_on_chip(self, tmp_path, monkeypatch):
        """A TPU-platform profile artifact flips `auto` to pallas for the
        decode path and the engine still serves correct greedy tokens —
        the full auto-resolution pipeline exercised on hardware."""
        import json

        import jax

        from calfkit_tpu.inference.config import RuntimeConfig, preset
        from calfkit_tpu.inference.engine import InferenceEngine

        _chip()
        platform = jax.devices()[0].platform
        kw = dict(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                  decode_steps_per_dispatch=8)
        # baseline: explicit XLA
        monkeypatch.setenv("CALFKIT_ATTN_PROFILE", "/nonexistent.json")
        xla_engine = InferenceEngine(
            preset("debug"), RuntimeConfig(attention_impl="xla", **kw), seed=3
        )
        await xla_engine.start()
        prompt = list(range(3, 40))
        want = [t async for t in xla_engine.generate(prompt, max_new_tokens=12)]
        await xla_engine.stop()
        # artifact-resolved: auto -> pallas for decode on this platform
        artifact = tmp_path / "attn.json"
        artifact.write_text(json.dumps({
            "platform": platform, "winners": {"decode": "pallas"},
        }))
        monkeypatch.setenv("CALFKIT_ATTN_PROFILE", str(artifact))
        auto_engine = InferenceEngine(preset("debug"), RuntimeConfig(**kw), seed=3)
        assert auto_engine._resolved_attn_impl("decode") == "pallas"
        await auto_engine.start()
        got = [t async for t in auto_engine.generate(prompt, max_new_tokens=12)]
        await auto_engine.stop()
        assert got == want

    async def test_long_context_sp_lane_on_chip(self):
        """A prompt past max_seq_len rides the ring-prefill lane on the
        accelerator and decodes greedily."""
        from calfkit_tpu.inference.config import RuntimeConfig, preset
        from calfkit_tpu.inference.engine import InferenceEngine

        _chip()
        engine = InferenceEngine(
            preset("debug"),
            RuntimeConfig(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4, long_context=True,
                          long_new_cap=8),
        )
        await engine.start()
        prompt = [(7 * i + 3) % 500 for i in range(200)]  # > max_seq_len
        out = [t async for t in engine.generate(prompt, max_new_tokens=6)]
        assert len(out) == 6
        assert engine.stats.long_requests == 1
        await engine.stop()

    async def test_int4_engine_on_chip(self):
        """int4 packed weights (r5): unpack + group-scale dequant compiles
        and serves deterministically on the accelerator, and matches the
        same engine's tokens across runs."""
        from calfkit_tpu.inference.config import RuntimeConfig, preset
        from calfkit_tpu.inference.engine import InferenceEngine

        _chip()
        engine = InferenceEngine(
            preset("debug"),
            RuntimeConfig(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4, quantization="int4",
                          kv_layout="paged", page_size=16, num_kv_pages=33),
            seed=11,
        )
        await engine.start()
        prompt = [3, 141, 59, 26]
        out = [t async for t in engine.generate(prompt, max_new_tokens=12)]
        again = [t async for t in engine.generate(prompt, max_new_tokens=12)]
        await engine.stop()
        assert len(out) == 12
        assert again == out
