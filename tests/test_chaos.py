"""Chaos scenarios (ISSUE 5): deterministic, scripted failure drills.

Each scenario injects an exact failure through the harness seams in
``tests/_chaos.py`` (engine ``_chaos`` hook, ``InMemoryMesh.chaos``
publish hook, the virtual deadline clock) and asserts the THREE
robustness invariants end to end:

1. failures surface as TYPED faults/exceptions (never silent hangs);
2. engine resources — slots, pages, shared-prefix refs — free within a
   BOUNDED number of ticks of the failure;
3. the flight recorder's timeline stays parseable and records the
   decision sequence (CANCEL/EXPIRE/SHED → frees, FAULT at a crash).

Catalog: caller-timeout storm (100 scripted runs), 2x admission
oversubscription, mid-stream engine fault, broker drop during return,
expired-on-arrival at a hop, engine deadline reap (queued AND active),
worker drain + bounded retry, and the max_out_blocks delivery stall.
"""

import asyncio
import threading
import time

import pytest

jax = pytest.importorskip("jax")
jax.config.update("jax_platforms", "cpu")
import jax.numpy as jnp  # noqa: E402

from calfkit_tpu import cancellation, leases, protocol  # noqa: E402
from calfkit_tpu.client import Client  # noqa: E402
from calfkit_tpu.client.caller import RetryPolicy  # noqa: E402
from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.exceptions import (  # noqa: E402
    ClientTimeoutError,
    DeadlineExceededError,
    EngineOverloadedError,
    NodeFaultError,
    RunOrphanedError,
    exception_for,
)
from calfkit_tpu.fleet import FleetRouter  # noqa: E402
from calfkit_tpu.inference import model as M  # noqa: E402
from calfkit_tpu.inference.client import JaxLocalModelClient  # noqa: E402
from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402
from calfkit_tpu.models.error_report import FaultTypes  # noqa: E402
from calfkit_tpu.nodes import Agent  # noqa: E402
from calfkit_tpu.observability import flightrec  # noqa: E402
from calfkit_tpu.worker import Worker  # noqa: E402

from tests._chaos import (  # noqa: E402
    BrokerChaos,
    ChaosScript,
    FleetTopology,
    ServingStubModel,
    assert_engine_drained,
    settle,
    virtual_clock,
)

CFG = preset("debug")


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _rt(**over):
    kw = dict(
        max_batch_size=4, max_seq_len=128, prefill_chunk=16,
        decode_steps_per_dispatch=4, page_size=16,
    )
    kw.update(over)
    return RuntimeConfig(**kw)


async def _collect(engine, prompt, n, **kw):
    """Consume a generate() stream to completion (or typed failure)."""
    return [t async for t in engine.generate(prompt, max_new_tokens=n, **kw)]


def _journal_events(engine):
    return flightrec.parse_dump(engine._journal.dump_lines())


def _drained(engine, total_free_pages=None):
    """Settle predicate mirroring assert_engine_drained: the decode thread
    nulls ``_pend`` BEFORE _free_deferred returns the slot/pages, so the
    free list (and page pool) must be part of the condition — settling on
    the queues alone observes a state that is consistent one tick later."""
    return (
        not engine._active
        and engine._pend is None
        and engine._inflight is None
        and not engine._admitting
        and not engine._pending
        and not engine._carry
        and len(engine._free) == engine.runtime.max_batch_size
        and (
            total_free_pages is None
            or engine._page_alloc is None
            or engine._page_alloc.free_pages == total_free_pages
        )
    )


class TestCallerTimeoutStorm:
    """The acceptance scenario: a dead caller's work actually stops."""

    async def test_storm_100_runs_zero_leaked_slots(self, params):
        """100 scripted runs: one active + one queued request per run,
        both cancelled through the mesh fan-out entry point
        (``cancellation.propagate_cancel`` — what a ``cancel`` record
        reaching ANY node in the process invokes).  After every run the
        engine must be byte-for-byte drained: all slots free, all pages
        back, nothing queued.  Every 20th run the flight-recorder
        timeline is checked to end CANCEL → … → SLOT_FREE."""
        runtime = _rt(
            max_batch_size=1, kv_layout="paged", overlap_dispatch=True,
            flightrec_events=1 << 15,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        total_free = engine._page_alloc.free_pages
        await engine.start()
        try:
            for run in range(100):
                corr_a = f"storm-{run}-active"
                corr_b = f"storm-{run}-queued"
                # gate the 2nd decode dispatch (ISSUE 11 flake fix): the
                # real decode thread races the cancel in host time, and
                # on a fast host a 64-token run could RETIRE before the
                # cancel landed — the scripted block pins every run
                # mid-generation until both cancels are flagged, so the
                # reap (not completion) is the only way out, every run
                gate = threading.Event()
                engine._chaos = ChaosScript().block_at("dispatch", 2, gate)
                try:
                    task_a = asyncio.create_task(
                        _collect(engine, [1, 2, 3 + run % 5], 64, corr=corr_a)
                    )
                    await settle(
                        lambda: engine._active,
                        message=f"run {run}: request never admitted",
                    )
                    task_b = asyncio.create_task(
                        _collect(engine, [7, 8], 64, corr=corr_b)
                    )
                    await settle(
                        lambda: len(engine._pending) + len(engine._carry)
                        == 1,
                        message=f"run {run}: second request never queued",
                    )
                    # the caller timed out: the mesh cancel fans out to
                    # every registered engine.  Both propagations run in
                    # ONE loop step — the queued entry cannot slip into
                    # admission between them.
                    flagged = cancellation.propagate_cancel(corr_a)
                    flagged += cancellation.propagate_cancel(corr_b)
                    assert flagged == 2, (
                        f"run {run}: fan-out flagged {flagged}"
                    )
                finally:
                    # ALWAYS release the pinned dispatch: a failed assert
                    # above must surface as the assert, not as a decode
                    # thread parked on gate.wait() hanging the whole run
                    gate.set()
                ticks = await settle(
                    lambda: _drained(engine, total_free),
                    message=f"run {run}: engine not drained after cancel",
                )
                assert ticks < 400
                assert_engine_drained(engine, total_free)
                # plain consumer-cancel ends the stream without error
                await task_a
                await task_b
                if run % 20 == 0:
                    events = _journal_events(engine)
                    tl = flightrec.timeline_events(events, corr_a)
                    names = [e["event"] for e in tl]
                    assert "CANCEL" in names, names
                    assert "SLOT_FREE" in names, names
                    assert names.index("CANCEL") < (
                        len(names) - 1 - names[::-1].index("SLOT_FREE")
                    ), f"CANCEL did not precede the final SLOT_FREE: {names}"
                    # the queued request never held a slot: its timeline
                    # is submit → cancel, nothing leaked to free
                    tl_b = flightrec.timeline_events(events, corr_b)
                    b_names = [e["event"] for e in tl_b]
                    assert "CANCEL" in b_names, b_names
            assert engine.stats.cancelled_requests == 200
            assert engine.stats.cancel_propagated == 200
            # the engine still serves after the storm
            assert len(await _collect(engine, [9], 8)) == 8
        finally:
            await engine.stop()

    async def test_client_timeout_cancels_engine_end_to_end(self, params):
        """client → mesh → worker node → engine: after a REAL
        ``ClientTimeoutError``, the cancel record crosses the mesh and
        the engine frees the request's slot and pages within bounded
        ticks.  The virtual clock is FROZEN so the engine-side deadline
        reaper cannot race the cancel — propagation is the only path
        that can reclaim the request."""
        runtime = _rt(
            max_batch_size=2, decode_steps_per_dispatch=1,
            kv_layout="paged", overlap_dispatch=True,
            flightrec_events=1 << 14,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        total_free = engine._page_alloc.free_pages
        # throttle decode (runs OFF the event loop, in to_thread) so the
        # generation deterministically outlives the client timeout on any
        # CPU: >= 10ms per emitted token vs a 0.3s budget for 100 tokens
        throttle = ChaosScript()

        def pace(point):
            throttle(point)
            if point == "dispatch":
                time.sleep(0.01)

        engine._chaos = pace
        model = JaxLocalModelClient(
            config=CFG, runtime=runtime, engine=engine, max_new_tokens=100
        )
        with virtual_clock():
            mesh = InMemoryMesh()
            agent = Agent("slow", model=model)
            async with Worker([agent], mesh=mesh, owns_transport=True):
                client = Client.connect(mesh)
                handle = await client.agent("slow").start(
                    "take your time", timeout=0.3
                )
                with pytest.raises(ClientTimeoutError):
                    await handle.result()
                # the timeout published the cancel; it must reach THIS
                # engine and free everything within bounded ticks
                await settle(
                    lambda: engine.stats.cancel_propagated >= 1,
                    message="mesh cancel never reached the engine",
                )
                await settle(
                    lambda: _drained(engine, total_free),
                    message="engine did not drain after the mesh cancel",
                )
                assert_engine_drained(engine, total_free)
                assert engine.stats.expired_requests == 0  # frozen clock
                events = _journal_events(engine)
                tl = flightrec.timeline_events(
                    events, handle.correlation_id
                )
                names = [e["event"] for e in tl]
                assert "CANCEL" in names, names
                await client.close()


class TestOversubscription:
    async def test_2x_oversubscription_sheds_typed(self, params):
        """2x the engine's admission capacity arrives at once: the
        excess is refused with a typed, attributed
        ``EngineOverloadedError`` at submit (no device work), the
        admitted requests complete in full, and the journal carries one
        SHED per refusal."""
        runtime = _rt(
            max_batch_size=2, max_pending=2, overlap_dispatch=True,
            flightrec_events=1 << 12,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            results = await asyncio.gather(
                *[
                    _collect(engine, [1 + i], 8, corr=f"over-{i}")
                    for i in range(8)
                ],
                return_exceptions=True,
            )
            shed = [r for r in results if isinstance(r, EngineOverloadedError)]
            served = [r for r in results if isinstance(r, list)]
            assert len(shed) + len(served) == 8
            assert shed, "2x oversubscription produced no sheds"
            assert served, "oversubscription shed everything"
            for exc in shed:
                assert exc.lane == "short"
                assert exc.limit == 2
                assert exc.pending >= 2
            for stream in served:
                assert len(stream) == 8, "an admitted request was starved"
            assert engine.stats.shed_requests == len(shed)
            sheds = [
                e for e in _journal_events(engine) if e["event"] == "SHED"
            ]
            assert len(sheds) == len(shed)
            # a shed is O(1) bookkeeping: the engine serves on
            assert len(await _collect(engine, [9], 8)) == 8
        finally:
            await engine.stop()

    async def test_shed_keeps_typed_code_across_the_mesh(self, params):
        """An engine shed crossing the agent's model-call wrap
        (``engine/turn.py``) must keep its ``mesh.overloaded`` code —
        not flatten into ``mesh.model_error`` — or caller-side retry
        can never classify it (regression: the wrap predates the
        authoritative error-type table)."""
        runtime = _rt(
            max_batch_size=1, max_pending=1, decode_steps_per_dispatch=1
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        model = JaxLocalModelClient(
            config=CFG, runtime=runtime, engine=engine, max_new_tokens=32
        )
        mesh = InMemoryMesh()
        async with Worker(
            [Agent("svc", model=model)], mesh=mesh, owns_transport=True
        ):
            client = Client.connect(mesh)
            results = await asyncio.gather(
                *[
                    client.agent("svc").execute(f"p{i}", timeout=120)
                    for i in range(6)
                ],
                return_exceptions=True,
            )
            served = [r for r in results if not isinstance(r, BaseException)]
            faults = [r for r in results if isinstance(r, BaseException)]
            assert served, "oversubscription shed everything"
            assert faults, "2x oversubscription never shed over the mesh"
            for exc in faults:
                assert isinstance(exc, NodeFaultError), repr(exc)
                assert exc.report.error_type == FaultTypes.OVERLOADED, (
                    exc.report.error_type
                )
                assert RetryPolicy.retriable(exc)
            assert engine.stats.shed_requests == len(faults)
            await client.close()
        await engine.stop()


class TestMultiTenantQos:
    """ISSUE 20 chaos drills: the rate-limit admission gate and the
    priority-ordered shed, each with the three robustness invariants
    (typed faults, bounded resource free, auditable decision trail)."""

    async def test_rate_limited_tenant_storm_typed_and_drained(self, params):
        """A single tenant storms past its admission budget: the excess
        is refused at the NODE KERNEL with the typed RETRIABLE
        ``mesh.rate_limited`` fault (carrying tenant id + retry hint),
        the admitted calls complete in full, and the engine drains with
        zero leaked slots or pages — a refused call never touched the
        engine at all."""
        from calfkit_tpu.qos import TenantRateLimiter

        runtime = _rt(max_batch_size=4, max_pending=8)
        engine = InferenceEngine(CFG, runtime, params=params)
        model = JaxLocalModelClient(
            config=CFG, runtime=runtime, engine=engine, max_new_tokens=8
        )
        mesh = InMemoryMesh()
        # negligible refill over the test's wall time: exactly the burst
        # (2 calls) is admitted, everything after is refused
        limiter = TenantRateLimiter(rate_per_s=0.0001, burst=2)
        async with Worker(
            [Agent("svc", model=model)], mesh=mesh, owns_transport=True,
            qos=limiter,
        ):
            client = Client.connect(mesh)
            results = await asyncio.gather(
                *[
                    client.agent("svc").execute(f"p{i}", timeout=120)
                    for i in range(6)
                ],
                return_exceptions=True,
            )
            served = [r for r in results if not isinstance(r, BaseException)]
            faults = [r for r in results if isinstance(r, BaseException)]
            assert len(served) == 2, "burst admitted more than its budget"
            assert len(faults) == 4, "storm excess was not refused"
            for exc in faults:
                assert isinstance(exc, NodeFaultError), repr(exc)
                assert exc.report.error_type == FaultTypes.RATE_LIMITED, (
                    exc.report.error_type
                )
                # the budget refills on a known schedule: backoff-and-
                # retry is the right caller response, so the fault MUST
                # classify retriable
                assert RetryPolicy.retriable(exc)
                assert exc.report.data.get("tenant_id") == client.client_id
                assert float(exc.report.data["retry_after_s"]) > 0.0
            # a refused call never reached the engine: no shed, no
            # journal entry, and the engine drains clean
            assert engine.stats.shed_requests == 0
            await settle(
                lambda: _drained(engine), message="engine never drained"
            )
            assert_engine_drained(engine)
            await client.close()
        await engine.stop()

    async def test_interactive_preempts_queued_batch_never_reverse(
        self, params
    ):
        """The shed-order law, end to end at the engine: with the short
        lane full of batch work, arriving interactive submits evict
        QUEUED batch requests (typed retriable EngineOverloadedError
        with the full lane/pending/limit detail) and run in their
        place.  Zero interactive sheds while any batch request was
        sheddable — and the journal carries one SHED per eviction."""
        runtime = _rt(
            max_batch_size=2, max_pending=2, overlap_dispatch=True,
            flightrec_events=1 << 12,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        await engine.start()
        try:
            batch = [
                asyncio.ensure_future(
                    _collect(
                        engine, [1 + i], 32,
                        corr=f"bulk-{i}", priority="batch",
                    )
                )
                for i in range(2)
            ]
            # stage the backlog: let the first pair claim the slots
            # BEFORE queueing the next pair, or all four race into the
            # queue and bounded admission sheds the tail at submit
            await settle(
                lambda: len(engine._active) == 2,
                message="batch pair never went active",
            )
            batch += [
                asyncio.ensure_future(
                    _collect(
                        engine, [3 + i], 32,
                        corr=f"bulk-{2 + i}", priority="batch",
                    )
                )
                for i in range(2)
            ]
            # 2 batch active, 2 batch queued — the victim pool
            await settle(
                lambda: len(engine._pending) == 2,
                message="batch backlog never queued",
            )
            interactive = await asyncio.gather(
                *[
                    _collect(
                        engine, [9 + i], 8,
                        corr=f"chat-{i}", priority="interactive",
                    )
                    for i in range(2)
                ],
                return_exceptions=True,
            )
            batch_results = await asyncio.gather(
                *batch, return_exceptions=True
            )
            # every interactive request completed — none were shed
            for stream in interactive:
                assert isinstance(stream, list), repr(stream)
                assert len(stream) == 8
            victims = [
                r for r in batch_results
                if isinstance(r, EngineOverloadedError)
            ]
            assert len(victims) == 2, (
                "each interactive arrival must evict one queued batch "
                f"request, got {batch_results!r}"
            )
            for exc in victims:
                # the eviction carries the SAME typed detail a
                # shed-at-submit would (the drive-by uniformity law)
                assert exc.lane == "short"
                assert exc.limit == 2
                assert exc.pending >= 2
                # crossing the mesh this types as mesh.overloaded, which
                # is retriable — the caller's RetryPolicy re-drives the
                # preempted batch work
                from calfkit_tpu.exceptions import (
                    FAULT_TYPE_BY_EXCEPTION,
                    RETRIABLE_FAULT_TYPES,
                )

                assert (
                    FAULT_TYPE_BY_EXCEPTION[type(exc)]
                    in RETRIABLE_FAULT_TYPES
                )
            assert engine.stats.shed_requests == 2
            assert engine.stats.batch_shed == 2
            assert engine.stats.interactive_shed == 0, (
                "an interactive request was shed while batch work was "
                "sheddable — the shed-order law is broken"
            )
            sheds = [
                e for e in _journal_events(engine) if e["event"] == "SHED"
            ]
            assert len(sheds) == 2
            assert {e["corr"] for e in sheds} <= {f"bulk-{i}" for i in range(4)}
            await settle(
                lambda: _drained(engine), message="engine never drained"
            )
            assert_engine_drained(engine)
        finally:
            await engine.stop()


class TestMidStreamFault:
    async def test_injected_dispatch_fault_dumps_and_terminates(
        self, params, tmp_path, monkeypatch
    ):
        """A fault on the 3rd decode dispatch: consumers' streams
        terminate (no hang), the scheduler stops, and the fault dump is
        parseable JSONL whose final event is FAULT."""
        monkeypatch.setenv("CALFKIT_FLIGHTREC_DIR", str(tmp_path))
        runtime = _rt(overlap_dispatch=True)
        engine = InferenceEngine(CFG, runtime, params=params)
        engine._chaos = ChaosScript().fail_at(
            "dispatch", 3, RuntimeError("injected mid-stream chaos fault")
        )
        await engine.start()
        got = await _collect(engine, [1, 2, 3], 64, corr="chaos-fault")
        assert len(got) < 64, "the injected fault never fired"
        await settle(lambda: not engine._running)
        dumps = sorted(tmp_path.glob("*.jsonl"))
        assert dumps, "no fault dump was written"
        events = flightrec.parse_dump(
            dumps[-1].read_text().splitlines()
        )
        assert events, "fault dump is not parseable"
        assert events[-1]["event"] == "FAULT"
        assert "chaos fault" in events[-1].get("note", "")
        assert any(e["event"] == "DISPATCH_LAUNCH" for e in events)
        await engine.stop()  # teardown after a crash is clean


class TestBrokerDropDuringReturn:
    async def test_dropped_return_times_out_and_publishes_cancel(self):
        """The broker loses the agent's return record: the caller gets a
        typed ``ClientTimeoutError`` (bounded wait, no hang) and its
        timeout publishes a ``cancel`` record that reaches in-process
        cancellation targets through the node."""
        mesh = InMemoryMesh()
        chaos = BrokerChaos().drop(kind="return")
        mesh.chaos = chaos
        seen_cancels: list[str] = []

        class _Target:
            def cancel_correlation(self, corr: str) -> int:
                seen_cancels.append(corr)
                return 0

        target = _Target()
        cancellation.register_cancel_target(target)
        agent = Agent(
            "echo",
            model=TestModelClient(custom_output_text="ok", call_tools="none"),
        )
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("echo").start("hi", timeout=0.4)
            with pytest.raises(ClientTimeoutError):
                await handle.result()
            assert [kind for _, kind in chaos.dropped] == ["return"]
            # the publish is fire-and-forget off the timeout rail: settle
            # on it rather than asserting synchronously after the raise
            await settle(
                lambda: chaos.kinds_seen("cancel") >= 1,
                message="the timeout did not publish a mesh cancel",
            )
            await settle(
                lambda: handle.correlation_id in seen_cancels,
                message="the cancel record never fanned out at the node",
            )
            await client.close()


class TestConsumerCancelShortCircuit:
    async def test_cancel_record_never_reaches_consumer_fn(self):
        """A ``cancel``-kind record on a consumer's topic is a control
        record: it must fan out to cancellation targets — never run the
        user's fn, which the dispatcher's EXPRESS path would otherwise
        execute inline on the intake pull task."""
        from calfkit_tpu.mesh.transport import Record
        from calfkit_tpu.nodes import ConsumerNode

        seen_cancels: list[str] = []

        class _Target:
            def cancel_correlation(self, corr: str) -> int:
                seen_cancels.append(corr)
                return 1

        target = _Target()
        cancellation.register_cancel_target(target)
        calls: list = []
        node = ConsumerNode(
            lambda ctx: calls.append(ctx), name="watch", topics=["t.obs"]
        )
        await node._handle_delivery(
            Record(
                topic="t.obs",
                value=b"",
                key=b"task-1",
                headers={
                    protocol.HDR_KIND: "cancel",
                    protocol.HDR_CORRELATION: "corr-express",
                    protocol.HDR_TASK: "task-1",
                },
            )
        )
        assert calls == [], "consumer fn ran for a control record"
        assert seen_cancels == ["corr-express"]


class TestCancelTombstone:
    async def test_cancelled_before_delivery_faults_fast(self):
        """A cancel that lands while the call record is still in flight
        (queued behind a busy lane, on the wire) leaves a tombstone; the
        admission gate hits it and faults typed ``mesh.cancelled``
        instead of executing a full run for a caller that left."""
        mesh = InMemoryMesh()
        chaos = BrokerChaos()
        mesh.chaos = chaos
        ran: list[str] = []

        def _tap(topic: str, headers: dict) -> None:
            # the cancel "overtakes" the call deterministically: the
            # tombstone is recorded the instant the call crosses the
            # broker, before its delivery executes
            if headers.get(protocol.HDR_KIND) == "call" and "svc" in topic:
                cancellation.propagate_cancel(
                    headers.get(protocol.HDR_CORRELATION, "")
                )

        chaos.on_publish = _tap
        agent = Agent(
            "svc",
            model=TestModelClient(custom_output_text="ok", call_tools="none"),
            before_node=[lambda ctx: ran.append(ctx.correlation_id) and None],
        )
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            with pytest.raises(NodeFaultError) as ei:
                await client.agent("svc").execute("x", timeout=5)
            assert ei.value.report.error_type == FaultTypes.CANCELLED
            # deliberate abandonment is NOT retriable
            assert not RetryPolicy.retriable(ei.value)
            assert ran == [], "agent body ran for a cancelled run"
            await client.close()


class TestCancelForwarding:
    async def test_cancel_follows_the_run_downstream(self):
        """The cancel record is re-published along the run's path: the
        agent's kernel remembers which topics it sent the run's calls to
        and forwards the cancel there — an engine in ANOTHER process is
        only reachable through its topic, never through the in-process
        registry.  Scripted: cancel lands while the tool executes; the
        tool's input topic must see a cancel record exactly once."""
        from calfkit_tpu.nodes import agent_tool

        mesh = InMemoryMesh()
        chaos = BrokerChaos()
        mesh.chaos = chaos
        started = asyncio.Event()
        release = asyncio.Event()

        @agent_tool
        async def probe(q: str) -> str:
            """Parks until released.

            Args:
                q: ignored.
            """
            started.set()
            await release.wait()
            return "done"

        agent = Agent(
            "svc", model=TestModelClient(), tools=[probe],
        )
        tool_topic = protocol.tool_input_topic("probe")
        async with Worker([agent, probe], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("svc").start("go")
            await asyncio.wait_for(started.wait(), 10)
            assert chaos.seen.count((tool_topic, "cancel")) == 0
            await handle.cancel()
            await settle(
                lambda: (tool_topic, "cancel") in chaos.seen,
                message="cancel was never forwarded to the tool's topic",
            )
            # idempotent: a duplicate cancel record forwards nothing
            # (the downstream entry was popped by the first)
            agent_topic = next(
                t for t, k in chaos.seen if k == "call" and "svc" in t
            )
            await mesh.publish(
                agent_topic,
                b"",
                key=b"dup",
                headers={
                    protocol.HDR_KIND: "cancel",
                    protocol.HDR_CORRELATION: handle.correlation_id,
                },
            )
            release.set()
            # the agent's final return proves its topic's pull advanced
            # past the duplicate cancel (same pull task, in order)
            await settle(
                lambda: chaos.kinds_seen("return") >= 2,
                message="run never settled after release",
            )
            assert chaos.seen.count((tool_topic, "cancel")) == 1
            await client.close()


class TestDeadlineExpiry:
    async def test_expired_on_arrival_faults_typed(self):
        """The clock jumps past the deadline while the call is on the
        wire (scripted at the broker): the receiving hop records a typed
        ``mesh.deadline_exceeded`` fault instead of executing."""
        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            chaos = BrokerChaos()

            def jump(topic, headers):
                if headers.get(protocol.HDR_KIND) == "call":
                    clock.advance(60)

            chaos.on_publish = jump
            mesh.chaos = chaos
            agent = Agent(
                "late",
                model=TestModelClient(
                    custom_output_text="never", call_tools="none"
                ),
            )
            async with Worker([agent], mesh=mesh, owns_transport=True):
                client = Client.connect(mesh)
                with pytest.raises(NodeFaultError) as ei:
                    await client.agent("late").execute("hi", timeout=30)
                assert (
                    ei.value.report.error_type
                    == FaultTypes.DEADLINE_EXCEEDED
                )
                # the wire code maps back to the canonical local type
                assert (
                    exception_for(FaultTypes.DEADLINE_EXCEEDED)
                    is DeadlineExceededError
                )
                await client.close()

    async def test_engine_reaps_expired_queued_and_active(self, params):
        """One active and one queued request, both deadlined: advancing
        the virtual clock expires BOTH through the cancellation path —
        typed ``DeadlineExceededError`` at each consumer, all resources
        freed, EXPIRE events journaled."""
        with virtual_clock() as clock:
            runtime = _rt(
                max_batch_size=1, kv_layout="paged", overlap_dispatch=True,
                flightrec_events=1 << 12,
            )
            engine = InferenceEngine(CFG, runtime, params=params)
            total_free = engine._page_alloc.free_pages
            await engine.start()
            try:
                task_a = asyncio.create_task(
                    _collect(
                        engine, [1, 2, 3], 64, corr="exp-active",
                        deadline=clock.now + 5,
                    )
                )
                await settle(lambda: engine._active)
                task_b = asyncio.create_task(
                    _collect(
                        engine, [4, 5], 64, corr="exp-queued",
                        deadline=clock.now + 5,
                    )
                )
                await settle(
                    lambda: len(engine._pending) + len(engine._carry) == 1
                )
                clock.advance(10)
                with pytest.raises(DeadlineExceededError):
                    await task_a
                with pytest.raises(DeadlineExceededError):
                    await task_b
                await settle(lambda: _drained(engine, total_free))
                assert_engine_drained(engine, total_free)
                assert engine.stats.expired_requests == 2
                expires = [
                    e for e in _journal_events(engine)
                    if e["event"] == "EXPIRE"
                ]
                assert len(expires) == 2
                # an expiry-driven reap is not a consumer cancel
                assert engine.stats.cancelled_requests == 0
                # un-deadlined work still serves
                assert len(await _collect(engine, [9], 8)) == 8
            finally:
                await engine.stop()

    async def test_expired_at_engine_admission(self, params):
        """An already-expired submit is refused before ANY device work."""
        with virtual_clock() as clock:
            engine = InferenceEngine(CFG, _rt(), params=params)
            await engine.start()
            try:
                with pytest.raises(DeadlineExceededError, match="expired"):
                    await _collect(
                        engine, [1, 2], 8, deadline=clock.now - 1
                    )
                assert engine.stats.expired_requests == 1
            finally:
                await engine.stop()


class TestWorkerDrain:
    async def test_drain_refuses_new_calls_typed_and_retriable(self):
        """Drain mode: readiness flips false, NEW calls fault with the
        typed, retriable ``mesh.overloaded`` code, and the caller-side
        bounded retry actually re-publishes (and stays bounded)."""
        mesh = InMemoryMesh()
        chaos = BrokerChaos()
        mesh.chaos = chaos
        agent = Agent(
            "svc",
            model=TestModelClient(custom_output_text="ok", call_tools="none"),
        )
        worker = Worker([agent], mesh=mesh, owns_transport=True)
        async with worker:
            client = Client.connect(mesh)
            result = await client.agent("svc").execute("a", timeout=5)
            assert result.output == "ok"
            assert worker.ready()[0] is True

            worker.drain()
            assert worker.ready()[0] is False
            assert worker.draining

            with pytest.raises(NodeFaultError) as ei:
                await client.agent("svc").execute("b", timeout=5)
            assert ei.value.report.error_type == FaultTypes.OVERLOADED
            assert RetryPolicy.retriable(ei.value)

            # bounded retry with backoff: exactly `attempts` publishes,
            # then the typed fault surfaces (still draining)
            calls_before = chaos.kinds_seen("call")
            with pytest.raises(NodeFaultError):
                await client.agent("svc").execute(
                    "c", timeout=5,
                    retry=RetryPolicy(attempts=3, base_delay=0.01),
                )
            assert chaos.kinds_seen("call") - calls_before == 3
            await client.close()


class TestDeliveryStall:
    async def test_stalled_consumer_is_stall_cancelled(self, params):
        """A consumer that stops draining accumulates at most
        ``max_out_blocks`` undrained blocks before the scheduler
        stall-cancels the request; resuming surfaces a typed
        ``EngineOverloadedError`` and nothing leaked."""
        runtime = _rt(
            max_out_blocks=2, kv_layout="paged", overlap_dispatch=True
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        total_free = engine._page_alloc.free_pages
        await engine.start()
        try:
            agen = engine.generate(
                [1, 2, 3], max_new_tokens=100, corr="stall"
            )
            first = await agen.__anext__()
            assert isinstance(first, int)
            # the consumer stalls; the engine keeps decoding until the
            # delivery bound trips the stall-cancel
            await settle(
                lambda: engine.stats.delivery_stalled >= 1,
                message="stall was never detected",
            )
            with pytest.raises(EngineOverloadedError, match="max_out_blocks"):
                async for _ in agen:
                    pass
            await settle(lambda: _drained(engine, total_free))
            assert_engine_drained(engine, total_free)
            # a healthy consumer is unaffected
            assert len(await _collect(engine, [9], 8)) == 8
        finally:
            await engine.stop()


class TestRaggedWaveCancellation:
    async def test_cancel_request_packed_into_mixed_wave(self, params):
        """ISSUE 6 chaos: cancel a request while its prefill chunk is
        riding a MIXED ragged dispatch (decode rows + its admission
        wave fused into one invocation).  The corpse must shed at
        activation, its co-wave survivor must stream in full, the
        decoding bystanders must be untouched, and no slot or page may
        leak — the unified lane keeps the bifurcated lane's cancel
        semantics."""
        runtime = _rt(
            kv_layout="paged", chunked_prefill=True, ragged_waves=True,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        total_free = engine._page_alloc.free_pages
        await engine.start()
        try:
            # two decoding bystanders keep the fused lane busy
            bystanders = [
                asyncio.create_task(_collect(engine, [1 + i], 24))
                for i in range(2)
            ]
            await settle(lambda: len(engine._active) == 2)
            # same bucket (48 → 3 chunks): both join one admission wave
            # that must be ABSORBED into the bystanders' decode dispatches
            doomed = asyncio.create_task(
                _collect(engine, list(range(1, 44)), 16, corr="doomed")
            )
            survivor = asyncio.create_task(
                _collect(engine, list(range(100, 143)), 16)
            )
            await settle(
                lambda: engine._inflight is not None
                and len(engine._inflight["wave"]) == 2
                and engine.stats.unified_dispatches >= 1,
                message="no mixed (decode+chunk) wave ever formed",
            )
            doomed.cancel()
            with pytest.raises(asyncio.CancelledError):
                await doomed
            assert len(await survivor) == 16
            assert [len(s) for s in await asyncio.gather(*bystanders)] == [
                24, 24,
            ]
            await settle(lambda: _drained(engine, total_free))
            assert_engine_drained(engine, total_free)
            assert engine.stats.prefill_absorbed_tokens > 0
            # the journal shows the fused lane ran and the cancel reaped
            names = {e["event"] for e in _journal_events(engine)}
            assert "RAGGED_WAVE" in names
            assert "CANCEL" in names
            # the lane still admits mixed waves afterwards
            assert len(await _collect(engine, list(range(1, 44)), 8)) == 8
        finally:
            await engine.stop()


class TestFleetChaos:
    """Multi-worker topologies (ISSUE 7): replica failover, drain
    handoff, and shed-retry storms run deterministically — fast real
    heartbeats, virtual-clock staleness, per-replica delivery ledgers,
    and the engine no-leak oracle where real engines serve."""

    @staticmethod
    def _engine_fleet(params, n, **rt_over):
        """n real engines wrapped as agent models (debug preset)."""
        engines, models = [], []
        for _ in range(n):
            runtime = _rt(**rt_over)
            engine = InferenceEngine(CFG, runtime, params=params)
            engines.append(engine)
            models.append(
                JaxLocalModelClient(
                    config=CFG, runtime=runtime, engine=engine,
                    max_new_tokens=24,
                )
            )
        return engines, models

    @staticmethod
    async def _eligible(router, n, message):
        """Boot adverts say ready=False by design (a booting worker
        must not draw traffic): wait for the first post-boot beat."""
        await router.start()
        await settle(
            lambda: len(router.registry.eligible("svc")) == n,
            message=message,
        )

    async def test_draining_replica_gets_zero_new_calls(self, params):
        """Drain one of two replicas mid-generation: the in-flight run
        completes ON the draining replica, every subsequent call lands
        on the other one (zero NEW deliveries to the drained worker),
        and both engines drain leak-free."""
        with virtual_clock():
            mesh = InMemoryMesh()
            engines, models = self._engine_fleet(params, 2)
            async with FleetTopology(mesh, models) as fleet:
                low = fleet.index_of_lowest_key()
                # pace the replica the first (depth-tied) pick lands on,
                # so its run is still decoding when the drain hits
                slow = ChaosScript()

                def pace(point):
                    slow(point)
                    if point == "dispatch":
                        time.sleep(0.02)

                engines[low]._chaos = pace
                router = FleetRouter(
                    mesh, "least-loaded",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(mesh, router=router)
                await self._eligible(router, 2, "fleet never became routable")

                inflight = asyncio.create_task(
                    client.agent("svc").execute("long haul", timeout=60)
                )
                await settle(
                    lambda: engines[low]._active,
                    message="the in-flight run never reached the engine",
                )
                assert fleet.calls_delivered(low) == 1

                fleet.workers[low].drain()
                assert fleet.workers[low].ready()[0] is False
                await settle(
                    lambda: [
                        r.instance_id
                        for r in router.registry.eligible("svc")
                    ] == [fleet.instance_id(1 - low)],
                    message="drain never reached the registry",
                )
                # the run is still in flight on the draining replica
                assert engines[low]._active, "paced run finished too early"

                for i in range(4):
                    result = await client.agent("svc").execute(
                        f"post-drain {i}", timeout=60
                    )
                    assert result.output
                # zero NEW calls on the drained replica; all four on the
                # survivor — and the in-flight run finished normally
                assert fleet.calls_delivered(low) == 1
                assert fleet.calls_delivered(1 - low) == 4
                assert (await inflight).output
                await settle(lambda: _drained(engines[low]))
                assert_engine_drained(engines[low])
                assert_engine_drained(engines[1 - low])
                assert engines[low].stats.shed_requests == 0
                await client.close()
            for engine in engines:
                await engine.stop()
            await mesh.stop()

    async def test_shed_retried_on_a_different_replica(self, params):
        """A prefix-affinity storm on one tightly-bounded home replica
        (capacity 2: one slot + max_pending 1): the overflow sheds
        typed, every shed is retried against the OTHER replica (the
        shed source is excluded from the retry's placement), every run
        ultimately succeeds, and the home replica's topic saw exactly
        the first attempts — a shed retry NEVER re-picks its shed
        source."""
        with virtual_clock():
            mesh = InMemoryMesh()
            chaos = BrokerChaos()
            mesh.chaos = chaos
            # asymmetric capacity, so the scenario is deterministic for
            # ANY shed count: replica 0 sheds its overflow, replica 1
            # has the headroom to absorb every retry without shedding
            engines, models = [], []
            for max_pending in (1, 8):
                runtime = _rt(
                    max_batch_size=1, max_pending=max_pending,
                    decode_steps_per_dispatch=1,
                )
                engine = InferenceEngine(CFG, runtime, params=params)
                engines.append(engine)
                models.append(
                    JaxLocalModelClient(
                        config=CFG, runtime=runtime, engine=engine,
                        max_new_tokens=24,
                    )
                )
            home = 0
            async with FleetTopology(mesh, models) as fleet:
                router = FleetRouter(
                    mesh, "prefix-affinity",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(mesh, router=router)
                await self._eligible(router, 2, "fleet never became routable")

                # find a session prompt (>= one 64-char affinity page,
                # small enough to fit max_seq_len 128 with scaffolding)
                # whose rendezvous home is the BOUNDED replica — the
                # search is over session ids, exactly how real sessions
                # scatter across homes
                from calfkit_tpu.fleet import affinity_key_for

                candidates = [
                    f"session-{i:02d}: shared preamble " * 3
                    for i in range(64)
                ]
                assert all(
                    affinity_key_for(p) is not None for p in candidates
                ), "candidate prompts are below one affinity page"
                prompt = next(
                    p
                    for p in candidates
                    if (picked := router.select("svc", prompt_text=p))
                    is not None
                    and picked.instance_id == fleet.instance_id(home)
                )
                # pace the home so the storm overlaps one generation
                slow = ChaosScript()

                def pace(point):
                    slow(point)
                    if point == "dispatch":
                        time.sleep(0.01)

                engines[home]._chaos = pace

                results = await asyncio.gather(
                    *[
                        client.agent("svc").execute(
                            prompt, timeout=60,
                            retry=RetryPolicy(attempts=3, base_delay=0.01),
                        )
                        for _ in range(4)
                    ]
                )
                assert all(r.output for r in results)
                sheds = engines[home].stats.shed_requests
                assert sheds >= 1, "the storm never overflowed the home"
                assert engines[1 - home].stats.shed_requests == 0
                home_topic = fleet.agents[home].replica_topic()
                other_topic = fleet.agents[1 - home].replica_topic()
                home_calls = chaos.seen.count((home_topic, "call"))
                other_calls = chaos.seen.count((other_topic, "call"))
                # affinity homed all four first attempts; every shed
                # retried on the OTHER replica and nowhere else
                assert home_calls == 4, (home_calls, other_calls, sheds)
                assert other_calls == sheds, (home_calls, other_calls, sheds)
                assert fleet.calls_delivered(1 - home) == sheds
                await settle(lambda: _drained(engines[home]))
                await settle(lambda: _drained(engines[1 - home]))
                assert_engine_drained(engines[home])
                assert_engine_drained(engines[1 - home])
                await client.close()
            for engine in engines:
                await engine.stop()
            await mesh.stop()

    async def test_stale_heartbeat_excluded_until_readvertise(self):
        """A replica whose heartbeat loop wedges keeps serving nothing
        NEW once the virtual clock passes stale_after; the moment it
        re-advertises (fresh stamp) it is routable again.  Pure routing
        scenario — scripted stub models, ledgers as the oracle."""
        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            models = [ServingStubModel(text=f"r{i}") for i in range(2)]
            async with FleetTopology(mesh, models) as fleet:
                router = FleetRouter(
                    mesh, "least-loaded",
                    stale_after=fleet.config.stale_after,
                )
                client = Client.connect(mesh, router=router)
                await self._eligible(router, 2, "fleet never became routable")

                low = fleet.index_of_lowest_key()
                # depth-tied least-loaded picks the lowest key: pin it
                result = await client.agent("svc").execute("warm", timeout=10)
                assert result.output == f"r{low}"
                assert fleet.calls_delivered(low) == 1

                # the lowest-key replica's heartbeat wedges; time passes
                fleet.wedge_heartbeat(low)
                clock.advance(fleet.config.stale_after + 1)
                await settle(
                    lambda: [
                        r.instance_id
                        for r in router.registry.eligible("svc")
                    ] == [fleet.instance_id(1 - low)],
                    message="the wedged replica never went stale "
                    "(is the survivor re-stamping?)",
                )
                for i in range(3):
                    result = await client.agent("svc").execute(
                        f"while-stale {i}", timeout=10
                    )
                    assert result.output == f"r{1 - low}"
                assert fleet.calls_delivered(low) == 1  # nothing new

                # recovery: one fresh advert restores eligibility and
                # the depth-tied pick returns to the lowest key
                await fleet.resume_heartbeat(low)
                await settle(
                    lambda: len(router.registry.eligible("svc")) == 2,
                    message="re-advertising did not restore eligibility",
                )
                result = await client.agent("svc").execute("back", timeout=10)
                assert result.output == f"r{low}"
                assert fleet.calls_delivered(low) == 2
                await client.close()
            await mesh.stop()


class TestFailoverChaos:
    """In-flight failure recovery (ISSUE 9): hard replica death driven
    through FleetTopology's process-death seam (kill = stop consuming +
    stop heartbeating + publishes vanish, no drain), recovery supervised
    by the gateway's FailoverPolicy under the virtual clock."""

    @staticmethod
    def _failover_client(mesh, fleet, **policy_over):
        from calfkit_tpu.fleet import FailoverPolicy, FleetRouter

        kw = dict(probe_interval=0.02, max_failovers=2)
        kw.update(policy_over)
        router = FleetRouter(
            mesh, "least-loaded", stale_after=fleet.config.stale_after
        )
        client = Client.connect(
            mesh, router=router, failover=FailoverPolicy(**kw)
        )
        return router, client

    async def test_kill_mid_stream_completes_contiguous(self):
        """THE acceptance scenario: hard-kill a replica mid-stream.  The
        request completes on the survivor, the caller observes ONE
        contiguous stream (concatenated token deltas == the terminal
        output: no duplicated, no missing text), and — after the zombie
        resumes — the old correlation is tombstoned so the orphaned run
        never executes twice.  StreamingStubModel pins exactly how much
        text the caller saw before the death."""
        from calfkit_tpu.models.node_result import InvocationResult
        from tests._chaos import StreamingStubModel

        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            chaos = BrokerChaos()
            mesh.chaos = chaos
            models = [
                StreamingStubModel(text="alpha beta gamma delta")
                for _ in range(2)
            ]
            async with FleetTopology(
                mesh, models, agent_kwargs={"stream_tokens": True}
            ) as fleet:
                low = fleet.index_of_lowest_key()
                models[1 - low].release.set()  # only the victim pauses
                victim_topic = fleet.agents[low].replica_topic()
                victim_corrs: list = []

                def note(topic, headers):
                    if (
                        topic == victim_topic
                        and headers.get(protocol.HDR_KIND) == "call"
                    ):
                        victim_corrs.append(
                            headers.get(protocol.HDR_CORRELATION)
                        )

                chaos.on_publish = note
                router, client = self._failover_client(mesh, fleet)
                await TestFleetChaos._eligible(
                    router, 2, "fleet never became routable"
                )

                token_texts: list = []
                result = None
                killed = False
                async for item in client.agent("svc").stream(
                    "tell me a story", timeout=60
                ):
                    if isinstance(item, InvocationResult):
                        result = item
                        continue
                    if getattr(item.step, "kind", "") != "token":
                        continue
                    token_texts.append(item.step.text)
                    if not killed:
                        # the first delivered tokens ("alpha ") are on
                        # the wire; the replica dies NOW, mid-stream
                        killed = True
                        fleet.kill(low)
                        clock.advance(fleet.config.stale_after + 1)
                assert killed, "the stream never delivered a first token"
                assert result is not None
                assert result.output == "alpha beta gamma delta"
                # contiguity law: what streamed is exactly the answer —
                # no duplicated "alpha ", no missing words
                assert "".join(token_texts) == result.output
                # the call was placed once on each replica (original +
                # failover re-dispatch, marked for the advert), and the
                # orphan was cancelled toward the dead replica's topic
                assert fleet.calls_delivered(low) == 1
                assert fleet.calls_delivered(1 - low) == 1
                assert len(victim_corrs) == 1
                assert (victim_topic, "cancel") in chaos.seen
                assert fleet.agents[1 - low]._failover_requests == 1
                # zombie returns: the buffered cancel replays FIRST
                # (express law) and tombstones the orphaned correlation
                models[low].release.set()
                await fleet.resume(low)
                await settle(
                    lambda: cancellation.was_cancelled(victim_corrs[0]),
                    message="the zombie never tombstoned the orphan",
                )
                await client.close()
            await mesh.stop()

    async def test_kill_mid_run_real_engines_no_leaks(self, params):
        """The engine-oracle half of the acceptance: hard-kill a replica
        while its REAL engine is decoding the run.  The survivor serves
        the re-dispatch, the caller gets a result well inside its
        deadline, and BOTH engines — including the corpse, whose
        in-flight compute keeps burning into dropped publishes — drain
        with zero leaked slots or pages."""
        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            chaos = BrokerChaos()
            mesh.chaos = chaos
            engines, models = TestFleetChaos._engine_fleet(params, 2)
            async with FleetTopology(mesh, models) as fleet:
                low = fleet.index_of_lowest_key()
                # pace the victim so the kill lands mid-generation
                slow = ChaosScript()

                def pace(point):
                    slow(point)
                    if point == "dispatch":
                        time.sleep(0.02)

                engines[low]._chaos = pace
                router, client = self._failover_client(mesh, fleet)
                await TestFleetChaos._eligible(
                    router, 2, "fleet never became routable"
                )
                call = asyncio.create_task(
                    client.agent("svc").execute("long haul", timeout=60)
                )
                await settle(
                    lambda: engines[low]._active,
                    message="the run never reached the victim engine",
                )
                fleet.kill(low)
                clock.advance(fleet.config.stale_after + 1)
                result = await call
                assert result.output
                assert fleet.calls_delivered(low) == 1
                assert fleet.calls_delivered(1 - low) == 1
                victim_topic = fleet.agents[low].replica_topic()
                assert (victim_topic, "cancel") in chaos.seen
                # the corpse finishes its abandoned decode into dropped
                # publishes and must STILL free everything
                await settle(lambda: _drained(engines[low]))
                await settle(lambda: _drained(engines[1 - low]))
                assert_engine_drained(engines[low])
                assert_engine_drained(engines[1 - low])
                await client.close()
            for engine in engines:
                await engine.stop()
            await mesh.stop()

    async def test_kill_mid_prefill_reissues_whole_call(self, params):
        """Kill the placed replica before ANY token was delivered (the
        mid-prefill shape): execute() re-issues the whole call on the
        survivor under the remaining deadline and returns its answer."""
        del params

        class BlockedStubModel(ServingStubModel):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.release = asyncio.Event()

            async def request(self, messages, settings=None, params=None):
                await self.release.wait()
                return await super().request(messages, settings, params)

        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            chaos = BrokerChaos()
            mesh.chaos = chaos
            models = [BlockedStubModel(text=f"r{i}") for i in range(2)]
            async with FleetTopology(mesh, models) as fleet:
                low = fleet.index_of_lowest_key()
                models[1 - low].release.set()  # only the victim blocks
                router, client = self._failover_client(mesh, fleet)
                await TestFleetChaos._eligible(
                    router, 2, "fleet never became routable"
                )
                call = asyncio.create_task(
                    client.agent("svc").execute("prefill me", timeout=60)
                )
                await settle(
                    lambda: fleet.calls_delivered(low) == 1,
                    message="the call never reached the victim",
                )
                fleet.kill(low)
                clock.advance(fleet.config.stale_after + 1)
                result = await call
                assert result.output == f"r{1 - low}"
                assert fleet.calls_delivered(1 - low) == 1
                victim_topic = fleet.agents[low].replica_topic()
                assert (victim_topic, "cancel") in chaos.seen
                models[low].release.set()  # unblock for clean teardown
                await client.close()
            await mesh.stop()

    async def test_zombie_replica_never_executes_orphaned_run(self):
        """A call lands on a replica that is ALREADY dead (killed before
        consuming it).  Failover completes the run elsewhere; when the
        zombie resumes consuming, the buffered cancel replays FIRST (the
        dispatcher's express law) and the orphaned call faults at the
        admission gate — the zombie executes nothing."""
        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            chaos = BrokerChaos()
            mesh.chaos = chaos
            models = [ServingStubModel(text=f"r{i}") for i in range(2)]
            async with FleetTopology(mesh, models) as fleet:
                low = fleet.index_of_lowest_key()
                victim_topic = fleet.agents[low].replica_topic()
                victim_corrs: list = []

                def note(topic, headers):
                    if (
                        topic == victim_topic
                        and headers.get(protocol.HDR_KIND) == "call"
                    ):
                        victim_corrs.append(
                            headers.get(protocol.HDR_CORRELATION)
                        )

                chaos.on_publish = note
                router, client = self._failover_client(mesh, fleet)
                await TestFleetChaos._eligible(
                    router, 2, "fleet never became routable"
                )
                # the replica dies FIRST; its advert is still fresh, so
                # the depth-tied pick still places the call on it
                fleet.kill(low)
                call = asyncio.create_task(
                    client.agent("svc").execute("orphan me", timeout=60)
                )
                await settle(
                    lambda: len(victim_corrs) == 1,
                    message="the call never targeted the dead replica",
                )
                clock.advance(fleet.config.stale_after + 1)
                result = await call
                assert result.output == f"r{1 - low}"
                # nothing executed on the corpse: the gate buffered it
                assert fleet.calls_delivered(low) == 0
                assert models[low].replies == 0
                # the zombie resumes: cancel replays first, the orphaned
                # call dies at the admission gate (tombstone), zero turns
                await fleet.resume(low)
                await settle(
                    lambda: cancellation.was_cancelled(victim_corrs[0]),
                    message="the zombie never saw the cancel",
                )
                await settle(
                    lambda: chaos.kinds_seen("fault") >= 1,
                    message="the tombstoned call never faulted",
                )
                assert fleet.calls_delivered(low) == 0
                assert models[low].replies == 0
                await client.close()
            await mesh.stop()

    async def test_stream_fault_fails_open_on_single_replica(self):
        """Review regression: a retriable FAULT mid-stream on a fleet
        with NO alternative replica must not burn the deadline waiting
        for an eligible placement — the faulting replica is alive and
        answering, so the re-dispatch fails open (shared topic) and the
        recovered replica serves the retry within milliseconds."""
        from calfkit_tpu.exceptions import EngineOverloadedError
        from calfkit_tpu.models.node_result import InvocationResult

        class ShedOnceStubModel(ServingStubModel):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.shed_once = True

            async def request(self, messages, settings=None, params=None):
                if self.shed_once:
                    self.shed_once = False
                    raise EngineOverloadedError(
                        "transient shed", lane="short", pending=9, limit=1
                    )
                return await super().request(messages, settings, params)

        with virtual_clock():
            mesh = InMemoryMesh()
            models = [ShedOnceStubModel(text="recovered")]
            async with FleetTopology(mesh, models) as fleet:
                router, client = self._failover_client(mesh, fleet)
                await TestFleetChaos._eligible(
                    router, 1, "the replica never became routable"
                )
                result = None
                async for item in client.agent("svc").stream(
                    "shed me once", timeout=20
                ):
                    if isinstance(item, InvocationResult):
                        result = item
                assert result is not None
                assert result.output == "recovered"
                # both attempts reached the same (only) replica
                assert fleet.calls_delivered(0) == 2
                assert models[0].replies == 1
                await client.close()
            await mesh.stop()

    async def test_hedge_race_first_terminal_wins(self):
        """hedge_after: a slow primary gets a duplicate dispatched on
        the OTHER replica after the latency threshold (virtual clock);
        the first terminal wins and the loser's correlation is
        cancelled."""

        class SlowStubModel(ServingStubModel):
            def __init__(self, **kw):
                super().__init__(**kw)
                self.release = asyncio.Event()

            async def request(self, messages, settings=None, params=None):
                await self.release.wait()
                return await super().request(messages, settings, params)

        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            chaos = BrokerChaos()
            mesh.chaos = chaos
            models = [SlowStubModel(text=f"r{i}") for i in range(2)]
            async with FleetTopology(mesh, models) as fleet:
                low = fleet.index_of_lowest_key()
                models[1 - low].release.set()  # only the primary is slow
                router, client = self._failover_client(
                    mesh, fleet, hedge_after=1.0
                )
                await TestFleetChaos._eligible(
                    router, 2, "fleet never became routable"
                )
                call = asyncio.create_task(
                    client.agent("svc").execute("race me", timeout=60)
                )
                await settle(
                    lambda: fleet.calls_delivered(low) == 1,
                    message="the primary never got the call",
                )
                clock.advance(1.5)  # past hedge_after: the duplicate fires
                result = await call
                assert result.output == f"r{1 - low}"
                assert fleet.calls_delivered(1 - low) == 1
                # the duplicate was marked and the loser cancelled
                assert fleet.agents[1 - low]._hedge_requests == 1
                victim_topic = fleet.agents[low].replica_topic()
                await settle(
                    lambda: (victim_topic, "cancel") in chaos.seen,
                    message="the losing attempt was never cancelled",
                )
                models[low].release.set()  # clean teardown
                await client.close()
            await mesh.stop()


class TestWedgeWatchdog:
    """The engine wedge watchdog (ISSUE 9): a scripted hung device grant
    (the decode thread blocks mid-dispatch, exactly the BENCH r05 state)
    converts to typed RETRIABLE faults within the threshold, readiness
    flips false, the flight recorder dumps — and a late landing
    un-wedges the engine with zero leaked slots or pages."""

    async def test_wedged_dispatch_faults_typed_and_recovers(
        self, params, tmp_path, monkeypatch
    ):
        import threading

        from calfkit_tpu.exceptions import EngineWedgedError

        monkeypatch.setenv("CALFKIT_FLIGHTREC_DIR", str(tmp_path))
        with virtual_clock() as clock:
            runtime = _rt(
                max_batch_size=1, watchdog_stall_s=0.5,
                decode_steps_per_dispatch=2,
            )
            engine = InferenceEngine(CFG, runtime, params=params)
            gate = threading.Event()
            script = ChaosScript().block_at("dispatch", 2, gate)
            engine._chaos = script
            await engine.start()
            try:
                active = asyncio.create_task(
                    _collect(engine, [1, 2, 3], 32, corr="wedge-active")
                )
                await settle(
                    lambda: script.calls.get("dispatch", 0) >= 2,
                    message="the dispatch never reached the block point",
                )
                queued = asyncio.create_task(
                    _collect(engine, [4, 5], 32, corr="wedge-queued")
                )
                await settle(
                    lambda: engine._pending,
                    message="the second request never queued",
                )
                # no landing while the clock passes the threshold
                clock.advance(0.6)
                with pytest.raises(EngineWedgedError):
                    await asyncio.wait_for(active, timeout=10)
                with pytest.raises(EngineWedgedError):
                    await asyncio.wait_for(queued, timeout=10)
                assert engine._wedged
                assert engine.stats.watchdog_trips == 1
                assert engine.stats.watchdog_faulted == 2
                # readiness follows the wedge (advert + /readyz)
                model = JaxLocalModelClient(
                    config=CFG, runtime=runtime, engine=engine
                )
                ready, reason = model.ready()
                assert ready is False and "wedged" in reason
                assert model.stats_snapshot()["wedged"] is True
                # a submit during the wedge sheds fast and typed
                with pytest.raises(EngineWedgedError):
                    await _collect(engine, [9], 4, corr="wedge-late")
                # the dump landed and carries the WEDGE event
                dumps = list(tmp_path.glob("*.jsonl"))
                assert dumps, "no wedge dump written"
                events = _journal_events(engine)
                assert any(e["event"] == "WEDGE" for e in events)
                # ---- recovery: the grant returns, a landing un-wedges
                clock.advance(0.01)
                gate.set()
                await settle(
                    lambda: not engine._wedged,
                    message="a landing never un-wedged the engine",
                )
                assert model.ready()[0] is True
                await settle(lambda: _drained(engine))
                assert_engine_drained(engine)
                # serving resumes for new work
                tokens = await _collect(engine, [1, 2], 4, corr="after")
                assert tokens
            finally:
                gate.set()
                await engine.stop()


class TestOrphanReaper:
    """Caller liveness leases (ISSUE 10): the server-side orphan reaper.
    A caller that dies — heartbeats stop past the lease TTL — has its
    runs abandoned BY THE ENGINE, queued and active alike, slots/pages
    freed through the ordinary retirement path, with a typed
    non-retriable ``mesh.orphaned`` terminal.  This is what makes
    fire-and-forget ``send()`` safe: no client-side supervisor exists
    for a run nobody awaits."""

    async def test_caller_death_reaps_queued_and_active(self, params):
        """Beats stop; one TTL later the engine reaps BOTH the active
        and the queued leased run: typed RunOrphanedError, zero leaked
        slots/pages, journal timeline ending ORPHAN → … → SLOT_FREE."""
        runtime = _rt(
            max_batch_size=1, kv_layout="paged", overlap_dispatch=True,
            flightrec_events=1 << 14,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        total_free = engine._page_alloc.free_pages
        with virtual_clock() as clock:
            await engine.start()
            try:
                ttl = 5.0
                leases.note_beat("lease-dead", ttl)
                active = asyncio.create_task(
                    _collect(
                        engine, [1, 2, 3], 64, corr="orph-a",
                        lease=("lease-dead", ttl),
                    )
                )
                await settle(
                    lambda: engine._active,
                    message="the leased run never activated",
                )
                queued = asyncio.create_task(
                    _collect(
                        engine, [7, 8], 64, corr="orph-b",
                        lease=("lease-dead", ttl),
                    )
                )
                await settle(
                    lambda: len(engine._pending) + len(engine._carry) == 1,
                    message="the second leased run never queued",
                )
                # the caller dies: no more beats — one TTL later both
                # runs are orphans
                clock.advance(ttl + 0.5)
                with pytest.raises(RunOrphanedError):
                    await asyncio.wait_for(active, timeout=10)
                with pytest.raises(RunOrphanedError):
                    await asyncio.wait_for(queued, timeout=10)
                await settle(
                    lambda: _drained(engine, total_free),
                    message="engine did not drain after the orphan reap",
                )
                assert_engine_drained(engine, total_free)
                assert engine.stats.orphaned_requests == 2
                # orphans are NOT consumer cancels: no double count
                assert engine.stats.cancelled_requests == 0
                assert engine.stats.expired_requests == 0
                events = _journal_events(engine)
                tl = flightrec.timeline_events(events, "orph-a")
                names = [e["event"] for e in tl]
                assert "ORPHAN" in names, names
                assert "SLOT_FREE" in names, names
                assert names.index("ORPHAN") < (
                    len(names) - 1 - names[::-1].index("SLOT_FREE")
                ), f"ORPHAN did not precede the final SLOT_FREE: {names}"
                # the engine still serves live callers after the reap
                leases.note_beat("lease-live", ttl)
                tokens = await _collect(
                    engine, [9], 8, corr="after",
                    lease=("lease-live", ttl),
                )
                assert len(tokens) == 8
            finally:
                await engine.stop()

    async def test_lease_lapsed_at_submit_refused_before_device_work(
        self, params
    ):
        """A run arriving under an already-lapsed lease is refused at
        the gate — the EXPIRE-at-submit twin, no prefill burned."""
        engine = InferenceEngine(CFG, _rt(), params=params)
        with virtual_clock() as clock:
            await engine.start()
            try:
                leases.note_beat("lease-gone", 2.0)
                clock.advance(3.0)
                with pytest.raises(RunOrphanedError):
                    await _collect(
                        engine, [1, 2], 8, corr="late",
                        lease=("lease-gone", 2.0),
                    )
                assert engine.stats.orphaned_requests == 1
                assert engine.stats.prefill_tokens == 0
            finally:
                await engine.stop()

    async def test_heartbeat_wedge_within_ttl_run_survives(self, params):
        """A late beat WITHIN the TTL re-arms the reaper instead of
        orphaning: the registered expiry pops, the store shows a fresh
        beat, and the run completes normally."""
        runtime = _rt(decode_steps_per_dispatch=2)
        engine = InferenceEngine(CFG, runtime, params=params)
        pace = ChaosScript()

        def throttle(point):
            pace(point)
            if point == "dispatch":
                time.sleep(0.01)

        engine._chaos = throttle
        with virtual_clock() as clock:
            await engine.start()
            try:
                ttl = 10.0
                leases.note_beat("lease-wedge", ttl)
                run = asyncio.create_task(
                    _collect(
                        engine, [1, 2, 3], 48, corr="survivor",
                        lease=("lease-wedge", ttl),
                    )
                )
                await settle(
                    lambda: engine._active,
                    message="the leased run never activated",
                )
                # the caller's beat wedges for 0.6 TTL, then recovers:
                # total elapsed passes the ORIGINAL expiry, but the late
                # beat keeps the lease alive — the reaper must re-arm,
                # not orphan
                clock.advance(ttl * 0.6)
                leases.note_beat("lease-wedge", ttl)
                clock.advance(ttl * 0.6)
                tokens = await asyncio.wait_for(run, timeout=30)
                assert len(tokens) == 48
                assert engine.stats.orphaned_requests == 0
            finally:
                await engine.stop()

    @pytest.mark.parametrize("ragged", [True, False])
    async def test_precedence_one_typed_error_both_schedulers(
        self, params, ragged
    ):
        """THE precedence law (ISSUE 10 satellite), pinned on BOTH
        schedulers: a run whose deadline AND lease lapse in the same
        instant faults with exactly ONE typed error — the deadline's
        (expired outranks orphaned; the deadline sweep also runs first
        each pass) — and a lease-only lapse faults ``mesh.orphaned``.
        The ragged and bifurcated lanes share one _raise_terminal and
        one reap, so agreement is checked, not assumed."""
        runtime = _rt(
            chunked_prefill=True, overlap_dispatch=True,
            ragged_waves=ragged, decode_steps_per_dispatch=2,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        pace = ChaosScript()

        def throttle(point):
            pace(point)
            if point == "dispatch":
                time.sleep(0.01)

        engine._chaos = throttle
        with virtual_clock() as clock:
            await engine.start()
            try:
                assert engine._ragged is ragged
                now = cancellation.wall_clock()
                ttl = 2.0
                leases.note_beat("lease-both", ttl)
                both = asyncio.create_task(
                    _collect(
                        engine, [1, 2, 3], 64, corr="both",
                        deadline=now + ttl, lease=("lease-both", ttl),
                    )
                )
                await settle(
                    lambda: engine._active,
                    message="the doubly-doomed run never activated",
                )
                # deadline AND lease lapse in one step: exactly one
                # typed error, and it is the deadline's
                clock.advance(ttl + 1.0)
                with pytest.raises(DeadlineExceededError):
                    await asyncio.wait_for(both, timeout=10)
                await settle(lambda: _drained(engine))
                assert engine.stats.expired_requests == 1
                assert engine.stats.orphaned_requests == 0
                assert engine.stats.cancelled_requests == 0
                # lease-only lapse on the same scheduler: mesh.orphaned
                leases.note_beat("lease-only", ttl)
                orphan = asyncio.create_task(
                    _collect(
                        engine, [4, 5], 64, corr="only",
                        lease=("lease-only", ttl),
                    )
                )
                await settle(
                    lambda: engine._active,
                    message="the leased-only run never activated",
                )
                clock.advance(ttl + 1.0)
                with pytest.raises(RunOrphanedError):
                    await asyncio.wait_for(orphan, timeout=10)
                await settle(lambda: _drained(engine))
                assert_engine_drained(engine)
                assert engine.stats.orphaned_requests == 1
                assert engine.stats.expired_requests == 1
            finally:
                await engine.stop()

    async def test_caller_death_mid_fire_and_forget_over_the_mesh(
        self, params
    ):
        """THE acceptance drill: a LEASED client ``send()``s a run nobody
        awaits through the real mesh → worker → engine path, then dies
        hard (beat task killed, no tombstone).  One TTL later the engine
        reaps the orphan — drained, zero leaks — and the typed
        ``mesh.orphaned`` fault went to the (dead) reply topic."""
        runtime = _rt(
            max_batch_size=2, decode_steps_per_dispatch=1,
            kv_layout="paged", overlap_dispatch=True,
            flightrec_events=1 << 14,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        total_free = engine._page_alloc.free_pages
        throttle = ChaosScript()

        def pace(point):
            throttle(point)
            if point == "dispatch":
                time.sleep(0.01)

        engine._chaos = pace
        model = JaxLocalModelClient(
            config=CFG, runtime=runtime, engine=engine, max_new_tokens=100
        )
        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            chaos = BrokerChaos()
            mesh.chaos = chaos
            agent = Agent("leased", model=model)
            async with Worker([agent], mesh=mesh, owns_transport=True):
                ttl = 1.0
                client = Client.connect(mesh, lease_ttl=ttl)
                corr = await client.agent("leased").send("fire and forget")
                await settle(
                    lambda: engine._active,
                    message="the send() never reached the engine",
                )
                # hard caller death: beats stop, no tombstone
                assert client._lease_task is not None
                client._lease_task.cancel()
                clock.advance(ttl + 0.5)
                await settle(
                    lambda: _drained(engine, total_free),
                    message="the engine never reaped the orphan",
                )
                assert_engine_drained(engine, total_free)
                assert engine.stats.orphaned_requests == 1
                # the typed fault went out for the record (dead inbox)
                await settle(
                    lambda: chaos.kinds_seen("fault") >= 1,
                    message="no mesh.orphaned fault was published",
                )
                events = _journal_events(engine)
                tl = flightrec.timeline_events(events, corr)
                names = [e["event"] for e in tl]
                assert "ORPHAN" in names, names
                await client.close()
            await engine.stop()

    async def test_clean_close_releases_lease_and_reaps_now(self, params):
        """A clean ``close()`` tombstones the lease: outstanding leased
        runs orphan IMMEDIATELY — no TTL of grace for a deliberate
        departure (frozen clock proves no lapse was needed)."""
        runtime = _rt(decode_steps_per_dispatch=1)
        engine = InferenceEngine(CFG, runtime, params=params)
        throttle = ChaosScript()

        def pace(point):
            throttle(point)
            if point == "dispatch":
                time.sleep(0.01)

        engine._chaos = pace
        model = JaxLocalModelClient(
            config=CFG, runtime=runtime, engine=engine, max_new_tokens=100
        )
        with virtual_clock():
            mesh = InMemoryMesh()
            agent = Agent("leaving", model=model)
            async with Worker([agent], mesh=mesh, owns_transport=True):
                client = Client.connect(mesh, lease_ttl=30.0)
                await client.agent("leaving").send("left behind")
                await settle(
                    lambda: engine._active,
                    message="the send() never reached the engine",
                )
                await client.close()  # tombstones the lease
                await settle(
                    lambda: _drained(engine),
                    message="a released lease never reaped the orphan",
                )
                assert engine.stats.orphaned_requests == 1
                assert engine.stats.expired_requests == 0
            await engine.stop()


    async def test_no_liveness_feed_means_no_enforcement(self, params):
        """Fail-safe wiring: a worker with NO control plane (no liveness
        feed) must treat leased calls as un-leased — beats cannot reach
        it, and orphaning a live caller's run one TTL after admission
        would be worse than burning a dead one's.  The run completes
        despite the clock passing the TTL."""
        runtime = _rt(decode_steps_per_dispatch=2)
        engine = InferenceEngine(CFG, runtime, params=params)
        throttle = ChaosScript()

        def pace(point):
            throttle(point)
            if point == "dispatch":
                time.sleep(0.01)

        engine._chaos = pace
        model = JaxLocalModelClient(
            config=CFG, runtime=runtime, engine=engine, max_new_tokens=24
        )
        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            agent = Agent("feedless", model=model)
            async with Worker(
                [agent], mesh=mesh, owns_transport=True,
                control_plane=False,
            ):
                ttl = 0.5
                client = Client.connect(mesh, lease_ttl=ttl)
                handle = await client.agent("feedless").start(
                    "still alive", timeout=600
                )
                await settle(
                    lambda: engine._active,
                    message="the call never reached the engine",
                )
                clock.advance(ttl * 10)  # far past the TTL
                result = await handle.result()
                assert result.output is not None
                assert engine.stats.orphaned_requests == 0
                await client.close()
            await engine.stop()


class TestDecodeFromOffsetResume:
    """True decode-from-offset resume (ISSUE 10): the survivor of a
    failover consumes ``deps["calfkit.resume_text"]`` — the delivered
    prefix enters via PREFILL, decode produces only the remaining
    tokens, and the caller observes one contiguous byte-exact stream
    (greedy parity vs an unkilled run)."""

    async def test_resume_generates_only_remaining_tokens(self, params):
        """Engine-level accounting: a resumed request's prefix enters as
        prefill (riding the prefix cache), decode counts ONLY the
        remaining tokens, the deltas are exactly the continuation, and
        the terminal response is byte-identical to the unresumed run."""
        from calfkit_tpu.engine.model_client import (
            ModelSettings,
            ResponseDone,
            ResumeOffset,
            TextDelta,
        )
        from calfkit_tpu.models.messages import ModelRequest, UserPart

        from tests._chaos import BijectiveTokenizer

        runtime = _rt(
            kv_layout="paged", chunked_prefill=True, prefix_cache=True,
            overlap_dispatch=True,
        )
        engine = InferenceEngine(CFG, runtime, params=params)
        model = JaxLocalModelClient(
            config=CFG, runtime=runtime, engine=engine,
            tokenizer=BijectiveTokenizer(), max_new_tokens=48,
        )
        messages = [ModelRequest(parts=[UserPart(content="tell a story")])]
        try:
            reference = await model.request(messages)
            full = reference.text() or ""
            assert len(full) >= 8, f"reference too short to resume: {full!r}"
            k = len(full) // 2
            p0 = engine.stats.prefill_tokens
            d0 = engine.stats.decode_tokens
            hits0 = engine.stats.prefix_hits

            events = []
            async for event in model.request_stream(
                messages, ModelSettings(resume_text=full[:k])
            ):
                events.append(event)
            # the resume protocol: offset first, then ONLY fresh deltas,
            # then a terminal carrying the FULL answer
            assert isinstance(events[0], ResumeOffset), events[0]
            assert events[0].chars == k
            deltas = "".join(
                e.text for e in events if isinstance(e, TextDelta)
            )
            assert deltas == full[k:], (deltas, full)
            done = events[-1]
            assert isinstance(done, ResponseDone)
            assert (done.response.text() or "") == full  # byte-exact
            # token accounting: the prefix entered via prefill (k tokens
            # on the bijective tokenizer), decode paid only the rest
            assert engine.stats.decode_tokens - d0 == len(full) - k
            prefill_delta = engine.stats.prefill_tokens - p0
            assert prefill_delta > k  # prompt + the delivered prefix
            # the shared prompt prefix rode the survivor-side cache
            assert engine.stats.prefix_hits > hits0
        finally:
            await engine.stop()

    async def test_resume_with_spent_budget_decodes_nothing(self, params):
        """A delivered prefix that already spent the whole token budget
        short-circuits: no engine work, just ResumeOffset + terminal."""
        from calfkit_tpu.engine.model_client import (
            ModelSettings,
            ResponseDone,
            ResumeOffset,
        )
        from calfkit_tpu.models.messages import ModelRequest, UserPart

        from tests._chaos import BijectiveTokenizer

        runtime = _rt()
        engine = InferenceEngine(CFG, runtime, params=params)
        model = JaxLocalModelClient(
            config=CFG, runtime=runtime, engine=engine,
            tokenizer=BijectiveTokenizer(), max_new_tokens=4,
        )
        messages = [ModelRequest(parts=[UserPart(content="hi")])]
        try:
            prior = "".join(chr(0x100 + i) for i in (9, 10, 11, 12))
            events = [
                e
                async for e in model.request_stream(
                    messages, ModelSettings(resume_text=prior)
                )
            ]
            assert isinstance(events[0], ResumeOffset)
            assert isinstance(events[-1], ResponseDone)
            assert (events[-1].response.text() or "") == prior
            assert engine.stats.decode_tokens == 0
            assert engine.stats.prefill_tokens == 0
        finally:
            await engine.stop()

    async def test_kill_mid_stream_resume_rides_survivor(self, params):
        """THE acceptance scenario: kill a replica mid-stream; the
        survivor RESUMES decode-from-offset — its prefill absorbed the
        delivered prefix, its decode produced only the remainder — and
        the caller observed one contiguous byte-exact stream, equal to
        an unkilled run's answer (greedy parity)."""
        from calfkit_tpu.models.node_result import InvocationResult

        from tests._chaos import BijectiveTokenizer

        with virtual_clock() as clock:
            mesh = InMemoryMesh()
            engines, models = [], []
            for _ in range(2):
                runtime = _rt(max_seq_len=256)
                engine = InferenceEngine(CFG, runtime, params=params)
                engines.append(engine)
                models.append(
                    JaxLocalModelClient(
                        config=CFG, runtime=runtime, engine=engine,
                        tokenizer=BijectiveTokenizer(), max_new_tokens=48,
                    )
                )
            async with FleetTopology(
                mesh, models, agent_kwargs={"stream_tokens": True}
            ) as fleet:
                low = fleet.index_of_lowest_key()
                router, client = TestFailoverChaos._failover_client(
                    mesh, fleet
                )
                await TestFleetChaos._eligible(
                    router, 2, "fleet never became routable"
                )
                # the unkilled reference (first call: EWMA ties at zero,
                # so it lands on the lowest key and warms that replica)
                ref = await client.agent("svc").execute(
                    "tell a story", timeout=120
                )
                full = ref.output or ""
                assert len(full) >= 24, f"answer too short: {full!r}"
                prompt_len = engines[low].stats.prefill_tokens
                assert prompt_len > 0
                # pace BOTH engines — the victim is whichever replica
                # the stream lands on (the EWMA tiebreak steers it away
                # from the ref-warmed one; derive it, don't assume it)
                slow = ChaosScript()

                def pace(point):
                    slow(point)
                    if point == "dispatch":
                        time.sleep(0.02)

                for engine in engines:
                    engine._chaos = pace
                before_p = [e.stats.prefill_tokens for e in engines]
                before_d = [e.stats.decode_tokens for e in engines]

                token_texts: list = []
                offsets: list = []
                result = None
                killed = False
                delivered_at_kill = 0
                victim = -1
                async for item in client.agent("svc").stream(
                    "tell a story", timeout=120
                ):
                    if isinstance(item, InvocationResult):
                        result = item
                        continue
                    if getattr(item.step, "kind", "") != "token":
                        continue
                    token_texts.append(item.step.text)
                    offsets.append(item.step.offset)
                    if not killed and sum(len(t) for t in token_texts) >= 8:
                        killed = True
                        delivered_at_kill = sum(len(t) for t in token_texts)
                        victim = 0 if engines[0]._active else 1
                        assert engines[victim]._active
                        fleet.kill(victim)
                        clock.advance(fleet.config.stale_after + 1)
                assert killed, "the stream never delivered enough to kill"
                assert result is not None
                streamed = "".join(token_texts)
                # one contiguous stream, byte-exact greedy parity with
                # the unkilled reference
                assert result.output == full
                assert streamed == full
                # the survivor resumed from offset: its prefill absorbed
                # prompt + delivered prefix, its decode paid ONLY the
                # remainder — nothing was re-generated (and nothing
                # needed deduping)
                survivor = 1 - victim
                resume_len = (
                    engines[survivor].stats.prefill_tokens
                    - before_p[survivor]
                    - prompt_len
                )
                assert resume_len >= delivered_at_kill > 0
                decode_delta = (
                    engines[survivor].stats.decode_tokens
                    - before_d[survivor]
                )
                assert decode_delta == len(full) - resume_len
                # the resumed attempt's first chunk was offset-stamped at
                # the delivered-prefix length
                assert resume_len in offsets, (resume_len, offsets)
                assert fleet.agents[survivor]._failover_requests == 1
                await client.close()
            for engine in engines:
                await engine.stop()
            await mesh.stop()
