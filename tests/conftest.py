"""Test-suite conftest: minimal async-test support (no pytest-asyncio in the
image) plus shared fixtures for the offline lane."""

from __future__ import annotations

import asyncio
import inspect

import pytest


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem: pytest.Function):
    """Run ``async def`` tests on a fresh event loop per test."""
    fn = pyfuncitem.obj
    if not inspect.iscoroutinefunction(fn):
        return None
    sig = inspect.signature(fn)
    kwargs = {
        name: pyfuncitem.funcargs[name]
        for name in sig.parameters
        if name in pyfuncitem.funcargs
    }
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(fn(**kwargs), timeout=60))
    finally:
        loop.close()
    return True
