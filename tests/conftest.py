"""Test-suite conftest: minimal async-test support (no pytest-asyncio in the
image) plus shared fixtures for the offline lane."""

from __future__ import annotations

import asyncio
import inspect

import pytest


_ASYNC_FINALIZERS: list = []


def register_async_finalizer(factory) -> None:
    """Queue an async callable to run on the test's OWN loop after the test
    body finishes (pass or fail) — sync fixtures can't await, and the loop
    is gone by normal fixture teardown time."""
    _ASYNC_FINALIZERS.append(factory)


@pytest.hookimpl(tryfirst=True)
def pytest_pyfunc_call(pyfuncitem: pytest.Function):
    """Run ``async def`` tests on a fresh event loop per test."""
    fn = pyfuncitem.obj
    if not inspect.iscoroutinefunction(fn):
        return None
    sig = inspect.signature(fn)
    kwargs = {
        name: pyfuncitem.funcargs[name]
        for name in sig.parameters
        if name in pyfuncitem.funcargs
    }
    loop = asyncio.new_event_loop()
    try:
        loop.run_until_complete(asyncio.wait_for(fn(**kwargs), timeout=60))
    finally:
        while _ASYNC_FINALIZERS:
            finalizer = _ASYNC_FINALIZERS.pop()
            try:
                loop.run_until_complete(
                    asyncio.wait_for(finalizer(), timeout=10)
                )
            except Exception:  # noqa: BLE001 - cleanup is best-effort
                pass
        loop.close()
    return True




async def churn_abandon(engine, prompt, rng, max_new_tokens=12):
    """One churn consumer: stream, abandoning mid-stream a third of the
    time (the cancellation path).  Shared by the paged churn stress and
    its prefix-cache variant so the harness cannot silently diverge."""
    agen = engine.generate(prompt, max_new_tokens=max_new_tokens)
    got = 0
    try:
        async for _ in agen:
            got += 1
            if rng.random() < 0.33 and got >= 2:
                break
    finally:
        await agen.aclose()
    return got


async def drain_engine(engine):
    """Wait (bounded) for slots/queues/pages to fully drain; callers
    assert the final state so a timeout fails LOUDLY."""
    import asyncio as _asyncio

    for _ in range(100):
        if (
            not engine._active and not engine._pending
            and not engine._carry and not engine._page_alloc.held_slots
        ):
            break
        await _asyncio.sleep(0.05)
