"""Kernel corners from the reference's long-tail families: co-tenant tool
isolation, node-name validation, task-identity forwarding, node-side decode
floor (reference analogs: tests/test_co_tenant_tool_isolation.py,
test_node_id_validation.py, test_task_header_forwarding.py,
test_decode_floor.py)."""

import pytest

from calfkit_tpu import protocol
from calfkit_tpu.client import Client
from calfkit_tpu.engine import FunctionModelClient, TestModelClient
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models import ModelResponse, TextOutput, ToolCallOutput
from calfkit_tpu.nodes import Agent, agent_tool
from calfkit_tpu.worker import Worker


class TestCoTenantToolIsolation:
    async def test_two_agents_one_worker_distinct_tools(self):
        """Co-tenant agents must each see ONLY their own eager tools —
        sharing a worker process shares nothing else."""
        views: dict[str, list[str]] = {}

        @agent_tool
        def tool_a(x: int) -> int:
            """A.

            Args:
                x: X.
            """
            return x

        @agent_tool
        def tool_b(x: int) -> int:
            """B.

            Args:
                x: X.
            """
            return x

        def make_model(name):
            def model(messages, params):
                views[name] = sorted(t.name for t in params.tool_defs)
                return ModelResponse(parts=[TextOutput(text="ok")])
            return FunctionModelClient(model)

        alpha = Agent("iso_a", model=make_model("iso_a"), tools=[tool_a])
        beta = Agent("iso_b", model=make_model("iso_b"), tools=[tool_b])
        mesh = InMemoryMesh()
        async with Worker([alpha, beta, tool_a, tool_b], mesh=mesh,
                          owns_transport=True):
            client = Client.connect(mesh)
            await client.agent("iso_a").execute("go", timeout=10)
            await client.agent("iso_b").execute("go", timeout=10)
            await client.close()
        assert views["iso_a"] == ["tool_a"]
        assert views["iso_b"] == ["tool_b"]

    async def test_concurrent_runs_do_not_cross_state(self):
        """Two interleaved runs on one agent: each model turn sees its own
        run's prompt only (single-writer per task, state rides the wire)."""
        import asyncio

        def model(messages, params):
            from calfkit_tpu.models.messages import ModelRequest, UserPart

            texts = [
                str(p.content)
                for m in messages
                if isinstance(m, ModelRequest)
                for p in m.parts
                if isinstance(p, UserPart)
            ]
            return ModelResponse(parts=[TextOutput(text="|".join(texts))])

        agent = Agent("tenant", model=FunctionModelClient(model))
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            gateway = client.agent("tenant")
            results = await asyncio.gather(
                *(gateway.execute(f"run-{i}", timeout=15) for i in range(6))
            )
            for i, result in enumerate(results):
                assert result.output == f"run-{i}"
            await client.close()


class TestNodeNaming:
    def test_agent_names_must_be_topic_safe(self):
        with pytest.raises(Exception):
            Agent("has space", model=TestModelClient())
        with pytest.raises(Exception):
            Agent("has/slash", model=TestModelClient())
        Agent("fine-name_1", model=TestModelClient())  # dots/dash/underscore ok

    def test_topic_grammar(self):
        assert protocol.is_topic_safe("agent.x.private.input")
        assert not protocol.is_topic_safe("")
        assert not protocol.is_topic_safe("a b")
        assert not protocol.is_topic_safe("x" * 300)  # kafka length cap


class TestTaskIdentityForwarding:
    async def test_one_task_id_spans_agent_and_tool_hops(self):
        """The client-minted task id is the partition key of EVERY hop."""
        seen: dict[str, set] = {"keys": set(), "tasks": set()}
        mesh = InMemoryMesh()

        @agent_tool
        def echo_tool(x: int) -> int:
            """E.

            Args:
                x: X.
            """
            return x

        def model(messages, params):
            from calfkit_tpu.models.messages import ModelRequest, ToolReturnPart

            done = any(
                isinstance(p, ToolReturnPart)
                for m in messages
                if isinstance(m, ModelRequest)
                for p in m.parts
            )
            if not done:
                return ModelResponse(parts=[ToolCallOutput(
                    tool_call_id="t1", tool_name="echo_tool", args={"x": 1})])
            return ModelResponse(parts=[TextOutput(text="done")])

        agent = Agent("spanner", model=FunctionModelClient(model),
                      tools=[echo_tool])

        async def tap(record):
            if record.key:
                seen["keys"].add(record.key)
            task = record.headers.get(protocol.HDR_TASK)
            if task:
                seen["tasks"].add(task)

        async with Worker([agent, echo_tool], mesh=mesh, owns_transport=True):
            sub = await mesh.subscribe(
                ["agent.spanner.private.input", "tool.echo_tool.input",
                 "agent.spanner.private.return"],
                tap, group_id=None, ordered=False,
            )
            client = Client.connect(mesh)
            result = await client.agent("spanner").execute("go", timeout=15)
            assert result.output == "done"
            assert result.task_id is not None
            await sub.stop()
            await client.close()
        assert seen["tasks"] == {result.task_id}
        assert len(seen["keys"]) == 1  # one partition key end-to-end


class TestNodeDecodeFloor:
    async def test_garbage_on_the_input_topic_does_not_wedge_the_agent(self):
        agent = Agent("sturdy", model=TestModelClient(custom_output_text="alive"))
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            # hostile bytes with envelope-shaped headers
            await mesh.publish(
                "agent.sturdy.private.input",
                b"\xff\xfe not json at all",
                key=b"k1",
                headers={
                    protocol.HDR_KIND: "call",
                    protocol.HDR_WIRE: "envelope",
                    protocol.HDR_TASK: "t-garbage",
                },
            )
            client = Client.connect(mesh)
            result = await client.agent("sturdy").execute("still there?",
                                                          timeout=10)
            assert result.output == "alive"
            await client.close()
