"""meshlint self-tests (ISSUE 12).

Fixture mini-projects pin every effect and rule; the real-tree tests pin
the acceptance contract: clean tree exits 0, a seeded transitive
violation (hot root -> clean helper -> logging helper) exits 1 printing
the full call chain, and the ``scripts/lint_hotpath.py`` shim keeps the
old CI entry point working.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
SCRIPTS = REPO / "scripts"
if str(SCRIPTS) not in sys.path:
    sys.path.insert(0, str(SCRIPTS))

from meshlint import Config, analyze, default_config  # noqa: E402
from meshlint.config import RequiredRoots  # noqa: E402


def make_config(tmp_path: Path, files: "dict[str, str]", **kwargs) -> Config:
    for rel, text in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(text))
    kwargs.setdefault("scan", ["pkg"])
    kwargs.setdefault("package_prefix", "pkg")
    return Config(root=tmp_path, **kwargs)


def rules_of(report) -> "set[str]":
    return {v.rule for v in report.violations}


# --------------------------------------------------------------- call graph


class TestTransitiveChains:
    def test_seeded_chain_reports_every_hop(self, tmp_path):
        """The acceptance shape: root -> clean helper -> logging helper,
        across three modules, reported as the full chain."""
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/a.py": """
                from calfkit_tpu.effects import hotpath
                from pkg.b import helper

                @hotpath
                def tick():
                    helper()
            """,
            "pkg/b.py": """
                from pkg.c import log_helper

                def helper():
                    log_helper()
            """,
            "pkg/c.py": """
                import logging
                logger = logging.getLogger(__name__)

                def log_helper():
                    logger.info("per-dispatch log line")
            """,
        })
        report = analyze(config)
        assert not report.ok
        [v] = [v for v in report.violations if v.rule == "hotpath"]
        assert v.effect == "LOG"
        assert [h.qname for h in v.chain] == [
            "pkg.a.tick", "pkg.b.helper", "pkg.c.log_helper",
        ]
        assert v.path == "pkg/c.py"
        rendered = report.render(chains=True)
        assert "pkg.a.tick" in rendered
        assert "pkg.b.helper" in rendered
        assert "pkg/c.py" in rendered

    def test_method_dispatch_through_self_and_local_ctor(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import time
                from calfkit_tpu.effects import hotpath

                class Helper:
                    def nap(self):
                        time.sleep(1)

                class Engine:
                    @hotpath
                    def tick(self):
                        self._go()

                    def _go(self):
                        h = Helper()
                        h.nap()
            """,
        })
        report = analyze(config)
        [v] = [v for v in report.violations if v.rule == "hotpath"]
        assert v.effect == "BLOCK"
        assert [h.qname for h in v.chain] == [
            "pkg.m.Engine.tick", "pkg.m.Engine._go", "pkg.m.Helper.nap",
        ]

    def test_conservative_name_fallback_links_dynamic_receivers(
        self, tmp_path
    ):
        """An attribute call on an untypable receiver still reaches every
        project method of that name — the over-approximation that keeps
        dynamically-dispatched helpers inside the closure."""
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import time
                from calfkit_tpu.effects import hotpath

                class Drafter:
                    def propose_draft(self):
                        time.sleep(1)

                class Engine:
                    @hotpath
                    def tick(self):
                        self._drafter.propose_draft()
            """,
        })
        report = analyze(config)
        assert any(
            v.rule == "hotpath" and v.effect == "BLOCK"
            for v in report.violations
        )

    def test_relative_import_in_package_init_resolves(self, tmp_path):
        """A level-1 relative import inside __init__.py resolves against
        the package ITSELF (p.q), not its parent — a mis-strip here
        silently voids coverage for any __init__-rooted chain."""
        config = make_config(tmp_path, {
            "pkg/__init__.py": """
                from calfkit_tpu.effects import hotpath
                from .helper import log_fn

                @hotpath
                def init_root():
                    log_fn()
            """,
            "pkg/helper.py": """
                import logging
                logger = logging.getLogger(__name__)

                def log_fn():
                    logger.info("hi")
            """,
        })
        report = analyze(config)
        assert any(
            v.rule == "hotpath" and v.chain[0].qname == "pkg.init_root"
            and v.chain[-1].qname == "pkg.helper.log_fn"
            for v in report.violations
        )

    def test_spawned_coroutine_does_not_leak_into_spawner_closure(
        self, tmp_path
    ):
        """`create_task(self._bg())` builds a coroutine object; the body
        runs on the spawned task (independently rooted by the stall
        rule), so its effects must not propagate into the spawner."""
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import asyncio
                import logging
                from calfkit_tpu.effects import hotpath
                logger = logging.getLogger(__name__)

                class E:
                    @hotpath
                    def kick(self):
                        asyncio.create_task(self._bg())

                    async def _bg(self):
                        logger.info("background beat")
            """,
        })
        report = analyze(config)
        assert "hotpath" not in rules_of(report)

    def test_reassigned_local_drops_precise_binding(self, tmp_path):
        """`x = C(); x = unknown(); x.get()` must not keep dispatching to
        C.get — statement ORDER drives the drop law ("get" is in the
        fallback skip set, so a stale binding is the only edge source)."""
        files = {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import time
                from calfkit_tpu.effects import hotpath

                class C:
                    def get(self):
                        time.sleep(1)

                @hotpath
                def stale():
                    x = C()
                    x = unknown_factory()
                    x.get()

                @hotpath
                def precise():
                    x = C()
                    x.get()
            """,
        }
        report = analyze(make_config(tmp_path, files))
        roots = {v.chain[0].qname for v in report.violations
                 if v.rule == "hotpath"}
        assert roots == {"pkg.m.precise"}

    def test_nested_def_body_not_attributed_to_parent(self, tmp_path):
        """A jit body builder's device code must not pollute the host
        function: a nested def that is only RETURNED contributes nothing;
        one the parent CALLS does."""
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import time
                from calfkit_tpu.effects import hotpath

                @hotpath
                def builder():
                    def body():
                        time.sleep(1)
                    return body

                @hotpath
                def caller():
                    def body():
                        time.sleep(1)
                    body()
            """,
        })
        report = analyze(config)
        offenders = {v.chain[0].qname for v in report.violations
                     if v.rule == "hotpath"}
        assert offenders == {"pkg.m.caller"}


# ------------------------------------------------------------ effect matrix


class TestEffectMatrix:
    def test_wallclock_vs_monotonic(self, tmp_path):
        """@no_wallclock bans BOTH clock families; @hotpath bans only the
        wall clock — perf_counter is the sanctioned hot-path clock."""
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import time
                from calfkit_tpu.effects import hotpath, no_wallclock

                @no_wallclock
                def gated_metric():
                    return time.perf_counter()

                @hotpath
                def tick():
                    t = time.perf_counter()
                    return t

                @hotpath
                def bad_tick():
                    return time.time()
            """,
        })
        report = analyze(config)
        flagged = {(v.chain[0].qname, v.effect) for v in report.violations}
        assert ("pkg.m.gated_metric", "MONOTONIC") in flagged
        assert ("pkg.m.bad_tick", "WALLCLOCK") in flagged
        assert not any(q == "pkg.m.tick" for q, _ in flagged)

    def test_device_sync_and_no_log(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                from calfkit_tpu.effects import hotpath, no_log

                @hotpath
                def tick(arr):
                    return arr.block_until_ready()

                @no_log
                def quiet():
                    print("hi")
            """,
        })
        report = analyze(config)
        flagged = {(v.chain[0].qname, v.effect) for v in report.violations}
        assert ("pkg.m.tick", "DEVICE_SYNC") in flagged
        assert ("pkg.m.quiet", "LOG") in flagged

    def test_from_imported_clock_names(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                from time import monotonic
                from calfkit_tpu.effects import no_wallclock

                @no_wallclock
                def stamp():
                    return monotonic()
            """,
        })
        report = analyze(config)
        assert any(v.effect == "MONOTONIC" for v in report.violations)

    def test_hotpath_must_stay_sync(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                from calfkit_tpu.effects import hotpath

                @hotpath
                async def select():
                    return None
            """,
        })
        report = analyze(config)
        assert "hotpath-sync-shape" in rules_of(report)


# ------------------------------------------------------------- escape rules


class TestEscapeComments:
    def test_blocking_ok_waives_site_for_every_root(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import time
                from calfkit_tpu.effects import hotpath

                @hotpath
                def tick():
                    helper()

                def helper():
                    # blocking-ok: first-dispatch jit build, cached after
                    time.sleep(0)
            """,
        })
        assert analyze(config).ok

    def test_comment_block_above_counts(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import time
                from calfkit_tpu.effects import no_wallclock

                @no_wallclock
                def stamp():
                    # this site is deliberate:
                    # wallclock-ok: report capture block, stripped by gate
                    return time.time()
            """,
        })
        assert analyze(config).ok

    def test_unrelated_comment_does_not_waive(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import time
                from calfkit_tpu.effects import no_block

                @no_block
                def f():
                    time.sleep(1)  # TODO fix later
            """,
        })
        assert not analyze(config).ok


# ----------------------------------------------------- event-loop stall rule


class TestAsyncStall:
    FILES = {
        "pkg/__init__.py": "",
        "pkg/m.py": """
            import asyncio
            import time

            def blocking_helper():
                time.sleep(1)

            async def stalls():
                blocking_helper()

            async def offloads():
                await asyncio.to_thread(blocking_helper)
        """,
    }

    def test_direct_transitive_block_flagged(self, tmp_path):
        report = analyze(make_config(tmp_path, self.FILES))
        stalls = [v for v in report.violations if v.rule == "async-stall"]
        assert len(stalls) == 1
        assert stalls[0].chain[0].qname == "pkg.m.stalls"

    def test_to_thread_handoff_is_legal(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import asyncio
                import time

                def blocking_helper():
                    time.sleep(1)

                async def offloads():
                    await asyncio.to_thread(blocking_helper)
            """,
        })
        assert "async-stall" not in rules_of(analyze(config))

    def test_stall_outside_package_prefix_ignored(self, tmp_path):
        config = make_config(tmp_path, self.FILES,
                             package_prefix="otherpkg")
        assert "async-stall" not in rules_of(analyze(config))


# ------------------------------------------------------- await atomicity


class TestAwaitAtomicity:
    def test_read_await_write_flagged(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import asyncio

                class S:
                    async def bump(self):
                        n = self._count
                        await asyncio.sleep(0)
                        self._count = n + 1
            """,
        })
        report = analyze(config)
        [v] = [v for v in report.violations if v.rule == "await-atomicity"]
        assert v.detail == "self._count"

    def test_augassign_after_await_is_fresh(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import asyncio

                class S:
                    async def bump(self):
                        if self._count > 0:
                            await asyncio.sleep(0)
                            self._count += 1
            """,
        })
        assert "await-atomicity" not in rules_of(analyze(config))

    def test_reread_after_await_is_fresh(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import asyncio

                class S:
                    async def bump(self):
                        n = self._count
                        await asyncio.sleep(0)
                        self._count = self._count + 1
            """,
        })
        assert "await-atomicity" not in rules_of(analyze(config))

    def test_atomicity_ok_annotation_honored(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import asyncio

                class S:
                    async def start(self):
                        if self._started:
                            return
                        await asyncio.sleep(0)
                        # atomicity-ok: double-checked under the lock
                        self._started = True
            """,
        })
        assert "await-atomicity" not in rules_of(analyze(config))

    def test_write_with_no_prior_read_not_flagged(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                import asyncio

                class S:
                    async def set(self):
                        await asyncio.sleep(0)
                        self._done = True
            """,
        })
        assert "await-atomicity" not in rules_of(analyze(config))


# ------------------------------------------------------ migrated rules


class TestUnboundedQueues:
    def make(self, tmp_path, body):
        return make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/q.py": "import asyncio\nfrom collections import deque\n"
                        "from dataclasses import dataclass, field\n" + body,
        }, queue_scope=["pkg.q"])

    def test_unjustified_flagged_justified_waived(self, tmp_path):
        report = analyze(self.make(tmp_path, textwrap.dedent("""
            BAD = asyncio.Queue()
            # unbounded-ok: drained by the per-tick reaper
            GOOD = asyncio.Queue()
        """)))
        queue_violations = [v for v in report.violations
                            if v.rule == "unbounded-queue"]
        assert len(queue_violations) == 1

    def test_bound_semantics(self, tmp_path):
        """maxsize<=0 is UNLIMITED for Queue kinds; deque(maxlen=0) is a
        real bound — the exact lore from the old lint."""
        report = analyze(self.make(tmp_path, textwrap.dedent("""
            A = asyncio.Queue(maxsize=8)     # bounded
            B = deque(maxlen=0)              # bounded (always empty)
            C = asyncio.Queue(0)             # UNLIMITED -> flagged
            D = deque()                      # unbounded -> flagged
        """)))
        lines = sorted(v.lineno for v in report.violations
                       if v.rule == "unbounded-queue")
        assert len(lines) == 2

    def test_default_factory_flagged(self, tmp_path):
        report = analyze(self.make(tmp_path, textwrap.dedent("""
            @dataclass
            class S:
                buf: deque = field(default_factory=deque)
        """)))
        assert any(v.rule == "unbounded-queue" and
                   "default_factory" in v.detail
                   for v in report.violations)

    def test_out_of_scope_module_ignored(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/other.py": "import asyncio\nQ = asyncio.Queue()\n",
        }, queue_scope=["pkg.q"])
        assert "unbounded-queue" not in rules_of(analyze(config))


class TestSimWallclock:
    def test_direct_read_flagged_and_waivable(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/sim/__init__.py": "",
            "pkg/sim/clock.py": """
                import time

                def bad():
                    return time.monotonic()

                def ok():
                    # wallclock-ok: real-time chaos helper, not scenario
                    return time.monotonic()
            """,
        }, sim_scope="pkg.sim")
        report = analyze(config)
        sim = [v for v in report.violations if v.rule == "sim-wallclock"]
        assert len(sim) == 1
        assert sim[0].detail == "time.monotonic()"


class TestFlightrecRules:
    def test_journal_append_formatting_flagged(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/engine.py": """
                class E:
                    def tick(self):
                        self._journal.append(1, f"row {self}")
                        self._journal.append(2, "precomputed", 3)
            """,
        }, journal_module="pkg.engine")
        report = analyze(config)
        sites = [v for v in report.violations
                 if v.rule == "journal-append-site"]
        assert len(sites) == 1
        assert sites[0].detail == "f-string"

    def test_append_body_rule_and_loud_miss(self, tmp_path):
        config = make_config(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/frec.py": """
                class FlightRecorder:
                    def append(self, code):
                        self._ring.append(("%s" % code,))
            """,
        }, flightrec_append=("pkg.frec", "FlightRecorder", "append"))
        report = analyze(config)
        assert any(v.rule == "flightrec-append" and "%-formatting" in v.detail
                   for v in report.violations)
        # loud-miss: a rename must break the lint, not silently pass
        gone = make_config(tmp_path, {},
                           flightrec_append=("pkg.frec", "FlightRecorder",
                                             "renamed_append"))
        assert any(v.effect == "MISSING"
                   for v in analyze(gone).violations)


class TestCoverage:
    def test_root_floor_enforced(self, tmp_path):
        files = {
            "pkg/__init__.py": "",
            "pkg/m.py": """
                from calfkit_tpu.effects import hotpath

                @hotpath
                def tick():
                    return None
            """,
        }
        short = make_config(tmp_path, files, required_roots=[
            RequiredRoots("pkg.m", "hotpath", 2, "closure must stay rooted"),
        ])
        report = analyze(short)
        assert "root-coverage" in rules_of(report)
        met = make_config(tmp_path, files, required_roots=[
            RequiredRoots("pkg.m", "hotpath", 1, ""),
        ])
        assert "root-coverage" not in rules_of(analyze(met))


# ----------------------------------------------------------- the real tree


def _seed_violation(root: Path) -> None:
    engine = root / "calfkit_tpu" / "inference" / "engine.py"
    engine.write_text(engine.read_text() + textwrap.dedent("""


        @hotpath
        def _meshlint_seeded_root():
            _meshlint_seeded_clean_helper()


        def _meshlint_seeded_clean_helper():
            _meshlint_seeded_logging_helper()


        def _meshlint_seeded_logging_helper():
            logger.info("seeded transitive violation")
    """))


@pytest.fixture(scope="module")
def tree_copy(tmp_path_factory):
    """A copy of everything meshlint scans, with a seeded hot-root ->
    clean-helper -> logging-helper chain appended to engine.py."""
    root = tmp_path_factory.mktemp("seeded-tree")
    shutil.copytree(
        REPO / "calfkit_tpu", root / "calfkit_tpu",
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    (root / "scripts").mkdir()
    shutil.copy(REPO / "bench.py", root / "bench.py")
    shutil.copy(REPO / "scripts" / "perf_gate.py",
                root / "scripts" / "perf_gate.py")
    _seed_violation(root)
    return root


class TestRealTree:
    def test_clean_tree_is_clean(self):
        report = analyze(default_config(REPO))
        assert report.ok, report.render(chains=True)
        # the closure actually covers the load-bearing roots
        assert report.stats["hotpath"] >= 20
        assert report.stats["no_wallclock"] >= 2
        assert report.stats["async_defs"] > 100

    def test_seeded_violation_exits_1_with_full_chain(
        self, tree_copy, tmp_path
    ):
        out_json = tmp_path / "meshlint.json"
        proc = subprocess.run(
            [sys.executable, "-m", "meshlint", "--root", str(tree_copy),
             "--chains", "--json", str(out_json)],
            capture_output=True, text=True,
            env={"PYTHONPATH": str(SCRIPTS), "PATH": "/usr/bin:/bin"},
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        # the full chain, every hop, with the offending file:line
        assert "_meshlint_seeded_root" in proc.stdout
        assert "_meshlint_seeded_clean_helper" in proc.stdout
        assert "_meshlint_seeded_logging_helper" in proc.stdout
        assert "logger.info()" in proc.stdout
        document = json.loads(out_json.read_text())
        assert document["ok"] is False
        [v] = [v for v in document["violations"]
               if v["rule"] == "hotpath"]
        assert [h["qname"].rsplit(".", 1)[-1] for h in v["chain"]] == [
            "_meshlint_seeded_root",
            "_meshlint_seeded_clean_helper",
            "_meshlint_seeded_logging_helper",
        ]
        assert v["path"].endswith("engine.py")
        assert v["lineno"] > 0
        # each non-root hop names the file its call line lives in
        for hop in v["chain"][1:]:
            assert hop["call_path"].endswith("engine.py")

    def test_shim_exits_0_on_clean_tree(self):
        proc = subprocess.run(
            [sys.executable, str(SCRIPTS / "lint_hotpath.py")],
            capture_output=True, text=True, cwd=str(REPO),
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "meshlint: clean" in proc.stdout

    def test_shim_exits_1_on_seeded_violation(self, tree_copy):
        proc = subprocess.run(
            [sys.executable, str(SCRIPTS / "lint_hotpath.py"),
             "--root", str(tree_copy)],
            capture_output=True, text=True, cwd=str(REPO),
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "_meshlint_seeded_clean_helper" in proc.stdout
