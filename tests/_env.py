"""Shared test-environment helpers (importable from conftest AND tests)."""

from __future__ import annotations

import os


def tpu_lane_enabled() -> bool:
    """Strict truthiness: CALFKIT_TESTS_TPU=0/false must NOT enable it."""
    return os.environ.get("CALFKIT_TESTS_TPU", "").lower() in (
        "1", "true", "yes",
    )
