"""Wire models: envelope round-trips, stack verbs, error reports, projection."""

import pytest

from calfkit_tpu.models import (
    AgentCard,
    Call,
    CallFrame,
    CapabilityRecord,
    DataPart,
    Envelope,
    ErrorReport,
    FaultMessage,
    FaultTypes,
    ModelRequest,
    ModelResponse,
    ReturnMessage,
    SessionContext,
    State,
    StepMessage,
    TextOutput,
    TextPart,
    ToolCallOutput,
    ToolCallStep,
    ToolDef,
    UserPart,
    WorkflowState,
    is_retry,
    render_parts_as_text,
    resolve_capability,
    retry_text_part,
)
from calfkit_tpu.models.capability import CapabilityResolutionError
from calfkit_tpu.models.node_result import InvocationResult, project_output
from pydantic import BaseModel


class TestParts:
    def test_render(self):
        parts = [TextPart(text="hi"), DataPart(data={"a": 1})]
        assert render_parts_as_text(parts) == 'hi\n{"a": 1}'

    def test_retry_marker(self):
        p = retry_text_part("try again")
        assert is_retry(p)
        assert not is_retry(TextPart(text="x"))


class TestWorkflowState:
    def test_invoke_unwind(self):
        wf = WorkflowState()
        f1 = CallFrame(target_topic="t1", callback_topic="cb1")
        f2 = CallFrame(target_topic="t2", callback_topic="cb2")
        wf.invoke_frame(f1)
        wf.invoke_frame(f2)
        assert wf.depth == 2
        assert wf.current() is f2
        assert wf.root_callback_topic() == "cb1"
        popped = wf.unwind_frame()
        assert popped.frame_id == f2.frame_id
        assert wf.current() is f1
        with pytest.raises(ValueError):
            WorkflowState().unwind_frame()

    def test_mark_fanout(self):
        wf = WorkflowState(frames=[CallFrame(target_topic="t", callback_topic="c")])
        wf.mark_fanout("fx")
        assert wf.current().fanout_id == "fx"
        wf.mark_fanout(None)
        assert wf.current().fanout_id is None


class TestEnvelope:
    def test_wire_roundtrip(self):
        env = Envelope(
            context=SessionContext(state=State(message_history=[
                ModelRequest(parts=[UserPart(content="hello")]),
                ModelResponse(parts=[TextOutput(text="hi")]),
            ])),
            workflow=WorkflowState(
                frames=[CallFrame(target_topic="t", callback_topic="c")]
            ),
            reply=ReturnMessage(parts=[TextPart(text="done")], frame_id="f1"),
        )
        again = Envelope.from_wire(env.to_wire())
        assert again == env

    def test_fault_reply_discriminated(self):
        env = Envelope(reply=FaultMessage(report=ErrorReport(message="boom")))
        again = Envelope.from_wire(env.to_wire())
        assert isinstance(again.reply, FaultMessage)
        assert again.reply.report.message == "boom"


class _Hostile:
    def __str__(self):  # pragma: no cover - exercised via build_safe
        raise RuntimeError("hostile str")

    def __repr__(self):
        raise RuntimeError("hostile repr")


class TestErrorReport:
    def test_build_safe_hostile(self):
        rep = ErrorReport.build_safe(FaultTypes.NODE_ERROR, _Hostile())
        assert rep.error_type == FaultTypes.NODE_ERROR
        assert "_Hostile" in rep.message  # fell back to object.__repr__

    def test_build_safe_exception_harvest(self):
        try:
            raise ValueError("inner")
        except ValueError as exc:
            rep = ErrorReport.build_safe(FaultTypes.TOOL_ERROR, exc=exc, node="n")
        assert rep.exception.type == "ValueError"
        assert "inner" in rep.message
        assert rep.exception.traceback and "ValueError" in rep.exception.traceback

    def test_cause_chain_flattens(self):
        a = ErrorReport.build_safe(FaultTypes.TOOL_ERROR, "leaf", frame_id="f1")
        b = ErrorReport.build_safe(FaultTypes.CALLEE_FAULT, "mid", cause=a, frame_id="f2")
        c = ErrorReport.build_safe(FaultTypes.CALLEE_FAULT, "top", cause=b, frame_id="f3")
        assert [r.message for r in c.causes] == ["mid", "leaf"]
        assert c.root_cause().message == "leaf"
        assert c.frame_chain[:3] == ["f3", "f2", "f1"]

    def test_elision_ladder(self):
        try:
            raise ValueError("x")
        except ValueError as exc:
            rep = ErrorReport.build_safe(FaultTypes.NODE_ERROR, exc=exc)
        no_tb = rep.without_tracebacks()
        assert no_tb.exception.traceback is None
        minimal = rep.to_minimal()
        assert minimal.exception is None and minimal.error_type == rep.error_type


class TestState:
    def test_latest_tool_calls(self):
        st = State(message_history=[
            ModelResponse(parts=[ToolCallOutput(tool_call_id="1", tool_name="a")]),
            ModelRequest(parts=[UserPart(content="x")]),
            ModelResponse(parts=[
                ToolCallOutput(tool_call_id="2", tool_name="b"),
                ToolCallOutput(tool_call_id="3", tool_name="c"),
            ]),
        ])
        assert [c.tool_call_id for c in st.latest_tool_calls()] == ["2", "3"]

    def test_args_dict(self):
        assert ToolCallOutput(tool_call_id="1", tool_name="t", args='{"a": 1}').args_dict() == {"a": 1}
        assert ToolCallOutput(tool_call_id="1", tool_name="t", args="").args_dict() == {}
        with pytest.raises(ValueError):
            ToolCallOutput(tool_call_id="1", tool_name="t", args="[1]").args_dict()


class TestCapability:
    def _records(self):
        return [
            CapabilityRecord(node_id="t1", dispatch_topic="tool.t1.input",
                             tools=[ToolDef(name="get_weather")]),
            CapabilityRecord(node_id="t2", dispatch_topic="tool.t2.input",
                             tools=[ToolDef(name="get_time")]),
        ]

    def test_resolve(self):
        r = resolve_capability(self._records(), "get_weather")
        assert r.dispatch_topic == "tool.t1.input"

    def test_missing_and_ambiguous(self):
        with pytest.raises(CapabilityResolutionError):
            resolve_capability(self._records(), "nope")
        dup = self._records() + [
            CapabilityRecord(node_id="t3", dispatch_topic="tool.t3.input",
                             tools=[ToolDef(name="get_weather")])
        ]
        with pytest.raises(CapabilityResolutionError):
            resolve_capability(dup, "get_weather")

    def test_agent_card(self):
        card = AgentCard(name="weather", description="d")
        assert card.derive_input_topic() == "agent.weather.private.input"
        with pytest.raises(ValueError):
            AgentCard(name="bad name")
        with pytest.raises(ValueError):
            AgentCard(name="x", description="d" * 513)


class _Out(BaseModel):
    city: str
    temp_c: float


class TestProjection:
    def test_str_output(self):
        assert project_output([TextPart(text="a"), TextPart(text="b")], str) == "a\nb"

    def test_typed_from_datapart(self):
        out = project_output([DataPart(data={"city": "SF", "temp_c": 18.0})], _Out)
        assert out.city == "SF"

    def test_typed_from_text_lenient(self):
        out = project_output(
            [TextPart(text='Sure: ```json\n{"city": "SF", "temp_c": 1.0}\n``` done')],
            _Out,
        )
        assert out.city == "SF"

    def test_from_envelope(self):
        env = Envelope(reply=ReturnMessage(parts=[TextPart(text="ok")]))
        res = InvocationResult.from_envelope(env, str, correlation_id="c1")
        assert res.output == "ok" and res.correlation_id == "c1"
        with pytest.raises(ValueError):
            InvocationResult.from_envelope(Envelope(), str)


class TestSteps:
    def test_step_message_roundtrip(self):
        sm = StepMessage(steps=[ToolCallStep(tool_call_id="1", tool_name="t", args={"a": 1})],
                         emitter="agent/w")
        again = StepMessage.from_wire(sm.to_wire())
        assert again == sm
