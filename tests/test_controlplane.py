"""Control plane: adverts, heartbeats, staleness, views, client.mesh,
discovery selectors, peers (messaging + handoff)."""

import asyncio
import time

import pytest

from calfkit_tpu import protocol
from calfkit_tpu.client import Client
from calfkit_tpu.controlplane import ControlPlaneConfig
from calfkit_tpu.engine import EchoModelClient, FunctionModelClient, TestModelClient
from calfkit_tpu.exceptions import MeshUnavailableError, NodeFaultError
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models import (
    FaultTypes,
    ModelResponse,
    TextOutput,
    ToolCallOutput,
)
from calfkit_tpu.nodes import Agent, Tools, agent_tool
from calfkit_tpu.peers import Handoff, Messaging
from calfkit_tpu.worker import Worker


@agent_tool
def lookup(q: str) -> str:
    """Lookup a fact.

    Args:
        q: Query.
    """
    return f"fact({q})"


class TestDiscovery:
    async def test_adverts_views_and_mesh_directory(self):
        mesh = InMemoryMesh()
        agent = Agent("finder", model=TestModelClient(custom_output_text="ok"),
                      tools=Tools(discover=True), description="Finds things.")
        async with Worker([agent, lookup], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            cards = await client.mesh_directory.get_agents()
            assert [c.name for c in cards] == ["finder"]
            assert cards[0].description == "Finds things."
            caps = await client.mesh_directory.get_capabilities()
            assert caps and caps[0].tools[0].name == "lookup"
            # discovery selector resolves the live tool and the run works
            result = await client.agent("finder").execute("find it", timeout=10)
            assert result.output == "ok"
            await client.mesh_directory.close()
            await client.close()

    async def test_tombstones_on_worker_stop(self):
        mesh = InMemoryMesh()
        await mesh.start()
        agent = Agent("fleeting", model=EchoModelClient())
        worker = Worker([agent], mesh=mesh)
        await worker.start()
        client = Client.connect(mesh)
        assert [c.name for c in await client.mesh_directory.get_agents()] == ["fleeting"]
        await worker.stop()
        await asyncio.sleep(0.05)
        assert await client.mesh_directory.get_agents() == []  # tombstoned
        await client.mesh_directory.close()
        await client.close()
        await mesh.stop()

    async def test_stale_heartbeats_filtered(self):
        from calfkit_tpu.controlplane.view import ControlPlaneView
        from calfkit_tpu.models.agents import AgentCard
        from calfkit_tpu.models.records import ControlPlaneRecord, ControlPlaneStamp

        mesh = InMemoryMesh()
        await mesh.start()
        writer = mesh.table_writer(protocol.AGENTS_TOPIC)
        stale = ControlPlaneRecord(
            stamp=ControlPlaneStamp(
                node_name="ghost", node_kind="agent", instance_id="i1",
                heartbeat_at=time.time() - 120,
            ),
            record=AgentCard(name="ghost").model_dump(),
        )
        live = ControlPlaneRecord(
            stamp=ControlPlaneStamp(
                node_name="alive", node_kind="agent", instance_id="i2",
            ),
            record=AgentCard(name="alive").model_dump(),
        )
        await writer.put("ghost@i1", stale.to_wire())
        await writer.put("alive@i2", live.to_wire())
        view = ControlPlaneView(mesh, protocol.AGENTS_TOPIC, AgentCard,
                               stale_after=15.0)
        await view.start()
        assert [c.name for c in view.records()] == ["alive"]
        await view.stop()
        await mesh.stop()

    async def test_discover_without_control_plane_faults(self):
        mesh = InMemoryMesh()
        agent = Agent("blind", model=TestModelClient(),
                      tools=Tools(discover=True))
        async with Worker([agent], mesh=mesh, owns_transport=True,
                          control_plane=False):
            client = Client.connect(mesh)
            with pytest.raises(NodeFaultError) as exc_info:
                await client.agent("blind").execute("x", timeout=10)
            assert exc_info.value.report.error_type == FaultTypes.CAPABILITY_UNAVAILABLE
            await client.close()

    async def test_mesh_unavailable_reason(self):
        mesh = InMemoryMesh()
        await mesh.start()
        client = Client.connect(mesh)
        # no worker ever ran: views catch up on empty topics fine -> empty
        assert await client.mesh_directory.get_agents() == []
        await client.mesh_directory.close()
        await client.close()
        await mesh.stop()


class TestPeersMessaging:
    async def test_message_agent_roundtrip_isolated_state(self):
        turn = {"n": 0}

        def asker_model(messages, params):
            turn["n"] += 1
            if turn["n"] == 1:
                return ModelResponse(parts=[ToolCallOutput(
                    tool_call_id="m1", tool_name="message_agent",
                    args={"agent_name": "expert", "message": "What is X?"},
                )])
            return ModelResponse(parts=[TextOutput(text="expert says: done")])

        expert_seen = {}

        def expert_model(messages, params):
            expert_seen["history_len"] = len(messages)
            return ModelResponse(parts=[TextOutput(text="X is 42")])

        mesh = InMemoryMesh()
        asker = Agent("asker", model=FunctionModelClient(asker_model),
                      peers=[Messaging("expert")])
        expert = Agent("expert", model=FunctionModelClient(expert_model),
                       description="Knows X.")
        async with Worker([asker, expert], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("asker").execute("ask the expert", timeout=15)
            assert result.output == "expert says: done"
            # isolation: the expert saw ONLY the message, not asker's history
            assert expert_seen["history_len"] == 1
            # the reply was materialized into asker's history as a tool return
            history = result.state.message_history
            returns = [p for m in history if m.role == "request"
                       for p in m.parts if p.kind == "tool_return"]
            assert any("X is 42" in str(r.content) for r in returns)
            await client.close()

    async def test_message_unknown_agent_retries(self):
        turn = {"n": 0}

        def model(messages, params):
            turn["n"] += 1
            if turn["n"] == 1:
                return ModelResponse(parts=[ToolCallOutput(
                    tool_call_id="m1", tool_name="message_agent",
                    args={"agent_name": "nobody", "message": "hi"},
                )])
            # sees the retry and gives up gracefully
            return ModelResponse(parts=[TextOutput(text="could not reach")])

        mesh = InMemoryMesh()
        agent = Agent("lonely", model=FunctionModelClient(model),
                      peers=[Messaging("friend")])  # friend not deployed
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("lonely").execute("try", timeout=15)
            assert result.output == "could not reach"
            assert turn["n"] == 2
            await client.close()


class TestPeersHandoff:
    async def test_handoff_tailcall_reaches_caller(self):
        def fronter_model(messages, params):
            return ModelResponse(parts=[ToolCallOutput(
                tool_call_id="h1", tool_name="handoff_to_agent",
                args={"agent_name": "specialist"},
            )])

        def specialist_model(messages, params):
            return ModelResponse(parts=[TextOutput(text="specialist answer")])

        mesh = InMemoryMesh()
        fronter = Agent("fronter", model=FunctionModelClient(fronter_model),
                        peers=[Handoff("specialist")])
        specialist = Agent("specialist", model=FunctionModelClient(specialist_model))
        async with Worker([fronter, specialist], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("fronter").start("help me", timeout=15)
            events = [e async for e in handle.stream()]
            final = events[-1]
            assert final.output == "specialist answer"
            kinds = [e.step.kind for e in events if hasattr(e, "step")]
            assert "handoff" in kinds
            await client.close()

    async def test_invalid_handoff_target_retries(self):
        turn = {"n": 0}

        def model(messages, params):
            turn["n"] += 1
            if turn["n"] == 1:
                return ModelResponse(parts=[ToolCallOutput(
                    tool_call_id="h1", tool_name="handoff_to_agent",
                    args={"agent_name": "ghost"},
                )])
            return ModelResponse(parts=[TextOutput(text="staying here")])

        mesh = InMemoryMesh()
        agent = Agent("careful", model=FunctionModelClient(model),
                      peers=[Handoff("real_target")])
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("careful").execute("go", timeout=15)
            assert result.output == "staying here"
            await client.close()


class TestProjection:
    def test_pov_projection(self):
        from calfkit_tpu.models.messages import (
            ModelRequest,
            ToolReturnPart,
            UserPart,
        )
        from calfkit_tpu.nodes.projection import project

        history = [
            ModelRequest(parts=[UserPart(content="hi")]),
            ModelResponse(parts=[
                TextOutput(text="let me check"),
                ToolCallOutput(tool_call_id="t1", tool_name="lookup", args={}),
            ], author="me"),
            ModelRequest(parts=[ToolReturnPart(tool_call_id="t1",
                                               tool_name="lookup", content="x")]),
            ModelResponse(parts=[TextOutput(text="other agent speaking")],
                          author="other"),
        ]
        mine = project(history, "me")
        # own turns native (tool call + return preserved)
        assert mine[1].tool_calls()[0].tool_call_id == "t1"
        assert mine[2].parts[0].kind == "tool_return"
        # foreign turn rendered as attributed user text
        assert mine[3].role == "request"
        assert "<other>" in mine[3].parts[0].content

        theirs = project(history, "other")
        # my tool call/return stripped from their view; my text attributed
        flat = [p.kind for m in theirs if m.role == "request" for p in m.parts]
        assert "tool_return" not in flat
        assert any("<me>" in str(getattr(p, "content", ""))
                   for m in theirs if m.role == "request" for p in m.parts)


class TestOnToolError:
    async def test_on_tool_error_substitutes(self):
        @agent_tool
        def fragile() -> str:
            raise RuntimeError("backend down")

        turn = {"n": 0}

        def model(messages, params):
            turn["n"] += 1
            if turn["n"] == 1:
                return ModelResponse(parts=[ToolCallOutput(
                    tool_call_id="f1", tool_name="fragile", args={})])
            return ModelResponse(parts=[TextOutput(text="handled gracefully")])

        def on_tool_error(marker, ctx, report):
            assert marker.tool_name == "fragile"
            from calfkit_tpu.models import TextPart
            return [TextPart(text=f"(fallback for {marker.tool_name})")]

        mesh = InMemoryMesh()
        agent = Agent("resilient", model=FunctionModelClient(model),
                      tools=[fragile], on_tool_error=on_tool_error)
        async with Worker([agent, fragile], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("resilient").execute("go", timeout=15)
            assert result.output == "handled gracefully"
            await client.close()


class TestHandoffRegressions:
    async def test_handoff_does_not_duplicate_prompt(self):
        """The TailCall clears the frame payload: the target must see the
        user prompt exactly once (via shared history), not re-staged."""
        seen = {}

        def fronter_model(messages, params):
            return ModelResponse(parts=[ToolCallOutput(
                tool_call_id="h1", tool_name="handoff_to_agent",
                args={"agent_name": "target"})])

        def target_model(messages, params):
            texts = [
                p.content for m in messages if m.role == "request"
                for p in m.parts if p.kind == "user"
                and isinstance(p.content, str)
            ]
            seen["user_texts"] = texts
            return ModelResponse(parts=[TextOutput(text="done")])

        mesh = InMemoryMesh()
        fronter = Agent("fronter2", model=FunctionModelClient(fronter_model),
                        peers=[Handoff("target")])
        target = Agent("target", model=FunctionModelClient(target_model))
        async with Worker([fronter, target], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("fronter2").execute("the prompt", timeout=15)
            assert result.output == "done"
            # exactly once — possibly <user>-attributed (the handed-off view
            # is multi-participant), and the handoff briefing surfaces too
            hits = sum(
                text.count("the prompt") for text in seen["user_texts"]
            )
            assert hits == 1
            await client.close()

    async def test_losing_handoff_calls_are_closed_in_history(self):
        """Rejected handoff + later winner: every tool call in the committed
        history must have a matching closure (no dangling tool_use)."""
        def model(messages, params):
            return ModelResponse(parts=[
                ToolCallOutput(tool_call_id="bad", tool_name="handoff_to_agent",
                               args={"agent_name": "ghost"}),
                ToolCallOutput(tool_call_id="good", tool_name="handoff_to_agent",
                               args={"agent_name": "sink"}),
            ])

        def sink_model(messages, params):
            # every tool call id in history must be answered
            call_ids = {c.tool_call_id for m in messages if m.role == "response"
                        for c in m.tool_calls()}
            answered = {p.tool_call_id for m in messages if m.role == "request"
                        for p in m.parts if p.kind in ("tool_return", "retry")}
            assert call_ids <= answered, f"dangling: {call_ids - answered}"
            return ModelResponse(parts=[TextOutput(text="clean")])

        mesh = InMemoryMesh()
        a = Agent("chooser", model=FunctionModelClient(model),
                  peers=[Handoff("sink")])
        sink = Agent("sink", model=FunctionModelClient(sink_model))
        async with Worker([a, sink], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("chooser").execute("pick", timeout=15)
            assert result.output == "clean"
            await client.close()


class TestConcurrentMultiAgent:
    async def test_three_agents_parallel_tool_calls(self):
        """BASELINE config 4: 3 agent nodes on shared topics, concurrent
        runs, parallel tool calls per run (reference analog:
        tests/test_concurrent_tool_calls.py)."""
        executed = []

        @agent_tool
        def probe(tag: str) -> str:
            """Probe.

            Args:
                tag: Marker.
            """
            executed.append(tag)
            return f"probe:{tag}"

        def make_model(name):
            # stateless per run: branch on the conversation, not a shared
            # counter (model calls interleave across concurrent runs)
            def model(messages, params):
                import uuid

                last = messages[-1]
                has_returns = last.role == "request" and any(
                    p.kind == "tool_return" for p in last.parts
                )
                if has_returns:
                    return ModelResponse(parts=[TextOutput(text=f"{name} done")])
                run_id = uuid.uuid4().hex[:6]
                return ModelResponse(parts=[
                    ToolCallOutput(tool_call_id=f"{name}-{run_id}-a",
                                   tool_name="probe", args={"tag": f"{name}-a"}),
                    ToolCallOutput(tool_call_id=f"{name}-{run_id}-b",
                                   tool_name="probe", args={"tag": f"{name}-b"}),
                ])

            return FunctionModelClient(model)

        mesh = InMemoryMesh()
        agents = [
            Agent(f"conc{i}", model=make_model(f"conc{i}"), tools=[probe])
            for i in range(3)
        ]
        async with Worker([*agents, probe], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            results = await asyncio.gather(*[
                client.agent(f"conc{i % 3}").execute(f"run {i}", timeout=30)
                for i in range(9)
            ])
            assert [r.output for r in results] == [
                f"conc{i % 3} done" for i in range(9)
            ]
            # EVERY run dispatched its 2-call parallel fan-out
            assert len(executed) == 18
            for r in results:
                roles = [m.role for m in r.state.message_history]
                assert roles == ["request", "response", "request", "response"]
            await client.close()


class TestEngineStatsOnControlPlane:
    async def test_engine_metrics_heartbeat_to_mesh_view(self):
        """An agent served by the local engine heartbeats live metrics
        (tok/s, occupancy, slots) onto the control plane; clients read them
        via mesh_directory.get_engine_stats() with normal staleness
        semantics (SURVEY §5: the TPU build adds real metrics)."""
        from calfkit_tpu.controlplane import ControlPlaneConfig
        from calfkit_tpu.inference import JaxLocalModelClient
        from calfkit_tpu.inference.config import RuntimeConfig, preset

        model = JaxLocalModelClient(
            config=preset("debug"),
            runtime=RuntimeConfig(max_batch_size=2, max_seq_len=128,
                                  prefill_chunk=16,
                                  decode_steps_per_dispatch=4),
            max_new_tokens=8,
        )
        mesh = InMemoryMesh()
        agent = Agent("metered", model=model)
        config = ControlPlaneConfig(heartbeat_interval=0.2)
        async with Worker([agent], mesh=mesh, owns_transport=True,
                          control_plane=config):
            client = Client.connect(mesh)
            result = await client.agent("metered").execute("hi", timeout=60)
            assert result.output
            stats = None
            for _ in range(100):  # metrics refresh on the next heartbeat
                records = await client.mesh_directory.get_engine_stats()
                # a heartbeat can catch the run mid-flight; wait for the
                # post-retirement snapshot (slot freed, tokens counted)
                if (records and records[0].decode_tokens > 0
                        and records[0].free_slots == 2):
                    stats = records[0]
                    break
                await asyncio.sleep(0.1)
            assert stats is not None, "engine stats never reached the view"
            assert stats.node_id == "agent.metered"
            assert stats.model_name == "debug"
            assert stats.max_batch_size == 2
            assert stats.free_slots == 2  # request retired
            assert stats.tokens_per_second > 0
            await client.mesh_directory.close()
            await client.close()
        await model.stop()
