"""Stress + integration depth over the native broker.

Reference anchors: tests/integration/test_fault_stress_kafka.py (concurrent
faulting runs against a real broker), tests/integration/ MCP round-trips
against an in-repo stdio server over a real transport.
"""

from __future__ import annotations

import asyncio
import sys
from pathlib import Path

import pytest

from calfkit_tpu.exceptions import ClientTimeoutError, NodeFaultError
from calfkit_tpu.mesh.tcp import TcpMesh, find_meshd, spawn_meshd
from calfkit_tpu.models import FaultTypes
from calfkit_tpu.models.messages import (
    ModelResponse,
    TextOutput,
    ToolCallOutput,
)

pytestmark = pytest.mark.skipif(
    find_meshd() is None, reason="meshd not built (make -C native)"
)

PORT = 19879
MCP_SERVER = [sys.executable, str(Path(__file__).parent / "_mcp_server.py")]


@pytest.fixture(scope="module")
def broker():
    proc = spawn_meshd(PORT)
    yield proc
    proc.terminate()
    proc.wait(timeout=5)


async def _mesh():
    mesh = TcpMesh(f"127.0.0.1:{PORT}")
    await mesh.start()
    return mesh


class TestMCPOverTcp:
    async def test_mcp_roundtrip_worker_and_client_separate_connections(
        self, broker
    ):
        """The reference's MCP round-trip, over a real transport: stdio MCP
        server subprocess -> toolbox node -> capability view -> agent ->
        client, with worker and client on separate broker connections."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.mcp import MCPServerSpec, MCPToolboxNode, Toolbox
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        toolbox = MCPToolboxNode(MCPServerSpec(name="tcpcalc", command=MCP_SERVER))
        turn = {"n": 0}

        def model(messages, params):
            turn["n"] += 1
            if turn["n"] == 1:
                assert any(
                    t.name == "toolbox.tcpcalc__add" for t in params.tool_defs
                )
                return ModelResponse(parts=[ToolCallOutput(
                    tool_call_id="c1", tool_name="toolbox.tcpcalc__add",
                    args={"a": 40, "b": 2},
                )])
            return ModelResponse(parts=[TextOutput(text="sum says 42")])

        agent = Agent(
            "tcp_mathy", model=FunctionModelClient(model),
            tools=Toolbox("tcpcalc"),
        )
        worker_mesh = await _mesh()
        client_mesh = await _mesh()
        async with Worker([agent, toolbox], mesh=worker_mesh):
            client = Client.connect(client_mesh)
            result = await client.agent("tcp_mathy").execute(
                "add 40 and 2", timeout=30
            )
            assert result.output == "sum says 42"
            await client.close()
        await worker_mesh.stop()
        await client_mesh.stop()


class TestFaultStress:
    async def test_concurrent_mixed_success_and_fault_runs(self, broker):
        """24 concurrent runs, half faulting through a raising tool: every
        run terminates correctly (right output XOR typed fault, no hangs,
        no cross-run bleed) — the reference's fault-stress shape."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool
        def stressed(x: int) -> str:
            """Succeed on even, explode on odd.

            Args:
                x: Input.
            """
            if x % 2:
                raise RuntimeError(f"boom-{x}")
            return f"ok-{x}"

        def model(messages, params):
            # turn 1: call the tool with the number from the prompt;
            # turn 2: report the tool result verbatim
            last = messages[-1]
            for part in last.parts:
                if part.kind == "user":
                    n = int(str(part.content).split()[-1])
                    return ModelResponse(parts=[ToolCallOutput(
                        tool_call_id=f"t{n}", tool_name="stressed",
                        args={"x": n},
                    )])
            returns = [p for p in last.parts if p.kind == "tool_return"]
            return ModelResponse(parts=[TextOutput(
                text=str(returns[0].content)
            )])

        agent = Agent(
            "stress_agent", model=FunctionModelClient(model), tools=[stressed]
        )
        worker_mesh = await _mesh()
        client_mesh = await _mesh()
        async with Worker([agent, stressed], mesh=worker_mesh, max_workers=16):
            client = Client.connect(client_mesh)

            async def one(i: int):
                try:
                    result = await client.agent("stress_agent").execute(
                        f"run {i}", timeout=25
                    )
                    return ("ok", i, result.output)
                except NodeFaultError as exc:
                    return ("fault", i, exc.report)
                except ClientTimeoutError:
                    return ("timeout", i, None)

            outcomes = await asyncio.gather(*[one(i) for i in range(24)])
            timeouts = [i for kind, i, _ in outcomes if kind == "timeout"]
            assert not timeouts, f"runs timed out (broker stall?): {timeouts}"
            for kind, i, payload in outcomes:
                if i % 2 == 0:
                    assert kind == "ok", (i, payload)
                    assert payload == f"ok-{i}"  # no cross-run bleed
                else:
                    assert kind == "fault", (i, payload)
                    assert payload.error_type == FaultTypes.CALLEE_FAULT
                    assert f"boom-{i}" in payload.root_cause().message
            await client.close()
        await worker_mesh.stop()
        await client_mesh.stop()

    async def test_steps_stay_run_scoped_under_load(self, broker):
        """Concurrent runs' step streams never leak across handles."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        def model(messages, params):
            for part in messages[-1].parts:
                if part.kind == "user":
                    return ModelResponse(parts=[TextOutput(
                        text=f"echo {part.content}"
                    )])
            return ModelResponse(parts=[TextOutput(text="?")])

        agent = Agent("steppy", model=FunctionModelClient(model))
        worker_mesh = await _mesh()
        client_mesh = await _mesh()
        async with Worker([agent], mesh=worker_mesh):
            client = Client.connect(client_mesh)

            async def one(i: int):
                handle = await client.agent("steppy").start(
                    f"msg-{i}", timeout=30
                )
                texts = []
                async for event in handle.stream():
                    step = getattr(event, "step", None)
                    if step is not None and getattr(step, "text", None):
                        texts.append(step.text)
                result = await handle.result(timeout=30)
                return i, texts, result.output

            results = await asyncio.gather(*[one(i) for i in range(12)])
            for i, texts, output in results:
                assert output == f"echo msg-{i}"
                for text in texts:
                    assert f"msg-{i}" in text  # only OWN steps observed
            await client.close()
        await worker_mesh.stop()
        await client_mesh.stop()


class TestHorizontalScaling:
    async def test_two_workers_share_one_agents_runs(self, broker):
        """The DP analog (SURVEY §2.4): two Worker replicas hosting the SAME
        agent share the consumer group — runs distribute across them, each
        run stays whole (per-key serial), and every reply is correct."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        served_by: dict[str, list[int]] = {"a": [], "b": []}

        def make_agent(tag: str) -> Agent:
            def model(messages, params):
                for part in messages[-1].parts:
                    if part.kind == "user":
                        n = int(str(part.content).split()[-1])
                        served_by[tag].append(n)
                        return ModelResponse(parts=[TextOutput(
                            text=f"answer {n}"
                        )])
                return ModelResponse(parts=[TextOutput(text="?")])

            return Agent("scaled_agent", model=FunctionModelClient(model))

        mesh_a = await _mesh()
        mesh_b = await _mesh()
        client_mesh = await _mesh()
        worker_a = Worker([make_agent("a")], mesh=mesh_a)
        worker_b = Worker([make_agent("b")], mesh=mesh_b)
        await worker_a.start()
        await worker_b.start()
        try:
            client = Client.connect(client_mesh)
            # warm-up: poll until BOTH members actually serve (fixed sleeps
            # flake on loaded CI; rebalance timing is the broker's business)
            probe = 1000
            deadline = asyncio.get_event_loop().time() + 20
            while not (served_by["a"] and served_by["b"]):
                assert asyncio.get_event_loop().time() < deadline, served_by
                await client.agent("scaled_agent").execute(
                    f"q {probe}", timeout=25
                )
                probe += 1
            results = await asyncio.gather(*[
                client.agent("scaled_agent").execute(f"q {i}", timeout=25)
                for i in range(24)
            ])
            for i, result in enumerate(results):
                assert result.output == f"answer {i}"
            served = sorted(
                n for n in served_by["a"] + served_by["b"] if n < 1000
            )
            assert served == list(range(24))
            await client.close()
        finally:
            await worker_a.stop()
            await worker_b.stop()
            await mesh_a.stop()
            await mesh_b.stop()
            await client_mesh.stop()
