"""Stress + integration depth over the native Kafka-wire broker
(mirrors tests/test_stress_meshd.py's shapes on kafkad: concurrent mixed
success/fault runs, run-scoped step isolation, and the DP analog — two
Worker replicas sharing one consumer group over the REAL Kafka group
protocol with a broker-side rebalance)."""

import asyncio

import pytest

from calfkit_tpu.mesh.kafka_wire import (
    KafkaWireMesh,
    find_kafkad,
    spawn_kafkad,
)

pytestmark = pytest.mark.skipif(
    find_kafkad() is None, reason="kafkad not built (make -C native)"
)


@pytest.fixture(scope="module")
def broker_port():
    proc = spawn_kafkad(0)
    yield proc.kafkad_port
    proc.terminate()
    proc.wait(timeout=5)


class TestFaultStressOverKafka:
    async def test_concurrent_mixed_success_and_fault_runs(self, broker_port):
        """24 concurrent runs, half faulting through a raising tool: every
        reply lands on the right run over the real wire protocol."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.exceptions import NodeFaultError
        from calfkit_tpu.models import ModelResponse
        from calfkit_tpu.models.messages import TextOutput, ToolCallOutput
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool
        def spiky(n: int) -> str:
            """Succeed on even, explode on odd.

            Args:
                n: the number.
            """
            if n % 2:
                raise RuntimeError(f"spike {n}")
            return f"ok {n}"

        def scripted(messages, params):
            has_returns = any(
                getattr(part, "kind", "") == "tool_return"
                for m in messages for part in getattr(m, "parts", [])
            )
            if not has_returns:
                # the prompt carries the number; echo it into the tool call
                prompt = str(messages[0].parts[-1].content)
                n = int(prompt.rsplit(" ", 1)[-1])
                return ModelResponse(parts=[ToolCallOutput(
                    tool_call_id=f"c{n}", tool_name="spiky", args={"n": n},
                )])
            return ModelResponse(parts=[TextOutput(text="done")])

        agent = Agent(
            "spiky_agent", model=FunctionModelClient(scripted), tools=[spiky]
        )
        mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        client_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        await client_mesh.start()
        async with Worker(
            [agent, spiky], mesh=mesh, owns_transport=True, max_workers=16
        ):
            client = Client.connect(client_mesh)

            async def one(n: int):
                try:
                    result = await client.agent("spiky_agent").execute(
                        f"run {n}", timeout=120
                    )
                    return ("ok", result.output)
                except NodeFaultError as exc:
                    return ("fault", exc.report.error_type)

            outcomes = await asyncio.gather(*[one(n) for n in range(24)])
            oks = [o for o in outcomes if o[0] == "ok"]
            faults = [o for o in outcomes if o[0] == "fault"]
            # evens succeed; odds fault through the tool's raise
            assert len(oks) == 12, outcomes
            assert len(faults) == 12
            assert all(o[1] == "done" for o in oks)
            await client.close()
        await client_mesh.stop()


class TestHorizontalScalingOverKafka:
    async def test_two_workers_share_one_group_via_broker_rebalance(
        self, broker_port
    ):
        """The DP analog over the REAL group protocol: two Worker replicas
        host the same agent; kafkad's JoinGroup/SyncGroup rebalance splits
        the node's input partitions between them; every run stays whole
        and every reply is correct."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import EchoModelClient
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        mesh_a = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        mesh_b = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        client_mesh = KafkaWireMesh(f"127.0.0.1:{broker_port}")
        await client_mesh.start()

        def replica() -> Agent:
            return Agent("scaled_agent", model=EchoModelClient())

        async with Worker([replica()], mesh=mesh_a, owns_transport=True):
            async with Worker([replica()], mesh=mesh_b, owns_transport=True):
                await asyncio.sleep(1.5)  # both replicas' generation settles
                client = Client.connect(client_mesh)
                results = await asyncio.gather(*[
                    client.agent("scaled_agent").execute(
                        f"msg {i}", timeout=120
                    )
                    for i in range(12)
                ])
                assert [r.output for r in results] == [
                    f"echo: msg {i}" for i in range(12)
                ]
                await client.close()
        await client_mesh.stop()
