"""Inference backend on CPU XLA: model math, engine scheduling, client."""

import asyncio
import time

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from calfkit_tpu.inference import model as M  # noqa: E402
from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from calfkit_tpu.inference.sampler import SamplingParams, sample  # noqa: E402
from calfkit_tpu.inference.sharding import (  # noqa: E402
    make_mesh,
    param_shardings,
    place_params,
)
from calfkit_tpu.inference.tokenizer import ByteTokenizer  # noqa: E402

CFG = preset("debug")


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


class TestModelMath:
    def test_incremental_decode_matches_prefill(self, params):
        B, S = 2, 12
        toks = jax.random.randint(jax.random.key(1), (B, S), 3, CFG.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        cache = M.make_empty_cache(CFG, B, 32, dtype=jnp.float32)
        full, _ = M.forward(params, CFG, toks, pos, cache, jnp.full((B,), S))

        cache2 = M.make_empty_cache(CFG, B, 32, dtype=jnp.float32)
        pre, cache2 = M.forward(
            params, CFG, toks[:, :8], pos[:, :8], cache2, jnp.full((B,), 8)
        )
        np.testing.assert_allclose(full[:, 7], pre[:, -1], atol=1e-4)
        last = pre[:, -1]
        for i in range(8, S):
            last, cache2 = M.forward(
                params, CFG, toks[:, i : i + 1], pos[:, i : i + 1], cache2,
                jnp.full((B,), i + 1),
            )
            np.testing.assert_allclose(full[:, i], last[:, -1], atol=1e-4)

    def test_decode_masks_ragged_kv_lengths(self, params):
        """Batched decode with rows at different kv lengths: each row's
        logits must match its solo decode (length masking isolates rows)."""
        toks0 = jax.random.randint(jax.random.key(2), (1, 10), 3, CFG.vocab_size)
        toks1 = jax.random.randint(jax.random.key(4), (1, 5), 3, CFG.vocab_size)
        # prefill each row alone
        c0 = M.make_empty_cache(CFG, 1, 32, dtype=jnp.float32)
        _, c0 = M.forward(
            params, CFG, toks0, jnp.arange(10)[None], c0, jnp.array([10])
        )
        c1 = M.make_empty_cache(CFG, 1, 32, dtype=jnp.float32)
        _, c1 = M.forward(
            params, CFG, toks1, jnp.arange(5)[None], c1, jnp.array([5])
        )
        # assemble the batch cache and decode one token per row
        batch_cache = tuple(
            jnp.concatenate([a, b], axis=1) for a, b in zip(c0, c1)
        )
        next_toks = jnp.array([[3], [4]])
        lens = jnp.array([11, 6])
        pos = (lens - 1)[:, None]
        out, _ = M.forward(params, CFG, next_toks, pos, batch_cache, lens)
        # solo decodes
        solo0, _ = M.forward(
            params, CFG, next_toks[:1], pos[:1], c0, jnp.array([11])
        )
        solo1, _ = M.forward(
            params, CFG, next_toks[1:], pos[1:], c1, jnp.array([6])
        )
        np.testing.assert_allclose(out[0], solo0[0], atol=1e-4)
        np.testing.assert_allclose(out[1], solo1[0], atol=1e-4)

    def test_sharded_matches_local(self, params):
        B, S = 2, 8
        toks = jax.random.randint(jax.random.key(3), (B, S), 3, CFG.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        cache = M.make_empty_cache(CFG, B, 16, dtype=jnp.float32)
        lens = jnp.full((B,), S)
        local, _ = M.forward(params, CFG, toks, pos, cache, lens)
        mesh = make_mesh(tp=4, dp=2)
        sharded_params = place_params(params, param_shardings(CFG, mesh))
        sharded, _ = jax.jit(M.forward, static_argnums=1)(
            sharded_params, CFG, toks, pos, cache, lens
        )
        np.testing.assert_allclose(local, sharded, atol=1e-3)


class TestSampler:
    def test_greedy(self):
        logits = jnp.array([[0.0, 5.0, 1.0], [9.0, 0.0, 0.0]])
        out = sample(logits, jax.random.key(0), SamplingParams())
        assert out.tolist() == [1, 0]

    def test_top_k_restricts_support(self):
        logits = jnp.array([[10.0, 9.0, -5.0, -6.0]] * 64)
        out = sample(
            logits, jax.random.key(1), SamplingParams(temperature=1.0, top_k=2)
        )
        assert set(np.asarray(out).tolist()) <= {0, 1}

    def test_top_p_restricts_support(self):
        logits = jnp.array([[10.0, 1.0, 0.5, 0.1]] * 64)
        out = sample(
            logits, jax.random.key(2), SamplingParams(temperature=1.0, top_p=0.5)
        )
        assert set(np.asarray(out).tolist()) == {0}


class TestEngine:
    async def test_single_request_deterministic(self):
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=4, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4),
        )
        await engine.start()
        prompt = [1, 5, 9, 13]
        out1 = [t async for t in engine.generate(prompt, max_new_tokens=12)]
        out2 = [t async for t in engine.generate(prompt, max_new_tokens=12)]
        assert out1 == out2  # greedy: same prompt, same slot-independent result
        assert len(out1) == 12
        await engine.stop()

    async def test_continuous_batching_concurrent(self):
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=4, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4),
        )
        await engine.start()

        async def run(seed):
            prompt = [1 + seed, 2 + seed, 3 + seed]
            return [t async for t in engine.generate(prompt, max_new_tokens=8)]

        # 6 requests through 4 slots: forces queueing + slot reuse
        results = await asyncio.gather(*[run(i) for i in range(6)])
        assert all(len(r) == 8 for r in results)
        # same prompt -> same tokens regardless of slot/batch company
        again = await run(0)
        assert again == results[0]
        assert engine.stats.decode_tokens >= 6 * 8
        await engine.stop()

    async def test_batch_isolation(self):
        """A request's output must not change when other requests share the
        batch (masking/occupancy correctness)."""
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=4, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=2),
        )
        await engine.start()
        solo = [t async for t in engine.generate([7, 8, 9], max_new_tokens=10)]

        async def noise(i):
            return [t async for t in engine.generate([20 + i] * 5, max_new_tokens=10)]

        crowd_task = asyncio.gather(*[noise(i) for i in range(3)])
        crowded = [t async for t in engine.generate([7, 8, 9], max_new_tokens=10)]
        await crowd_task
        assert crowded == solo
        await engine.stop()

    async def test_prompt_too_long_rejected(self):
        engine = InferenceEngine(
            CFG, RuntimeConfig(max_batch_size=2, max_seq_len=32, prefill_chunk=16)
        )
        await engine.start()
        from calfkit_tpu.exceptions import InferenceError

        with pytest.raises(InferenceError):
            async for _ in engine.generate(list(range(40))):
                pass
        await engine.stop()


class TestLocalClient:
    async def test_request_roundtrip_bytes(self):
        from calfkit_tpu.engine.model_client import ModelRequestParameters
        from calfkit_tpu.inference.client import JaxLocalModelClient
        from calfkit_tpu.models.messages import user_message

        cfg = preset("debug")
        client = JaxLocalModelClient(
            config=cfg,
            runtime=RuntimeConfig(max_batch_size=2, max_seq_len=256,
                                  prefill_chunk=32),
            max_new_tokens=16,
        )
        resp = await client.request([user_message("hi")])
        assert resp.model_name == "debug"
        assert resp.usage.output_tokens > 0
        await client.stop()

    def test_tool_call_parser(self):
        from calfkit_tpu.inference.client import default_tool_call_parser

        text = 'Let me check.\n{"tool_name": "get_weather", "args": {"city": "SF"}}\nok'
        remaining, calls = default_tool_call_parser(text)
        assert calls[0].tool_name == "get_weather"
        assert calls[0].args == {"city": "SF"}
        assert "tool_name" not in remaining

    def test_render_messages_template(self):
        from calfkit_tpu.engine.model_client import ModelRequestParameters
        from calfkit_tpu.inference.client import render_messages
        from calfkit_tpu.models.capability import ToolDef
        from calfkit_tpu.models.messages import (
            ModelResponse,
            TextOutput,
            user_message,
        )

        text = render_messages(
            [
                user_message("hello"),
                ModelResponse(parts=[TextOutput(text="hi there")]),
                user_message("and again"),
            ],
            ModelRequestParameters(tool_defs=[ToolDef(name="t", description="d")]),
        )
        assert "<|user|>\nhello" in text
        assert "<|assistant|>\nhi there" in text
        assert '"tool_name"' in text  # tool grammar in system block
        assert text.endswith("<|assistant|>\n")


class TestEngineReviewRegressions:
    async def test_retire_during_prefill_no_phantom_slot(self):
        """max_new_tokens=1: the request retires inside its own prefill and
        must not leave a phantom _active[-1] busy-spinning the scheduler."""
        engine = InferenceEngine(
            CFG, RuntimeConfig(max_batch_size=2, max_seq_len=64, prefill_chunk=16,
                               decode_steps_per_dispatch=2)
        )
        await engine.start()
        out = [t async for t in engine.generate([1, 2, 3], max_new_tokens=1)]
        assert len(out) == 1
        await asyncio.sleep(0.1)
        assert engine._active == {}  # no phantom entry
        dispatches = engine.stats.decode_dispatches
        await asyncio.sleep(0.2)
        assert engine.stats.decode_dispatches == dispatches  # not spinning
        await engine.stop()

    async def test_stop_releases_queued_requests(self):
        """Requests still queued (not admitted) must get _DONE at stop."""
        engine = InferenceEngine(
            CFG, RuntimeConfig(max_batch_size=1, max_seq_len=64, prefill_chunk=16,
                               decode_steps_per_dispatch=2)
        )
        await engine.start()

        async def slow_request():
            return [t async for t in engine.generate([1, 2], max_new_tokens=40)]

        async def queued_request():
            return [t async for t in engine.generate([3, 4], max_new_tokens=40)]

        t1 = asyncio.create_task(slow_request())
        await asyncio.sleep(0.1)  # t1 occupies the only slot
        t2 = asyncio.create_task(queued_request())
        await asyncio.sleep(0.05)
        await engine.stop()
        done, pending = await asyncio.wait([t1, t2], timeout=2)
        assert not pending  # neither caller hangs


class TestQuantization:
    def test_quantized_forward_close_to_fp(self, params):
        from calfkit_tpu.inference.quant import quantize_params

        qparams = quantize_params(params)
        B, S = 2, 10
        toks = jax.random.randint(jax.random.key(7), (B, S), 3, CFG.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        lens = jnp.full((B,), S)
        cache = M.make_empty_cache(CFG, B, 32, dtype=jnp.float32)
        fp, _ = M.forward(params, CFG, toks, pos, cache, lens)
        cache2 = M.make_empty_cache(CFG, B, 32, dtype=jnp.float32)
        q, _ = M.forward(qparams, CFG, toks, pos, cache2, lens)
        # int8 weight-only: same top-1 predictions on a tiny random model is
        # too strict; require high logit correlation instead
        fp_f = np.asarray(fp, np.float32).ravel()
        q_f = np.asarray(q, np.float32).ravel()
        corr = np.corrcoef(fp_f, q_f)[0, 1]
        assert corr > 0.99, f"quantized logits diverged (corr={corr:.4f})"

    def test_quantized_sharded_placement(self, params):
        from calfkit_tpu.inference.quant import quantize_params, quantize_shardings
        from calfkit_tpu.inference.sharding import param_shardings, place_params

        mesh = make_mesh(tp=2, dp=1)
        qparams = quantize_params(params)
        qshard = quantize_shardings(param_shardings(CFG, mesh))
        placed = place_params(qparams, qshard)
        assert placed["layers"]["wq"]["q8"].dtype == jnp.int8

    async def test_engine_runs_int8(self):
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4, quantization="int8"),
        )
        await engine.start()
        out = [t async for t in engine.generate([1, 5, 9], max_new_tokens=10)]
        assert len(out) == 10
        again = [t async for t in engine.generate([1, 5, 9], max_new_tokens=10)]
        assert again == out  # deterministic under quantization too
        await engine.stop()


class TestInt4Quantization:
    """int4 weight-only (r5): packed nibbles + group-wise scales — half
    the decode weight stream of int8 again."""

    def test_pack_round_trip_is_exact_on_grid_values(self):
        from calfkit_tpu.inference.quant import dequant, quantize_tensor4

        # values that ARE representable (q * scale for q in [-7, 7]) must
        # survive quantize→dequant bit-exactly
        rng = np.random.default_rng(3)
        q = rng.integers(-7, 8, size=(4, 256, 6)).astype(np.float32)
        w = jnp.asarray(q * 0.035)  # one scale per whole axis group
        leaf = quantize_tensor4(w, (1,), group=128)
        key = next(k for k in leaf if k != "scale")
        assert leaf[key].dtype == jnp.uint8
        assert leaf[key].shape == (4, 128, 6)  # axis halved
        assert leaf["scale"].shape == (4, 2, 6)  # 256/128 groups
        back = dequant(leaf, jnp.float32)
        np.testing.assert_allclose(np.asarray(back), np.asarray(w), rtol=1e-6)

    def test_group_scales_beat_per_channel_on_outliers(self):
        from calfkit_tpu.inference.quant import dequant, quantize_tensor4

        # one huge outlier in group 0 must not destroy group 1's precision
        w = np.full((1, 256), 0.01, np.float32)
        w[0, 0] = 100.0
        leaf = quantize_tensor4(jnp.asarray(w), (1,), group=128)
        back = np.asarray(dequant(leaf, jnp.float32))
        assert abs(back[0, 0] - 100.0) < 100.0 / 7 + 1e-6
        # group 1 (no outlier) keeps small values accurately
        np.testing.assert_allclose(back[0, 128:], w[0, 128:], rtol=0.2)

    def test_host_and_device_quantizers_agree(self):
        from calfkit_tpu.inference.quant import (
            quantize_array_host,
            quantize_tensor4,
        )

        rng = np.random.default_rng(11)
        w = rng.standard_normal((3, 256, 4)).astype(np.float32)
        device = quantize_tensor4(jnp.asarray(w), (1,))
        host = quantize_array_host(w, (1,), bits=4)
        assert set(device) == set(host)
        key = next(k for k in device if k != "scale")
        np.testing.assert_array_equal(np.asarray(device[key]), host[key])
        np.testing.assert_allclose(
            np.asarray(device["scale"]), host["scale"], rtol=1e-6
        )

    def test_forward_parity_with_fp(self, params):
        """int4 logits track fp, and the error is QUANTIZATION noise (it
        shrinks monotonically as groups refine) — not an implementation
        bug.  On this 64-dim toy the default-group correlation ~0.95 is
        the intrinsic 4-bit floor (measured: g=64→0.948, g=4→0.983,
        g=2→0.993; real models average over 4096-wide fan-ins)."""
        from calfkit_tpu.inference.quant import (
            LAYER_REDUCTION_AXES,
            LM_HEAD_REDUCTION_AXES,
            quantize_tensor4,
        )

        B, S = 2, 10
        toks = jax.random.randint(jax.random.key(7), (B, S), 3, CFG.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        lens = jnp.full((B,), S)

        def logits(p):
            cache = M.make_empty_cache(CFG, B, 32, dtype=jnp.float32)
            out, _ = M.forward(p, CFG, toks, pos, cache, lens)
            return np.asarray(out, np.float32).ravel()

        def quantized(group):
            out = {"embed": params["embed"],
                   "final_norm": params["final_norm"], "layers": {}}
            for name, w in params["layers"].items():
                if name in LAYER_REDUCTION_AXES:
                    out["layers"][name] = quantize_tensor4(
                        w, LAYER_REDUCTION_AXES[name], group=group)
                else:
                    out["layers"][name] = w
            if "lm_head" in params:
                out["lm_head"] = quantize_tensor4(
                    params["lm_head"], LM_HEAD_REDUCTION_AXES, group=group)
            return out

        fp = logits(params)
        corr_default = np.corrcoef(fp, logits(quantized(128)))[0, 1]
        corr_fine = np.corrcoef(fp, logits(quantized(4)))[0, 1]
        assert corr_default > 0.93, f"int4 diverged (corr={corr_default:.4f})"
        assert corr_fine > 0.97, f"fine-group int4 diverged ({corr_fine:.4f})"
        # the noise-source pin: refining groups must REDUCE the error
        assert corr_fine > corr_default

    def test_sharded_placement_and_forward(self, params):
        from calfkit_tpu.inference.quant import (
            align_quant_sharding_keys,
            quantize_params,
            quantize_shardings,
        )
        from calfkit_tpu.inference.sharding import param_shardings, place_params

        mesh = make_mesh(tp=2, dp=1)
        qparams = quantize_params(params, bits=4)
        qshard = align_quant_sharding_keys(
            quantize_shardings(param_shardings(CFG, mesh), bits=4), qparams
        )
        placed = place_params(qparams, qshard)
        key = next(k for k in placed["layers"]["wq"] if k != "scale")
        assert placed["layers"]["wq"][key].dtype == jnp.uint8

    async def test_engine_runs_int4(self):
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4, quantization="int4"),
        )
        await engine.start()
        out = [t async for t in engine.generate([1, 5, 9], max_new_tokens=10)]
        assert len(out) == 10
        again = [t async for t in engine.generate([1, 5, 9], max_new_tokens=10)]
        assert again == out  # deterministic under quantization too
        await engine.stop()

    def test_bitness_mismatch_fails_loudly(self):
        from calfkit_tpu.inference.quant import random_quantized_params_host

        params = random_quantized_params_host(CFG, bits=4)
        with pytest.raises(ValueError, match="other bitness"):
            InferenceEngine(
                CFG,
                RuntimeConfig(max_batch_size=2, max_seq_len=64,
                              prefill_chunk=16, quantization="int8"),
                params=params,
            )

    async def test_int4_long_context_sp_lane(self):
        """int4 weights under the sequence-parallel ring-prefill lane:
        dequant of packed+grouped leaves must compile and serve inside
        shard_map over the sp mesh (weights replicated, sequence
        sharded)."""
        if len(jax.devices()) < 8:
            pytest.skip("needs the virtual 8-device mesh")
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=4, max_seq_len=64, prefill_chunk=16,
                          decode_steps_per_dispatch=4, long_context=True,
                          long_new_cap=8, tp=2, dp=4, quantization="int4"),
        )
        await engine.start()
        assert engine._sp_mesh().shape["sp"] == 8
        prompt = [(11 * i + 5) % CFG.vocab_size for i in range(100)]
        got = [t async for t in engine.generate(prompt, max_new_tokens=8)]
        assert len(got) == 8
        assert engine.stats.long_requests == 1
        await engine.stop()

    async def test_engine_runs_int4_paged_on_tp_mesh(self):
        """The 8B-shape path in miniature: host-built int4 params + paged
        KV on a tp=2 mesh (exercises the sharded unpack/reshape under
        GSPMD)."""
        from calfkit_tpu.inference.quant import random_quantized_params_host

        params = random_quantized_params_host(CFG, bits=4)
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=2, max_seq_len=64, prefill_chunk=16,
                          decode_steps_per_dispatch=4, quantization="int4",
                          kv_layout="paged", page_size=16, num_kv_pages=17,
                          tp=2, dp=1),
            params=params,
            mesh=make_mesh(tp=2, dp=1),
        )
        await engine.start()
        out = [t async for t in engine.generate([1, 5, 9], max_new_tokens=6)]
        assert len(out) == 6
        await engine.stop()


class TestPallasAttention:
    def test_interpret_matches_xla_merged(self, params):
        """The Pallas kernel (interpret mode) must match the XLA merged
        attention bit-for-tolerance on ragged lens + ring contents."""
        from calfkit_tpu.inference.model import _merged_decode_attention
        from calfkit_tpu.inference.pallas_attention import (
            merged_decode_attention_pallas,
        )

        B, K, G, hd, W, T = 3, CFG.n_kv_heads, CFG.n_heads // CFG.n_kv_heads, \
            CFG.head_dim, 32, 4
        ks = jax.random.split(jax.random.key(11), 5)
        q = jax.random.normal(ks[0], (B, 1, CFG.n_heads, hd), jnp.float32)
        kc = jax.random.normal(ks[1], (B, K, W, hd), jnp.float32)
        vc = jax.random.normal(ks[2], (B, K, W, hd), jnp.float32)
        rk = jax.random.normal(ks[3], (T, B, K, hd), jnp.float32)
        rv = jax.random.normal(ks[4], (T, B, K, hd), jnp.float32)
        lens = jnp.array([0, 7, 31])  # incl. a fresh row (len 0)
        for t in (0, 2, 3):
            ref = _merged_decode_attention(q, kc, vc, rk, rv, lens, jnp.int32(t))
            out = merged_decode_attention_pallas(
                q, kc, vc, rk, rv, lens, jnp.int32(t), interpret=True
            )
            np.testing.assert_allclose(
                np.asarray(ref, np.float32), np.asarray(out, np.float32),
                atol=2e-3, rtol=2e-3,
            )

    async def test_engine_runs_pallas_interpret(self):
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4,
                          attention_impl="pallas_interpret"),
        )
        await engine.start()
        out = [t async for t in engine.generate([1, 5, 9], max_new_tokens=8)]
        assert len(out) == 8
        await engine.stop()

        xla_engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4),
        )
        await xla_engine.start()
        ref = [t async for t in xla_engine.generate([1, 5, 9], max_new_tokens=8)]
        await xla_engine.stop()
        # NOTE: holds for these fixed seeds/prompts; on random-init weights
        # greedy argmax can amplify benign accumulation-order differences,
        # so don't extend this to arbitrary prompts (the numerical bound is
        # the allclose test above)
        assert out == ref  # same greedy tokens through either kernel


class TestPerRequestSampling:
    """Round-2: ModelSettings knobs ride per-slot device tensors, so one
    decode dispatch serves mixed greedy/sampled requests (ADVICE r1 medium)."""

    def _engine(self, max_batch_size=4):
        return InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=max_batch_size, max_seq_len=128,
                          prefill_chunk=16, decode_steps_per_dispatch=4),
        )

    async def test_seeded_sampling_reproducible(self):
        engine = self._engine()
        await engine.start()
        params = SamplingParams(temperature=1.2, top_k=50)
        prompt = [1, 5, 9, 13]
        out1 = [t async for t in engine.generate(
            prompt, max_new_tokens=12, sampling=params, seed=7)]
        out2 = [t async for t in engine.generate(
            prompt, max_new_tokens=12, sampling=params, seed=7)]
        assert out1 == out2  # same seed -> same stream, slot-independent
        assert len(out1) == 12
        await engine.stop()

    async def test_mixed_batch_greedy_rows_unaffected(self):
        engine = self._engine()
        await engine.start()
        prompt = [2, 4, 6]
        baseline = [t async for t in engine.generate(prompt, max_new_tokens=8)]

        async def sampled(i):
            return [t async for t in engine.generate(
                [3 + i, 7, 11], max_new_tokens=8,
                sampling=SamplingParams(temperature=1.5, top_p=0.9), seed=i)]

        async def greedy():
            return [t async for t in engine.generate(prompt, max_new_tokens=8)]

        results = await asyncio.gather(greedy(), sampled(1), sampled(2))
        assert results[0] == baseline  # sampled neighbors don't perturb greedy
        await engine.stop()

    async def test_abandoned_iterator_frees_slot(self):
        engine = self._engine(max_batch_size=2)
        await engine.start()
        agen = engine.generate([1, 2, 3], max_new_tokens=64)
        got = 0
        async for _ in agen:
            got += 1
            if got >= 2:
                break  # abandon mid-stream
        await agen.aclose()
        # engine must reclaim the slot and keep serving at full capacity
        outs = await asyncio.gather(*[
            _collect(engine.generate([5 + i, 6], max_new_tokens=6))
            for i in range(4)
        ])
        assert all(len(o) == 6 for o in outs)
        assert not engine._active
        assert sorted(engine._free) == [0, 1]
        await engine.stop()


async def _collect(agen):
    return [t async for t in agen]


class TestModelSettingsThreading:
    """JaxLocalModelClient honors per-request ModelSettings (ADVICE r1)."""

    def _client(self):
        from calfkit_tpu.inference.client import JaxLocalModelClient

        return JaxLocalModelClient(
            config=preset("debug"),
            runtime=RuntimeConfig(max_batch_size=2, max_seq_len=256,
                                  prefill_chunk=32,
                                  decode_steps_per_dispatch=4),
            max_new_tokens=24,
        )

    async def test_temperature_seed_reproducible(self):
        from calfkit_tpu.engine.model_client import ModelSettings
        from calfkit_tpu.models.messages import user_message

        client = self._client()
        settings = ModelSettings(temperature=0.9, top_k=40, seed=11)
        r1 = await client.request([user_message("hello")], settings)
        r2 = await client.request([user_message("hello")], settings)
        assert r1.text() == r2.text()
        await client.stop()

    async def test_stop_sequences_terminate(self):
        from calfkit_tpu.engine.model_client import ModelSettings
        from calfkit_tpu.models.messages import user_message

        client = self._client()
        free = await client.request([user_message("hi")])
        full = free.text()
        assert full  # byte tokenizer on random weights always emits text
        stop = full[1:3]  # a sequence the greedy model WILL produce
        r = await client.request(
            [user_message("hi")], ModelSettings(stop_sequences=[stop])
        )
        assert stop not in r.text()
        assert len(r.text()) < len(full)
        # the engine reclaims the cancelled slot at its next tick
        for _ in range(100):
            if not client._engine._active:
                break
            await asyncio.sleep(0.05)
        assert not client._engine._active
        await client.stop()

    async def test_max_tokens_respected(self):
        from calfkit_tpu.engine.model_client import ModelSettings
        from calfkit_tpu.models.messages import user_message

        client = self._client()
        r = await client.request(
            [user_message("hi")], ModelSettings(max_tokens=5)
        )
        assert r.usage.output_tokens <= 5
        await client.stop()


class TestQueuedCancellation:
    async def test_cancel_while_queued_drains_and_engine_stays_live(self):
        """A request cancelled BEFORE admission must be drained; the idle
        engine must keep awaiting (review r2: a skipped-but-present pending
        entry turned the serve loop into a busy spin)."""
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=1, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4),
        )
        await engine.start()

        async def long_req():
            return [t async for t in engine.generate([1, 2], max_new_tokens=24)]

        first = asyncio.create_task(long_req())
        await asyncio.sleep(0.3)  # first request admitted (slot occupied)
        queued = engine.generate([3, 4], max_new_tokens=24)
        starter = asyncio.create_task(anext(queued))
        await asyncio.sleep(0.1)  # body started: request enqueued, blocked
        starter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await starter
        await queued.aclose()
        assert (await first)  # original request completes
        # engine idles without spinning and still serves new work
        out = await asyncio.wait_for(
            _collect(engine.generate([5, 6], max_new_tokens=6)), timeout=30
        )
        assert len(out) == 6
        assert not engine._pending and not engine._active
        await engine.stop()


class TestPagedKV:
    """Paged KV cache (round 2): block-table pool, reserve-at-admission,
    trash-page masking.  Reference anchor: SURVEY §5 long-context / VERDICT
    r1 item 3."""

    def _engine(self, layout, **over):
        kw = dict(
            max_batch_size=4, max_seq_len=128, prefill_chunk=16,
            decode_steps_per_dispatch=4, page_size=16, kv_layout=layout,
        )
        kw.update(over)
        return InferenceEngine(CFG, RuntimeConfig(**kw), seed=3)

    async def test_paged_matches_dense_tokens(self):
        dense = self._engine("dense")
        paged = self._engine("paged")
        await dense.start()
        await paged.start()
        # lengths that cross page boundaries (page_size=16)
        prompts = [[1, 5, 9], list(range(2, 20)), list(range(3, 40))]
        for prompt in prompts:
            want = [t async for t in dense.generate(prompt, max_new_tokens=20)]
            got = [t async for t in paged.generate(prompt, max_new_tokens=20)]
            assert got == want, f"paged diverged for prompt len {len(prompt)}"
        await dense.stop()
        await paged.stop()

    async def test_oversubscribed_pool_admission_control(self):
        # pool of 9 usable pages; each request needs ceil((3+28+1)/16)=2
        # pages -> only 4 of 8 requests fit at once; the rest must wait and
        # ALL must complete, with full page accounting at the end
        engine = self._engine("paged", num_kv_pages=10)
        await engine.start()

        async def one(i):
            return [
                t async for t in engine.generate(
                    [1 + i, 2, 3], max_new_tokens=28
                )
            ]

        outs = await asyncio.gather(*[one(i) for i in range(8)])
        assert all(len(o) == 28 for o in outs)
        assert engine._page_alloc.free_pages == 9  # every page returned
        assert not engine._page_alloc.held_slots
        await engine.stop()

    async def test_page_reuse_no_cross_request_bleed(self):
        """A slot's pages are freed and reused; the new occupant's output
        must be identical to a fresh engine's (no stale KV bleed)."""
        engine = self._engine("paged", num_kv_pages=10)
        await engine.start()
        first = [t async for t in engine.generate([1, 5, 9], max_new_tokens=20)]
        # churn: different prompts through the same pages
        for i in range(3):
            [t async for t in engine.generate([7 + i, 8, 9, 10], max_new_tokens=12)]
        again = [t async for t in engine.generate([1, 5, 9], max_new_tokens=20)]
        assert again == first
        await engine.stop()

    async def test_cancel_returns_pages(self):
        engine = self._engine("paged")
        await engine.start()
        agen = engine.generate(list(range(2, 20)), max_new_tokens=40)
        got = 0
        async for _ in agen:
            got += 1
            if got >= 2:
                break
        await agen.aclose()
        out = [t async for t in engine.generate([4, 5], max_new_tokens=6)]
        assert len(out) == 6
        for _ in range(100):
            if not engine._page_alloc.held_slots:
                break
            await asyncio.sleep(0.05)
        assert not engine._page_alloc.held_slots
        await engine.stop()

    async def test_paged_pallas_interpret_matches_xla(self):
        xla = self._engine("paged")
        pal = self._engine("paged", attention_impl="pallas_interpret")
        await xla.start()
        await pal.start()
        prompt = list(range(2, 21))
        want = [t async for t in xla.generate(prompt, max_new_tokens=12)]
        got = [t async for t in pal.generate(prompt, max_new_tokens=12)]
        # NOTE fixed prompt/seed (see TestPallasAttention note on greedy
        # amplification of benign fp reordering)
        assert got == want
        await xla.stop()
        await pal.stop()

    async def test_128_streams_through_paged_blocks_sharded(self):
        """BASELINE config-5 shape proof: 128 concurrent streams decode
        through paged blocks on a tp=2 sharded virtual mesh, with the pool
        oversubscribed vs dense (VERDICT r1 item 3 acceptance)."""
        from calfkit_tpu.inference.sharding import make_mesh

        B = 128
        rt = RuntimeConfig(
            max_batch_size=B, max_seq_len=128, prefill_chunk=16,
            decode_steps_per_dispatch=4, page_size=16, kv_layout="paged",
            # dense equivalent would need B*8=1024 pages; give 2 pages per
            # stream (prompt+16 new tokens fits) + trash
            num_kv_pages=2 * B + 1, tp=2,
        )
        engine = InferenceEngine(CFG, rt, mesh=make_mesh(tp=2), seed=5)
        await engine.start()

        async def one(i):
            return [
                t async for t in engine.generate(
                    [1 + (i % 50), 3, 5], max_new_tokens=16
                )
            ]

        outs = await asyncio.gather(*[one(i) for i in range(160)])
        assert all(len(o) == 16 for o in outs)
        assert engine._page_alloc.free_pages == 2 * B
        await engine.stop()

    async def test_unservable_reservation_rejected_loudly(self):
        """A request the pool could NEVER fit raises instead of queueing
        forever (review r2)."""
        engine = self._engine("paged", num_kv_pages=4)  # 3 usable pages
        await engine.start()
        with pytest.raises(Exception, match="KV pages"):
            async for _ in engine.generate([1, 2, 3], max_new_tokens=100):
                pass
        # engine still serves right-sized work
        out = [t async for t in engine.generate([1, 2], max_new_tokens=8)]
        assert len(out) == 8
        await engine.stop()

    def test_unaligned_max_seq_rejected(self):
        with pytest.raises(ValueError, match="max_seq_len"):
            InferenceEngine(
                CFG,
                RuntimeConfig(max_batch_size=2, max_seq_len=120,
                              prefill_chunk=16, page_size=16,
                              kv_layout="paged"),
            )


class TestRandomQuantizedParams:
    async def test_host_built_int8_params_serve_paged(self):
        """The 8B bench path in miniature: host-generated int8 params +
        paged KV + int8 runtime serve end-to-end."""
        from calfkit_tpu.inference.quant import random_quantized_params_host

        params = random_quantized_params_host(CFG)
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=2, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4, page_size=16,
                          kv_layout="paged", quantization="int8"),
            params=params,
        )
        await engine.start()
        out = [t async for t in engine.generate([1, 5, 9], max_new_tokens=8)]
        assert len(out) == 8
        out2 = [t async for t in engine.generate([1, 5, 9], max_new_tokens=8)]
        assert out2 == out  # deterministic through the quantized path
        await engine.stop()


class TestChunkedPrefill:
    """Opt-in chunked admission: long prompts advance one chunk per
    scheduler pass with decode ticks in between (round 2)."""

    def _engine(self, layout="dense", chunk=16, **over):
        kw = dict(
            max_batch_size=4, max_seq_len=128, prefill_chunk=chunk,
            decode_steps_per_dispatch=4, page_size=16, kv_layout=layout,
            chunked_prefill=True,
        )
        kw.update(over)
        return InferenceEngine(CFG, RuntimeConfig(**kw), seed=3)

    async def test_chunked_matches_single_shot(self):
        plain = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=4, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4),
            seed=3,
        )
        chunked = self._engine()
        await plain.start()
        await chunked.start()
        # one-chunk, exact-multiple, and straddling lengths
        for prompt in ([1, 5, 9], list(range(2, 34)), list(range(3, 60))):
            want = [t async for t in plain.generate(prompt, max_new_tokens=16)]
            got = [t async for t in chunked.generate(prompt, max_new_tokens=16)]
            assert got == want, f"chunked diverged at len {len(prompt)}"
        await plain.stop()
        await chunked.stop()

    async def test_chunked_paged_matches_dense(self):
        dense = self._engine("dense")
        paged = self._engine("paged")
        await dense.start()
        await paged.start()
        prompt = list(range(2, 50))
        want = [t async for t in dense.generate(prompt, max_new_tokens=12)]
        got = [t async for t in paged.generate(prompt, max_new_tokens=12)]
        assert got == want
        await dense.stop()
        await paged.stop()

    async def test_decode_progresses_during_long_prefill(self):
        """The whole point: an active stream keeps emitting while a long
        admission is in flight."""
        engine = self._engine(chunk=16, max_seq_len=256)
        await engine.start()
        # occupy a slot with an active stream
        active = engine.generate([1, 2], max_new_tokens=200)
        times: list[float] = []

        async def consume_active():
            async for _ in active:
                times.append(time.perf_counter())

        consumer = asyncio.create_task(consume_active())
        await asyncio.sleep(0.5)  # stream is decoding
        before = len(times)
        # a LONG prompt (8 chunks): chunked admission interleaves
        long_out = [
            t async for t in engine.generate(
                list(range(2, 130)), max_new_tokens=8
            )
        ]
        assert len(long_out) == 8
        during = len(times) - before
        assert during > 0, "active stream starved during long admission"
        consumer.cancel()
        with pytest.raises(asyncio.CancelledError):
            await consumer
        await active.aclose()
        await engine.stop()

    async def test_stop_mid_inflight_releases_waiters(self):
        engine = self._engine(chunk=16, max_seq_len=256)
        await engine.start()
        agen = engine.generate(list(range(2, 130)), max_new_tokens=8)
        starter = asyncio.create_task(anext(agen))
        await asyncio.sleep(0.05)  # admission likely mid-chunk
        await engine.stop()
        with pytest.raises((StopAsyncIteration, asyncio.CancelledError)):
            await starter
        await agen.aclose()

    async def test_sampled_chunked_reproducible(self):
        engine = self._engine()
        await engine.start()
        params = SamplingParams(temperature=1.1, top_k=30)
        prompt = list(range(2, 40))
        out1 = [t async for t in engine.generate(
            prompt, max_new_tokens=10, sampling=params, seed=5)]
        out2 = [t async for t in engine.generate(
            prompt, max_new_tokens=10, sampling=params, seed=5)]
        assert out1 == out2
        await engine.stop()

    def test_unaligned_chunking_rejected(self):
        with pytest.raises(ValueError, match="chunked_prefill"):
            InferenceEngine(
                CFG,
                RuntimeConfig(max_batch_size=2, max_seq_len=120,
                              prefill_chunk=16, chunked_prefill=True),
            )

    async def test_fully_cancelled_inflight_wave_aborts(self):
        engine = self._engine(chunk=16, max_seq_len=256, layout="paged")
        await engine.start()
        agen = engine.generate(list(range(2, 130)), max_new_tokens=8)
        starter = asyncio.create_task(anext(agen))
        await asyncio.sleep(0.1)  # admission in flight
        starter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await starter
        await agen.aclose()
        for _ in range(100):
            if engine._inflight is None and not engine._page_alloc.held_slots:
                break
            await asyncio.sleep(0.05)
        assert engine._inflight is None
        assert not engine._page_alloc.held_slots  # reservation released
        # engine still serves
        out = [t async for t in engine.generate([4, 5], max_new_tokens=6)]
        assert len(out) == 6
        await engine.stop()


class TestLongContextLane:
    """Prompts beyond max_seq_len served through the engine's
    sequence-parallel lane (ring prefill + context-parallel decode),
    unified with the slot scheduler (PARITY known-gap closure)."""

    @staticmethod
    def _params():
        return M.init_params(CFG, jax.random.key(3), dtype=jnp.float32)

    def _long_engine(self, params, **rt):
        defaults = dict(
            max_batch_size=2, max_seq_len=64, prefill_chunk=16,
            decode_steps_per_dispatch=4, long_context=True, long_new_cap=16,
        )
        defaults.update(rt)
        return InferenceEngine(CFG, RuntimeConfig(**defaults), params=params)

    async def test_long_prompt_matches_short_lane(self):
        """The same 100-token prompt produces identical greedy tokens via
        the long lane (max_seq_len=64 engine) and via the ordinary short
        lane of a roomier engine — one merge law everywhere."""
        params = self._params()
        prompt = [(7 * i + 3) % CFG.vocab_size for i in range(100)]

        long_engine = self._long_engine(params)
        await long_engine.start()
        got = [t async for t in long_engine.generate(prompt, max_new_tokens=8)]
        assert long_engine.stats.long_requests == 1
        await long_engine.stop()

        ref_engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=2, max_seq_len=256, prefill_chunk=16,
                          decode_steps_per_dispatch=4),
            params=params,
        )
        await ref_engine.start()
        want = [t async for t in ref_engine.generate(prompt, max_new_tokens=8)]
        await ref_engine.stop()
        assert got == want

    async def test_long_and_short_interleave(self):
        """Short requests keep streaming while a long request is served."""
        params = self._params()
        engine = self._long_engine(params)
        await engine.start()
        long_prompt = [(3 * i + 1) % CFG.vocab_size for i in range(90)]

        async def long_run():
            return [t async for t in engine.generate(long_prompt, max_new_tokens=12)]

        async def short_run(i):
            return [t async for t in engine.generate([5 + i, 6, 7], max_new_tokens=6)]

        long_out, *short_outs = await asyncio.gather(
            long_run(), short_run(0), short_run(1), short_run(2)
        )
        assert len(long_out) == 12
        assert all(len(s) == 6 for s in short_outs)
        # short lane answers are unaffected by the long company
        solo = [t async for t in engine.generate([5, 6, 7], max_new_tokens=6)]
        assert short_outs[0] == solo
        await engine.stop()

    async def test_long_request_cancellation_reaps(self):
        params = self._params()
        engine = self._long_engine(params, long_new_cap=32)
        await engine.start()
        prompt = [(i + 2) % CFG.vocab_size for i in range(80)]
        agen = engine.generate(prompt, max_new_tokens=32)
        got = [await anext(agen)]  # first token arrived: lane is active
        await agen.aclose()  # abandon mid-generation -> cancel
        for _ in range(100):
            if engine._long is None and not engine._long_pending:
                break
            await asyncio.sleep(0.05)
        assert engine._long is None
        # lane still serves the next long request
        out = [t async for t in engine.generate(prompt, max_new_tokens=4)]
        assert len(out) == 4 and out[0] == got[0]
        await engine.stop()

    async def test_long_disabled_rejects(self):
        engine = InferenceEngine(
            CFG, RuntimeConfig(max_batch_size=2, max_seq_len=32, prefill_chunk=16)
        )
        await engine.start()
        from calfkit_tpu.exceptions import InferenceError

        with pytest.raises(InferenceError, match="long_context"):
            async for _ in engine.generate(list(range(40))):
                pass
        await engine.stop()

    async def test_long_prompt_ceiling_rejects(self):
        params = self._params()
        engine = self._long_engine(params, long_max_prompt=128)
        await engine.start()
        from calfkit_tpu.exceptions import InferenceError

        with pytest.raises(InferenceError, match="long_max_prompt"):
            async for _ in engine.generate(list(range(200))):
                pass
        await engine.stop()

    async def test_long_max_new_over_cap_faults(self):
        """A long request whose token budget exceeds long_new_cap FAULTS
        with a typed error by default — the engine must not silently
        rewrite the caller's budget (the pre-r6 clamp corrupted downstream
        accounting that trusted max_new_tokens)."""
        from calfkit_tpu.exceptions import InferenceError

        params = self._params()
        engine = self._long_engine(params, long_new_cap=8)
        await engine.start()
        prompt = [(i + 9) % CFG.vocab_size for i in range(70)]
        with pytest.raises(InferenceError, match="long_new_cap"):
            async for _ in engine.generate(prompt, max_new_tokens=1000):
                pass
        # the lane still serves a within-budget request afterwards
        out = [t async for t in engine.generate(prompt, max_new_tokens=4)]
        assert len(out) == 4
        await engine.stop()

    async def test_long_max_new_clamped_only_with_optin(self):
        """long_clamp_new_tokens=True restores clamping as an explicit
        negotiation (the old silent default)."""
        params = self._params()
        engine = self._long_engine(
            params, long_new_cap=8, long_clamp_new_tokens=True
        )
        await engine.start()
        prompt = [(i + 9) % CFG.vocab_size for i in range(70)]
        out = [t async for t in engine.generate(prompt, max_new_tokens=1000)]
        assert len(out) == 8  # clamped to the cap, not hung, not 1000
        await engine.stop()

    async def test_long_lane_sp8_over_full_mesh(self):
        """On a dp=4 x tp=2 engine mesh the long lane shards the sequence
        over ALL 8 devices (sp=8 ring) — tokens still match the short lane
        bit-for-bit (greedy)."""
        if len(jax.devices()) < 8:
            pytest.skip("needs the virtual 8-device mesh")
        params = self._params()
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=4, max_seq_len=64, prefill_chunk=16,
                          decode_steps_per_dispatch=4, long_context=True,
                          long_new_cap=8, tp=2, dp=4),
            params=params,
        )
        await engine.start()
        assert engine._sp_mesh().shape["sp"] == 8
        prompt = [(11 * i + 5) % CFG.vocab_size for i in range(100)]
        got = [t async for t in engine.generate(prompt, max_new_tokens=8)]
        await engine.stop()

        ref_engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=2, max_seq_len=256, prefill_chunk=16,
                          decode_steps_per_dispatch=4),
            params=params,
        )
        await ref_engine.start()
        want = [t async for t in ref_engine.generate(prompt, max_new_tokens=8)]
        await ref_engine.stop()
        assert got == want

    async def test_chunked_long_prefill_matches_monolithic(self):
        """With chunked_prefill=True the long lane prefills one chunk per
        scheduler pass (resumable, short ticks between chunks) — and the
        greedy tokens match the monolithic ring-prefill path exactly."""
        params = self._params()
        prompt = [(13 * i + 2) % CFG.vocab_size for i in range(100)]

        mono = self._long_engine(params)
        await mono.start()
        want = [t async for t in mono.generate(prompt, max_new_tokens=8)]
        await mono.stop()

        chunked = self._long_engine(params, chunked_prefill=True)
        await chunked.start()
        got = [t async for t in chunked.generate(prompt, max_new_tokens=8)]
        assert chunked.stats.long_requests == 1
        await chunked.stop()
        assert got == want

    async def test_short_streams_progress_during_chunked_long_prefill(self):
        """A long admission must not starve active short streams: with
        chunked_prefill the long prefill yields between chunks."""
        params = self._params()
        engine = self._long_engine(params, chunked_prefill=True)
        await engine.start()
        during_prefill = 0

        async def short_stream():
            nonlocal during_prefill
            out = []
            async for t in engine.generate([5, 6, 7], max_new_tokens=24):
                if engine._long_inflight is not None:
                    during_prefill += 1
                out.append(t)
            return out

        # park a short stream first so decode ticks are interleaving
        short_task = asyncio.create_task(short_stream())
        await asyncio.sleep(0.05)
        long_prompt = [(i + 4) % CFG.vocab_size for i in range(120)]
        long_out = [
            t async for t in engine.generate(long_prompt, max_new_tokens=8)
        ]
        short_out = await short_task
        assert len(long_out) == 8 and len(short_out) == 24
        # the ACTUAL interleaving observable: short tokens arrived while the
        # long prefill was mid-flight (a monolithic stall would leave 0)
        assert during_prefill > 0
        # the short stream's answer is company-independent
        solo = [t async for t in engine.generate([5, 6, 7], max_new_tokens=24)]
        assert short_out == solo
        await engine.stop()

    @staticmethod
    async def _collect(engine, prompt, n):
        return [t async for t in engine.generate(prompt, max_new_tokens=n)]

    async def test_chunked_long_prefill_cancellation_mid_flight(self):
        params = self._params()
        engine = self._long_engine(params, chunked_prefill=True)
        await engine.start()
        prompt = [(i + 1) % CFG.vocab_size for i in range(120)]
        agen = engine.generate(prompt, max_new_tokens=16)
        starter = asyncio.create_task(anext(agen))
        await asyncio.sleep(0.05)  # admission likely mid-chunk
        starter.cancel()
        with pytest.raises(asyncio.CancelledError):
            await starter
        await agen.aclose()
        for _ in range(100):
            if engine._long_inflight is None and engine._long is None:
                break
            await asyncio.sleep(0.05)
        assert engine._long_inflight is None and engine._long is None
        # lane still serves
        out = [t async for t in engine.generate(prompt, max_new_tokens=4)]
        assert len(out) == 4
        await engine.stop()

    async def test_chunked_long_prefill_sp8(self):
        """Chunked long prefill over a genuinely sequence-sharded scratch
        (sp=8): GSPMD shards each chunk's attention; tokens match the
        single-device short lane."""
        if len(jax.devices()) < 8:
            pytest.skip("needs the virtual 8-device mesh")
        params = self._params()
        prompt = [(17 * i + 3) % CFG.vocab_size for i in range(100)]
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=4, max_seq_len=64, prefill_chunk=16,
                          decode_steps_per_dispatch=4, long_context=True,
                          long_new_cap=8, tp=2, dp=4, chunked_prefill=True),
            params=params,
        )
        await engine.start()
        got = [t async for t in engine.generate(prompt, max_new_tokens=8)]
        await engine.stop()

        ref = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=2, max_seq_len=256, prefill_chunk=16,
                          decode_steps_per_dispatch=4),
            params=params,
        )
        await ref.start()
        want = [t async for t in ref.generate(prompt, max_new_tokens=8)]
        await ref.stop()
        assert got == want


class TestEngineStress:
    async def test_churn_with_random_cancels_leaks_nothing(self):
        """40 requests through 4 slots with a third of consumers abandoning
        mid-stream: every slot, page, and queue must come back."""
        import random

        rng = random.Random(7)
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=4, max_seq_len=128, prefill_chunk=16,
                          decode_steps_per_dispatch=4, kv_layout="paged",
                          page_size=16),
        )
        await engine.start()

        from tests.conftest import churn_abandon, drain_engine

        counts = await asyncio.gather(*[
            churn_abandon(engine, [2 + (i % 17), 3, 4, 5 + (i % 7)], rng)
            for i in range(40)
        ])
        assert all(c >= 2 for c in counts)
        # drain: all slots free, no pages held, nothing pending
        await drain_engine(engine)
        # loud on timeout: a leak in ANY of the four pools must fail, not
        # silently fall through the wait loop
        assert not engine._active and not engine._pending and not engine._carry
        assert sorted(engine._free) == list(range(4))
        assert not engine._page_alloc.held_slots
        # the retire heap must not pin any retired request's memory: every
        # surviving entry has its request reference nulled (r3 advisor)
        assert all(e[2] is None for e in engine._retire_heap)
        # engine still serves correctly after the churn
        out = [t async for t in engine.generate([9, 9, 9], max_new_tokens=5)]
        assert len(out) == 5
        await engine.stop()


class TestRetireHeap:
    """The bound-retirement heap's cross-thread discipline (VERDICT r3
    weak #5): early retirements null their entry, nulled entries pop
    lazily in _retirement_near, and compaction keeps the heap O(active)."""

    def _engine(self, bs: int = 2) -> InferenceEngine:
        return InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=bs, max_seq_len=128,
                          prefill_chunk=16, decode_steps_per_dispatch=4,
                          kv_layout="paged", page_size=16),
        )

    async def test_cancel_mid_stream_nulls_entry_and_lazy_pops(self):
        """Cancel a request whose bound sits at the heap TOP: the nulled
        entry must pop lazily inside _retirement_near, leaving the later
        bound visible — lazy invalidation breaking would either crash the
        peek or starve the short-dispatch TTFT lever."""
        engine = self._engine()
        await engine.start()

        # B holds the FAR bound; A (near bound) will sit at the heap top
        b_gen = engine.generate([7, 8, 9], max_new_tokens=90)
        b_iter = b_gen.__aiter__()
        await b_iter.__anext__()
        a_gen = engine.generate([3, 4, 5], max_new_tokens=30)
        got = 0
        async for _ in a_gen:
            got += 1
            if got == 2:
                break
        await a_gen.aclose()  # cancel A mid-stream
        for _ in range(200):
            if len(engine._active) == 1:
                break
            await asyncio.sleep(0.02)
        assert len(engine._active) == 1  # only B remains
        with engine._retire_lock:
            entries = list(engine._retire_heap)
        # A's entry is nulled (no memory pinned) or already compacted away
        live = [e for e in entries if e[2] is not None]
        assert all(e[2].slot != -1 for e in live)
        # the peek skips any stale top and still sees B's bound
        assert engine._retirement_near(10**6) is True
        with engine._retire_lock:
            assert all(e[2] is not None for e in engine._retire_heap[:1])
        await b_gen.aclose()
        await engine.stop()

    async def test_sustained_cancels_compact_heap(self):
        """Many early retirements must not grow the heap unboundedly:
        compaction rebuilds once nulled entries outnumber live ones."""
        engine = self._engine(bs=4)
        await engine.start()
        for i in range(30):
            agen = engine.generate([2 + (i % 9), 3], max_new_tokens=50)
            async for _ in agen:
                break  # first token then abandon
            await agen.aclose()
        for _ in range(200):
            if not engine._active:
                break
            await asyncio.sleep(0.02)
        assert not engine._active
        with engine._retire_lock:
            heap_len = len(engine._retire_heap)
            stale = engine._retire_stale
        # 30 tracked + 30 cancelled: without compaction the heap would hold
        # 30 corpses; with it, stale entries never exceed live ones + 1
        assert heap_len <= 8, heap_len
        assert stale * 2 <= heap_len + 1
        # still serves
        out = [t async for t in engine.generate([9, 9], max_new_tokens=5)]
        assert len(out) == 5
        await engine.stop()


class TestPallasPrefillAttention:
    """Flash-prefill kernel parity vs the XLA einsum path (interpret mode)."""

    def _parity(self, B, Sq, H, K, hd, Skv, q_pos, lens, **kw):
        import numpy as np

        from calfkit_tpu.inference.model import attention_xla
        from calfkit_tpu.inference.pallas_attention import (
            prefill_attention_pallas,
        )

        ks = jax.random.split(jax.random.key(B * Sq + Skv), 3)
        q = jax.random.normal(ks[0], (B, Sq, H, hd), jnp.float32)
        kc = jax.random.normal(ks[1], (B, K, Skv, hd), jnp.float32)
        vc = jax.random.normal(ks[2], (B, K, Skv, hd), jnp.float32)
        ref = attention_xla(q, kc, vc, q_pos, lens)
        out = prefill_attention_pallas(
            q, kc, vc, q_pos, lens, interpret=True, **kw
        )
        np.testing.assert_allclose(
            np.asarray(ref, np.float32), np.asarray(out, np.float32),
            atol=2e-3, rtol=2e-3,
        )

    def test_gqa_causal_parity(self):
        B, Sq, H, K, hd, Skv = 2, 32, 8, 2, 64, 32
        q_pos = jnp.broadcast_to(jnp.arange(Sq), (B, Sq))
        lens = jnp.array([Sq, Sq], jnp.int32)
        self._parity(B, Sq, H, K, hd, Skv, q_pos, lens)

    def test_mha_ragged_lens(self):
        # rows whose valid kv is shorter than the cache extent
        B, Sq, H, K, hd, Skv = 3, 16, 4, 4, 64, 64
        q_pos = jnp.broadcast_to(jnp.arange(Sq), (B, Sq))
        lens = jnp.array([16, 9, 3], jnp.int32)
        self._parity(B, Sq, H, K, hd, Skv, q_pos, lens)

    def test_chunk_at_offset_sees_prior_prefix(self):
        # chunked prefill: queries at positions [32..48) over a 64-cache
        B, Sq, H, K, hd, Skv = 2, 16, 8, 4, 64, 64
        q_pos = jnp.broadcast_to(32 + jnp.arange(Sq), (B, Sq))
        lens = jnp.array([48, 48], jnp.int32)
        self._parity(B, Sq, H, K, hd, Skv, q_pos, lens)

    def test_multiple_q_blocks_and_kv_chunks(self):
        # forces the grid (nq=2) AND the inner kv loop (n_chunks=4)
        B, Sq, H, K, hd, Skv = 1, 64, 8, 2, 64, 128
        q_pos = jnp.broadcast_to(jnp.arange(Sq), (B, Sq))
        lens = jnp.array([Sq], jnp.int32)
        self._parity(B, Sq, H, K, hd, Skv, q_pos, lens,
                     block_q=32, kv_chunk=32)

    def test_ineligible_shapes_raise(self):
        import pytest

        from calfkit_tpu.inference.pallas_attention import (
            prefill_attention_pallas,
        )

        q = jnp.zeros((1, 130, 4, 64), jnp.float32)  # 130 % 128 != 0
        kc = jnp.zeros((1, 4, 256, 64), jnp.float32)
        q_pos = jnp.zeros((1, 130), jnp.int32)
        with pytest.raises(ValueError, match="block_q"):
            prefill_attention_pallas(
                q, kc, kc, q_pos, jnp.array([130], jnp.int32), interpret=True
            )

    def test_dispatch_falls_back_to_xla_when_ineligible(self):
        import numpy as np

        from calfkit_tpu.inference.model import (
            attention_xla,
            prefill_attention,
        )

        B, Sq, H, K, hd, Skv = 1, 130, 4, 4, 64, 256  # Sq not blockable
        ks = jax.random.split(jax.random.key(7), 3)
        q = jax.random.normal(ks[0], (B, Sq, H, hd), jnp.float32)
        kc = jax.random.normal(ks[1], (B, K, Skv, hd), jnp.float32)
        vc = jax.random.normal(ks[2], (B, K, Skv, hd), jnp.float32)
        q_pos = jnp.broadcast_to(jnp.arange(Sq), (B, Sq))
        lens = jnp.array([Sq], jnp.int32)
        out = prefill_attention(q, kc, vc, q_pos, lens,
                                attn_impl="pallas_interpret")
        np.testing.assert_allclose(
            np.asarray(attention_xla(q, kc, vc, q_pos, lens), np.float32),
            np.asarray(out, np.float32), atol=1e-5, rtol=1e-5,
        )


class TestAttnAutoResolution:
    """attention_impl="auto" resolves per-path from the profile artifact
    (VERDICT r3 item 8: the Pallas flip is evidence-based and automatic on
    the first hardware profile)."""

    def _rt(self) -> RuntimeConfig:
        return RuntimeConfig(max_batch_size=2, max_seq_len=128,
                             prefill_chunk=16)

    def test_auto_resolves_per_path_from_artifact(self, tmp_path, monkeypatch):
        import json

        platform = jax.devices()[0].platform
        artifact = tmp_path / "attn.json"
        artifact.write_text(json.dumps({
            "platform": platform,
            "winners": {"decode": "pallas_interpret", "paged_decode": "xla"},
        }))
        monkeypatch.setenv("CALFKIT_ATTN_PROFILE", str(artifact))
        engine = InferenceEngine(CFG, self._rt())
        assert engine._resolved_attn_impl("decode") == "pallas_interpret"
        assert engine._resolved_attn_impl("paged_decode") == "xla"
        # no verdict for this path -> the safe default
        assert engine._resolved_attn_impl("prefill") == "xla"

    def test_platform_mismatch_keeps_xla(self, tmp_path, monkeypatch):
        import json

        artifact = tmp_path / "attn_tpu.json"
        artifact.write_text(json.dumps({
            "platform": "tpu", "winners": {"decode": "pallas"},
        }))
        monkeypatch.setenv("CALFKIT_ATTN_PROFILE", str(artifact))
        engine = InferenceEngine(CFG, self._rt())
        # a TPU verdict must not steer this CPU run
        assert engine._resolved_attn_impl("decode") == "xla"

    def test_explicit_impl_bypasses_artifact(self, tmp_path, monkeypatch):
        import json
        from dataclasses import replace

        artifact = tmp_path / "attn2.json"
        artifact.write_text(json.dumps({
            "platform": jax.devices()[0].platform,
            "winners": {"decode": "xla"},
        }))
        monkeypatch.setenv("CALFKIT_ATTN_PROFILE", str(artifact))
        engine = InferenceEngine(
            CFG, replace(self._rt(), attention_impl="pallas_interpret")
        )
        assert engine._resolved_attn_impl("decode") == "pallas_interpret"

    def test_missing_artifact_defaults_xla(self, monkeypatch):
        monkeypatch.setenv("CALFKIT_ATTN_PROFILE", "/nonexistent/attn.json")
        engine = InferenceEngine(CFG, self._rt())
        assert engine._resolved_attn_impl("decode") == "xla"

    def test_compute_winners_requires_sweep(self):
        """Pallas must beat XLA on EVERY config of a path (with margin) to
        win it; one losing shape keeps the safe default."""
        import importlib.util
        import os

        spec = importlib.util.spec_from_file_location(
            "profile_attention",
            os.path.join(os.path.dirname(__file__), "..", "scripts",
                         "profile_attention.py"),
        )
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)

        rows = [
            {"path": "decode", "config": "a", "impl": "xla",
             "ms_per_dispatch": 10.0},
            {"path": "decode", "config": "a", "impl": "pallas",
             "ms_per_dispatch": 8.0},
            {"path": "paged_decode", "config": "b", "impl": "xla",
             "ms_per_dispatch": 10.0},
            {"path": "paged_decode", "config": "b", "impl": "pallas",
             "ms_per_dispatch": 9.0},
            {"path": "paged_decode", "config": "c", "impl": "xla",
             "ms_per_dispatch": 10.0},
            {"path": "paged_decode", "config": "c", "impl": "pallas",
             "ms_per_dispatch": 11.0},  # loses one shape
            {"path": "prefill", "config": "d", "impl": "xla",
             "ms_per_dispatch": 10.0},
            {"path": "prefill", "config": "d", "impl": "pallas",
             "ms_per_dispatch": 9.9},  # within noise margin: not a win
        ]
        winners = mod.compute_winners(rows)
        assert winners == {
            "decode": "pallas", "paged_decode": "xla", "prefill": "xla",
        }


class TestPrefillWaveWidth:
    """max_prefill_wave: admission-wave width is a serving knob (burst
    TTFT vs prefill-scratch memory), power-of-two trimmed."""

    async def test_wide_wave_admits_in_one_dispatch(self):
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=16, max_seq_len=128,
                          prefill_chunk=16, decode_steps_per_dispatch=4,
                          max_prefill_wave=16),
        )
        waves: list[int] = []
        original = engine._prefill_wave

        def spy(wave, bucket):
            waves.append(len(wave))
            return original(wave, bucket)

        engine._prefill_wave = spy
        await engine.start()
        outs = await asyncio.gather(*[
            _gen_n(engine, [2 + i, 3, 4], 6) for i in range(16)
        ])
        assert all(len(o) == 6 for o in outs)
        # a drained 16-slot batch fills in far fewer dispatches than the
        # old fixed cap of 8 would allow; the widest wave used the knob
        assert max(waves) > 8, waves
        await engine.stop()

    async def test_narrow_wave_caps_at_one(self):
        engine = InferenceEngine(
            CFG,
            RuntimeConfig(max_batch_size=4, max_seq_len=128,
                          prefill_chunk=16, decode_steps_per_dispatch=4,
                          max_prefill_wave=1),
        )
        waves: list[int] = []
        original = engine._prefill_wave

        def spy(wave, bucket):
            waves.append(len(wave))
            return original(wave, bucket)

        engine._prefill_wave = spy
        await engine.start()
        outs = await asyncio.gather(*[
            _gen_n(engine, [2 + i, 3], 5) for i in range(6)
        ])
        assert all(len(o) == 5 for o in outs)
        assert set(waves) == {1}
        await engine.stop()

    def test_invalid_width_rejected(self):
        with pytest.raises(ValueError, match="max_prefill_wave"):
            InferenceEngine(
                CFG,
                RuntimeConfig(max_batch_size=2, max_seq_len=128,
                              prefill_chunk=16, max_prefill_wave=0),
            )


async def _gen_n(engine, prompt, n):
    return [t async for t in engine.generate(prompt, max_new_tokens=n)]


class TestPrefillWaveValidation:
    def test_non_power_of_two_rejected_loudly(self):
        with pytest.raises(ValueError, match="power of two"):
            InferenceEngine(
                CFG,
                RuntimeConfig(max_batch_size=16, max_seq_len=128,
                              prefill_chunk=16, max_prefill_wave=12),
            )
