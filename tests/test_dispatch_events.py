"""Direct unit coverage for the concurrency substrate: the key-ordered
dispatcher, the firehose stream, and client handle cancel-safety.

Reference anchors: the key-ordered subscriber semantics
(calfkit/_faststream_ext/_subscriber.py:102-350 — lanes, serial-per-key,
bounded in-flight, graceful drain, keyless warning, semaphore tripwire) and
the firehose (client/events.py:26-157 — bounded drop-oldest + counter).
"""

from __future__ import annotations

import asyncio

import pytest

from calfkit_tpu.mesh.dispatch import KeyOrderedDispatcher
from calfkit_tpu.mesh.transport import Record


def _rec(key: bytes | None, value: bytes = b"") -> Record:
    return Record(topic="t", key=key, value=value)


class TestKeyOrderedDispatcher:
    async def test_serial_per_key_parallel_across_keys(self):
        """A slow key must not block other keys; per-key order holds."""
        order: dict[bytes, list[int]] = {}
        slow_started = asyncio.Event()
        release_slow = asyncio.Event()

        async def handler(record: Record) -> None:
            if record.key == b"slow":
                slow_started.set()
                await release_slow.wait()
            order.setdefault(record.key, []).append(int(record.value))

        dispatcher = KeyOrderedDispatcher(handler, max_workers=4)
        dispatcher.start()
        await dispatcher.submit(_rec(b"slow", b"0"))
        await slow_started.wait()
        for i in range(5):
            await dispatcher.submit(_rec(b"fast", str(i).encode()))
        for _ in range(100):
            if len(order.get(b"fast", [])) == 5:
                break
            await asyncio.sleep(0.02)
        assert order[b"fast"] == [0, 1, 2, 3, 4]  # progressed AND ordered
        assert order.get(b"slow", []) == []  # still parked
        release_slow.set()
        await dispatcher.stop()
        assert order[b"slow"] == [0]

    async def test_same_key_never_interleaves(self):
        active = {"n": 0, "max": 0}
        out: list[int] = []

        async def handler(record: Record) -> None:
            active["n"] += 1
            active["max"] = max(active["max"], active["n"])
            await asyncio.sleep(0.001)
            out.append(int(record.value))
            active["n"] -= 1

        dispatcher = KeyOrderedDispatcher(handler, max_workers=4)
        dispatcher.start()
        for i in range(20):
            await dispatcher.submit(_rec(b"k", str(i).encode()))
        await dispatcher.stop()
        assert out == list(range(20))
        assert active["max"] == 1  # strictly serial for one key

    async def test_stop_drains_in_flight(self):
        done: list[int] = []

        async def handler(record: Record) -> None:
            await asyncio.sleep(0.01)
            done.append(int(record.value))

        dispatcher = KeyOrderedDispatcher(handler, max_workers=2)
        dispatcher.start()
        for i in range(6):
            await dispatcher.submit(_rec(f"k{i}".encode(), str(i).encode()))
        await dispatcher.stop()
        assert sorted(done) == list(range(6))  # nothing abandoned

    async def test_handler_exception_does_not_kill_lane(self):
        seen: list[int] = []

        async def handler(record: Record) -> None:
            n = int(record.value)
            if n == 1:
                raise RuntimeError("hostile delivery")
            seen.append(n)

        dispatcher = KeyOrderedDispatcher(handler, max_workers=2)
        dispatcher.start()
        for i in range(4):
            await dispatcher.submit(_rec(b"k", str(i).encode()))
        await dispatcher.stop()
        assert seen == [0, 2, 3]  # the lane survived the raise


class TestEventStream:
    async def test_drop_oldest_with_counter(self):
        from calfkit_tpu.client.events import EventStream

        stream = EventStream(buffer=3)
        for i in range(10):
            stream.push(i)  # type: ignore[arg-type]
        assert stream.dropped > 0
        stream.close()
        got = [e async for e in stream]
        assert len(got) <= 4
        assert got[-1] == 9  # newest survives, oldest dropped

    async def test_close_wakes_parked_consumer(self):
        from calfkit_tpu.client.events import EventStream

        stream = EventStream(buffer=4)

        async def consume():
            return [e async for e in stream]

        task = asyncio.create_task(consume())
        await asyncio.sleep(0.05)  # consumer parks on an empty queue
        stream.push("only")  # type: ignore[arg-type]
        await asyncio.sleep(0.05)
        stream.close()
        got = await asyncio.wait_for(task, timeout=5)
        assert got == ["only"]

    async def test_push_after_close_is_noop(self):
        from calfkit_tpu.client.events import EventStream

        stream = EventStream(buffer=4)
        stream.close()
        stream.push("late")  # type: ignore[arg-type]
        assert [e async for e in stream] == []


class TestHandleCancelSafety:
    async def test_cancelled_result_waiter_does_not_poison_handle(self):
        """Cancel one result() waiter mid-wait; a later result() on the same
        handle must still complete (reference: hub.py cancel-safe channel)."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        mesh = InMemoryMesh()
        agent = Agent(
            "cancelsafe", model=TestModelClient(custom_output_text="finished")
        )
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("cancelsafe").start("go")
            waiter = asyncio.create_task(handle.result(timeout=30))
            await asyncio.sleep(0)  # let it park
            waiter.cancel()
            with pytest.raises(asyncio.CancelledError):
                await waiter
            result = await handle.result(timeout=30)
            assert result.output == "finished"
            await client.close()
