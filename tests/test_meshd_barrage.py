"""meshd under hostile input (r5: the kafkad corrupt-frame barrage's
sibling for the line-protocol broker) — garbage lines, oversized fields,
bad base64, torn writes, and abrupt disconnects must never crash or
wedge the dev broker other clients depend on.
"""

from __future__ import annotations

import asyncio
import random
import socket

import pytest

from calfkit_tpu.mesh.tcp import TcpMesh, find_meshd, spawn_meshd

pytestmark = pytest.mark.skipif(
    find_meshd() is None, reason="meshd not built (make -C native)"
)


@pytest.fixture()
def broker_port():
    proc = spawn_meshd(0)
    yield proc.meshd_port
    proc.terminate()
    proc.wait(timeout=5)


def _alive(port: int) -> bool:
    with socket.create_connection(("127.0.0.1", port), 5) as s:
        s.sendall(b"PING\n")
        s.settimeout(5)
        got = b""
        while len(got) < 4:  # recv may legally return partial reads
            chunk = s.recv(4 - len(got))
            if not chunk:
                return False
            got += chunk
        return got == b"PONG"


class TestMeshdBarrage:
    def test_garbage_line_barrage(self, broker_port):
        rng = random.Random(53)
        for i in range(150):
            with socket.create_connection(("127.0.0.1", broker_port), 5) as s:
                kind = i % 5
                if kind == 0:  # random binary garbage + newline
                    s.sendall(rng.randbytes(rng.randint(1, 400)) + b"\n")
                elif kind == 1:  # known verb, wrong arity/fields
                    s.sendall(b"PUB\n")
                    s.sendall(b"PUB topic\n")
                    s.sendall(b"POLL notanumber x y\n")
                elif kind == 2:  # bad base64 in every field slot
                    s.sendall(b"PUB t !!! ??? %%%\n")
                elif kind == 3:  # torn write: no newline, then hang up
                    s.sendall(b"PUB half-a-comm")
                else:  # huge single line (1 MiB of x)
                    s.sendall(b"NOPE " + b"x" * (1 << 20) + b"\n")
                # abrupt close without reading any response
        assert _alive(broker_port)

    def test_half_open_connections_do_not_wedge(self, broker_port):
        # open many connections that never send anything, then verify the
        # broker still serves; meshd threads block on read, which is fine
        # as long as new connections keep being accepted
        conns = [
            socket.create_connection(("127.0.0.1", broker_port), 5)
            for _ in range(64)
        ]
        try:
            assert _alive(broker_port)
        finally:
            for conn in conns:
                conn.close()

    def test_real_traffic_flows_after_barrage(self, broker_port):
        async def run() -> None:
            mesh = TcpMesh(f"127.0.0.1:{broker_port}")
            await mesh.start()
            await mesh.ensure_topics(["post.barrage"])
            got = asyncio.Event()
            vals: list[bytes] = []

            async def handler(record):
                vals.append(record.value)
                got.set()

            sub = await mesh.subscribe(
                ["post.barrage"], handler, group_id="pb"
            )
            await mesh.publish("post.barrage", b"still-works", key=b"k")
            await asyncio.wait_for(got.wait(), 15)
            assert vals == [b"still-works"]
            await sub.stop()
            await mesh.stop()

        # barrage first, then the full transport path
        rng = random.Random(59)
        for _ in range(40):
            with socket.create_connection(("127.0.0.1", broker_port), 5) as s:
                s.sendall(rng.randbytes(rng.randint(1, 200)) + b"\n")
        asyncio.run(run())
