"""Bedrock + Mistral model clients (VERDICT r4 missing-item 5: the
provider-breadth remainder on the shared http seam).

Bedrock is exercised at three seams, each against an independent oracle:
the SigV4 signer against the published AWS test-suite vector, the binary
eventstream decoder against frames ENCODED by a test-local writer, and
the Converse mapping against httpx.MockTransport.  Mistral pins exactly
its documented deviations from the OpenAI shape.
"""

from __future__ import annotations

import datetime
import json
import struct
import zlib

import httpx
import pytest

from calfkit_tpu.engine.model_client import (
    ModelRequestParameters,
    ModelSettings,
    ResponseDone,
    TextDelta,
)
from calfkit_tpu.models.capability import ToolDef
from calfkit_tpu.models.messages import (
    ModelRequest,
    ModelResponse,
    RetryPart,
    ToolCallOutput,
    ToolReturnPart,
    UserPart,
)
from calfkit_tpu.providers import (
    BedrockModelClient,
    MistralModelClient,
    ModelAPIError,
)
from calfkit_tpu.providers.bedrock import (
    decode_event_frames,
    render_converse,
    sigv4_headers,
)

TOOL = ToolDef(
    name="lookup",
    description="Look things up.",
    parameters_schema={
        "type": "object",
        "properties": {"q": {"type": "string"}},
        "required": ["q"],
    },
)

HISTORY = [
    ModelRequest(parts=[UserPart(content="find the answer")],
                 instructions="be brief"),
    ModelResponse(parts=[ToolCallOutput(
        tool_call_id="c1", tool_name="lookup", args={"q": "answer"})]),
    ModelRequest(parts=[ToolReturnPart(
        tool_call_id="c1", tool_name="lookup", content="42")]),
]


class TestSigV4:
    def test_aws_published_vector(self):
        """The AWS SigV4 documentation example (IAM ListUsers,
        2015-08-30) — an oracle this implementation did not produce."""
        headers = sigv4_headers(
            method="GET",
            url="https://iam.amazonaws.com/?Action=ListUsers&Version=2010-05-08",
            region="us-east-1",
            service="iam",
            access_key="AKIDEXAMPLE",
            secret_key="wJalrXUtnFEMI/K7MDENG+bPxRfiCYEXAMPLEKEY",
            now=datetime.datetime(2015, 8, 30, 12, 36, 0,
                                  tzinfo=datetime.timezone.utc),
            extra_headers={
                "content-type":
                    "application/x-www-form-urlencoded; charset=utf-8",
            },
        )
        assert headers["Authorization"] == (
            "AWS4-HMAC-SHA256 "
            "Credential=AKIDEXAMPLE/20150830/us-east-1/iam/aws4_request, "
            "SignedHeaders=content-type;host;x-amz-date, "
            "Signature=5d672d79c15b13162d9279b0855cfba6"
            "789a8edb4c82c400e06b5924a6f2b5d7"
        )

    def test_session_token_is_signed_and_sent(self):
        headers = sigv4_headers(
            method="POST", url="https://bedrock-runtime.us-east-1.amazonaws.com/x",
            region="us-east-1", service="bedrock",
            access_key="AK", secret_key="SK", session_token="TOKEN",
            payload=b"{}",
        )
        assert headers["X-Amz-Security-Token"] == "TOKEN"
        assert "x-amz-security-token" in headers["Authorization"]


def encode_event_frame(headers: dict[str, str], payload: bytes) -> bytes:
    """Test-local eventstream WRITER (independent of the decoder)."""
    hdr = b""
    for name, value in headers.items():
        raw_name = name.encode()
        raw_value = value.encode()
        hdr += bytes([len(raw_name)]) + raw_name + b"\x07"
        hdr += struct.pack(">H", len(raw_value)) + raw_value
    total = 12 + len(hdr) + len(payload) + 4
    prelude = struct.pack(">II", total, len(hdr))
    prelude += struct.pack(">I", zlib.crc32(prelude))
    body = prelude + hdr + payload
    return body + struct.pack(">I", zlib.crc32(body))


class TestEventStreamCodec:
    def test_round_trip_and_partial_frames(self):
        frame_a = encode_event_frame(
            {":event-type": "contentBlockDelta"}, b'{"x":1}'
        )
        frame_b = encode_event_frame({":event-type": "messageStop"}, b"{}")
        blob = frame_a + frame_b
        # feed byte by byte: frames must come out exactly at boundaries
        buffer = bytearray()
        seen = []
        for i in range(len(blob)):
            buffer.extend(blob[i:i + 1])
            seen.extend(decode_event_frames(buffer))
        assert [h[":event-type"] for h, _p in seen] == [
            "contentBlockDelta", "messageStop",
        ]
        assert seen[0][1] == b'{"x":1}'
        assert not buffer  # fully consumed

    def test_corrupt_crc_is_typed(self):
        frame = bytearray(encode_event_frame({":event-type": "x"}, b"{}"))
        frame[-1] ^= 0xFF
        with pytest.raises(ModelAPIError, match="crc"):
            decode_event_frames(frame)

    def test_corrupt_prelude_is_typed(self):
        frame = bytearray(encode_event_frame({":event-type": "x"}, b"{}"))
        frame[0] ^= 0x01
        with pytest.raises(ModelAPIError, match="crc|implausible"):
            decode_event_frames(frame)


def _bedrock(handler) -> BedrockModelClient:
    return BedrockModelClient(
        "anthropic.claude-test", region="us-east-1",
        access_key="AK", secret_key="SK",
        http_client=httpx.AsyncClient(transport=httpx.MockTransport(handler)),
    )


class TestBedrockConverse:
    def test_render_merges_adjacent_roles(self):
        system, turns = render_converse(HISTORY)
        assert system == [{"text": "be brief"}]
        assert [t["role"] for t in turns] == ["user", "assistant", "user"]
        assert turns[1]["content"][0]["toolUse"]["input"] == {"q": "answer"}
        assert turns[2]["content"][0]["toolResult"]["toolUseId"] == "c1"

    def test_retry_part_becomes_error_tool_result(self):
        _s, turns = render_converse([
            ModelResponse(parts=[ToolCallOutput(
                tool_call_id="c9", tool_name="lookup", args={})]),
            ModelRequest(parts=[RetryPart(
                content="bad args", tool_call_id="c9", tool_name="lookup")]),
        ])
        result = turns[-1]["content"][0]["toolResult"]
        assert result["status"] == "error"

    async def test_request_mapping_and_parse(self):
        captured = {}

        def handler(request: httpx.Request) -> httpx.Response:
            captured["url"] = str(request.url)
            captured["payload"] = json.loads(request.content)
            captured["auth"] = request.headers.get("Authorization", "")
            return httpx.Response(200, json={
                "output": {"message": {"role": "assistant", "content": [
                    {"text": "the answer is 42"},
                ]}},
                "stopReason": "end_turn",
                "usage": {"inputTokens": 10, "outputTokens": 5},
            })

        client = _bedrock(handler)
        response = await client.request(
            HISTORY, ModelSettings(max_tokens=64, temperature=0.5),
            ModelRequestParameters(tool_defs=[TOOL]),
        )
        assert "/model/anthropic.claude-test/converse" in captured["url"]
        assert captured["auth"].startswith("AWS4-HMAC-SHA256")
        assert captured["payload"]["inferenceConfig"] == {
            "maxTokens": 64, "temperature": 0.5,
        }
        spec = captured["payload"]["toolConfig"]["tools"][0]["toolSpec"]
        assert spec["name"] == "lookup"
        assert response.text() == "the answer is 42"
        assert response.usage.input_tokens == 10
        await client.aclose()

    async def test_structured_output_forces_any_tool_choice(self):
        captured = {}

        def handler(request: httpx.Request) -> httpx.Response:
            captured["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "output": {"message": {"role": "assistant", "content": [
                    {"toolUse": {"toolUseId": "t1", "name": "lookup",
                                 "input": {"q": "x"}}},
                ]}},
                "usage": {},
            })

        client = _bedrock(handler)
        response = await client.request(
            HISTORY, None,
            ModelRequestParameters(tool_defs=[TOOL], allow_text_output=False),
        )
        assert captured["payload"]["toolConfig"]["toolChoice"] == {"any": {}}
        call = response.tool_calls()[0]
        assert call.tool_name == "lookup"
        assert json.loads(call.args) == {"q": "x"}
        await client.aclose()

    async def test_http_error_is_typed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(403, json={"message": "no creds"})

        client = _bedrock(handler)
        with pytest.raises(ModelAPIError) as info:
            await client.request(HISTORY)
        assert info.value.status == 403
        await client.aclose()

    async def test_stream_text_tool_and_usage(self):
        frames = b"".join([
            encode_event_frame(
                {":event-type": "messageStart", ":message-type": "event"},
                json.dumps({"role": "assistant"}).encode()),
            encode_event_frame(
                {":event-type": "contentBlockDelta", ":message-type": "event"},
                json.dumps({"contentBlockIndex": 0,
                            "delta": {"text": "half "}}).encode()),
            encode_event_frame(
                {":event-type": "contentBlockDelta", ":message-type": "event"},
                json.dumps({"contentBlockIndex": 0,
                            "delta": {"text": "done"}}).encode()),
            encode_event_frame(
                {":event-type": "contentBlockStart", ":message-type": "event"},
                json.dumps({"contentBlockIndex": 1, "start": {"toolUse": {
                    "toolUseId": "t7", "name": "lookup"}}}).encode()),
            encode_event_frame(
                {":event-type": "contentBlockDelta", ":message-type": "event"},
                json.dumps({"contentBlockIndex": 1, "delta": {
                    "toolUse": {"input": '{"q":'}}}).encode()),
            encode_event_frame(
                {":event-type": "contentBlockDelta", ":message-type": "event"},
                json.dumps({"contentBlockIndex": 1, "delta": {
                    "toolUse": {"input": '"x"}'}}}).encode()),
            encode_event_frame(
                {":event-type": "messageStop", ":message-type": "event"},
                json.dumps({"stopReason": "tool_use"}).encode()),
            encode_event_frame(
                {":event-type": "metadata", ":message-type": "event"},
                json.dumps({"usage": {"inputTokens": 3,
                                      "outputTokens": 9}}).encode()),
        ])

        def handler(request: httpx.Request) -> httpx.Response:
            assert "/converse-stream" in str(request.url)
            return httpx.Response(200, content=frames)

        client = _bedrock(handler)
        deltas, done = [], None
        async for item in client.request_stream(HISTORY):
            if isinstance(item, TextDelta):
                deltas.append(item.text)
            elif isinstance(item, ResponseDone):
                done = item.response
        assert "".join(deltas) == "half done"
        assert done.text() == "half done"
        call = done.tool_calls()[0]
        assert (call.tool_call_id, call.tool_name) == ("t7", "lookup")
        assert json.loads(call.args) == {"q": "x"}
        assert done.usage.output_tokens == 9
        await client.aclose()

    async def test_stream_without_message_stop_raises(self):
        frames = encode_event_frame(
            {":event-type": "contentBlockDelta", ":message-type": "event"},
            json.dumps({"delta": {"text": "trunc"}}).encode())

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, content=frames)

        client = _bedrock(handler)
        with pytest.raises(ModelAPIError, match="messageStop"):
            async for _ in client.request_stream(HISTORY):
                pass
        await client.aclose()

    async def test_midstream_exception_frame_is_typed(self):
        frames = encode_event_frame(
            {":message-type": "exception",
             ":exception-type": "throttlingException"},
            b'{"message":"slow down"}')

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, content=frames)

        client = _bedrock(handler)
        with pytest.raises(ModelAPIError, match="throttlingException"):
            async for _ in client.request_stream(HISTORY):
                pass
        await client.aclose()


class TestMistral:
    def _client(self, handler) -> MistralModelClient:
        return MistralModelClient(
            "mistral-test", api_key="k",
            http_client=httpx.AsyncClient(
                transport=httpx.MockTransport(handler)),
        )

    async def test_tool_choice_any_and_tool_name_threading(self):
        captured = {}

        def handler(request: httpx.Request) -> httpx.Response:
            captured["url"] = str(request.url)
            captured["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "choices": [{"message": {"role": "assistant",
                                         "content": "ok"}}],
                "usage": {"prompt_tokens": 1, "completion_tokens": 1},
                "model": "mistral-test",
            })

        client = self._client(handler)
        response = await client.request(
            HISTORY, None,
            ModelRequestParameters(tool_defs=[TOOL], allow_text_output=False),
        )
        assert captured["url"] == "https://api.mistral.ai/v1/chat/completions"
        payload = captured["payload"]
        assert payload["tool_choice"] == "any"
        tool_message = next(
            m for m in payload["messages"] if m.get("role") == "tool"
        )
        assert tool_message["name"] == "lookup"  # Mistral deviation
        assert response.text() == "ok"
        await client.aclose()

    async def test_max_tokens_never_reasoning_spelled(self):
        captured = {}

        def handler(request: httpx.Request) -> httpx.Response:
            captured["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "choices": [{"message": {"content": "x"}}], "usage": {},
            })

        client = self._client(handler)
        await client.request(HISTORY, ModelSettings(max_tokens=7))
        assert captured["payload"]["max_tokens"] == 7
        assert "max_completion_tokens" not in captured["payload"]
        await client.aclose()
