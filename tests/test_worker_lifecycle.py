"""Worker lifecycle corners beyond the e2e happy paths.

Reference analogs: tests/test_lifecycle.py, test_lifecycle_e2e.py,
test_lifecycle_resource_fields.py, test_lifecycle_resource_injection.py,
test_lifecycle_review_fixes.py in /root/reference/tests/.
"""

import pytest

from calfkit_tpu.engine import EchoModelClient
from calfkit_tpu.exceptions import LifecycleConfigError
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.nodes import Agent
from calfkit_tpu.worker import Worker


def _worker(mesh, **kw):
    return Worker([Agent("w", model=EchoModelClient())], mesh=mesh, **kw)


class TestResourceBag:
    async def test_resources_injected_into_node_bags(self):
        """Every node's resource bag sees the worker's resources (setdefault
        — a node's own entry wins)."""
        mesh = InMemoryMesh()
        agent = Agent("w", model=EchoModelClient())
        agent.resources["mine"] = "node-owned"
        worker = Worker([agent], mesh=mesh)

        @worker.resource
        async def shared():
            yield {"conn": 7}

        @worker.resource(key="mine")
        async def would_shadow():
            yield "worker-owned"

        await worker.start()
        assert agent.resources["shared"] == {"conn": 7}
        assert agent.resources["mine"] == "node-owned"  # node entry wins
        assert agent.resources["worker"] is worker
        await worker.stop()
        await mesh.stop()

    async def test_resource_custom_key(self):
        mesh = InMemoryMesh()
        worker = _worker(mesh)

        @worker.resource(key="db")
        async def make_database():
            yield 42

        await worker.start()
        assert worker.resources["db"] == 42
        assert "make_database" not in worker.resources
        await worker.stop()
        await mesh.stop()

    async def test_non_asyncgen_resource_rejected_at_registration(self):
        worker = _worker(InMemoryMesh())
        with pytest.raises(LifecycleConfigError, match="async generator"):

            @worker.resource
            def sync_resource():
                return 1

    async def test_resources_torn_down_in_reverse_order(self):
        mesh = InMemoryMesh()
        worker = _worker(mesh)
        log: list[str] = []

        @worker.resource
        async def first():
            log.append("up-1")
            yield 1
            log.append("down-1")

        @worker.resource
        async def second():
            log.append("up-2")
            yield 2
            log.append("down-2")

        await worker.start()
        await worker.stop()
        assert log == ["up-1", "up-2", "down-2", "down-1"]  # LIFO teardown
        await mesh.stop()

    async def test_failing_teardown_does_not_block_others(self):
        mesh = InMemoryMesh()
        worker = _worker(mesh)
        log: list[str] = []

        @worker.resource
        async def fine():
            yield 1
            log.append("fine-down")

        @worker.resource
        async def broken():
            yield 2
            raise RuntimeError("teardown boom")

        await worker.start()
        await worker.stop()  # must not raise
        assert log == ["fine-down"]  # the earlier resource still tore down
        await mesh.stop()


class TestBootFailure:
    async def test_failed_resource_rolls_back_prior_resources(self):
        mesh = InMemoryMesh()
        worker = _worker(mesh)
        log: list[str] = []

        @worker.resource
        async def good():
            log.append("up")
            yield 1
            log.append("down")

        @worker.resource
        async def bad():
            raise RuntimeError("boot boom")
            yield  # pragma: no cover

        with pytest.raises(RuntimeError, match="boot boom"):
            await worker.start()
        assert log == ["up", "down"]  # the good one was rolled back
        # worker is spent: single-use even after a failed boot
        with pytest.raises(LifecycleConfigError):
            await worker.start()
        await mesh.stop()

    async def test_failed_on_startup_hook_aborts_before_resources(self):
        mesh = InMemoryMesh()
        worker = _worker(mesh)
        log: list[str] = []

        @worker.on_startup
        def explode():
            raise RuntimeError("hook boom")

        @worker.resource
        async def never():
            log.append("up")
            yield

        with pytest.raises(RuntimeError, match="hook boom"):
            await worker.start()
        assert log == []  # hooks run before resources enter
        await mesh.stop()

    async def test_after_shutdown_runs_on_rollback(self):
        mesh = InMemoryMesh()
        worker = _worker(mesh)
        log: list[str] = []

        @worker.after_shutdown
        def observed():
            log.append("after-shutdown")

        @worker.resource
        async def bad():
            raise RuntimeError("boom")
            yield  # pragma: no cover

        with pytest.raises(RuntimeError):
            await worker.start()
        assert log == ["after-shutdown"]
        await mesh.stop()


class TestHookOrdering:
    async def test_full_bracket_order(self):
        """resource phase wraps serving phase: on_startup / resources /
        (serving: after_startup ... on_shutdown) / after_shutdown /
        resource finalizers."""
        mesh = InMemoryMesh()
        worker = _worker(mesh)
        log: list[str] = []

        @worker.on_startup
        def a():
            log.append("on_startup")

        @worker.resource
        async def r():
            log.append("resource-up")
            yield 1
            log.append("resource-down")

        @worker.after_startup
        async def b():
            log.append("after_startup")

        @worker.on_shutdown
        def c():
            log.append("on_shutdown")

        @worker.after_shutdown
        async def d():
            log.append("after_shutdown")

        async with worker:
            log.append("serving")
        assert log == [
            "on_startup", "resource-up", "after_startup", "serving",
            "on_shutdown", "after_shutdown", "resource-down",
        ]
        await mesh.stop()

    async def test_stop_is_idempotent(self):
        mesh = InMemoryMesh()
        worker = _worker(mesh)
        count = {"n": 0}

        @worker.after_shutdown
        def once():
            count["n"] += 1

        await worker.start()
        await worker.stop()
        await worker.stop()
        assert count["n"] == 1
        await mesh.stop()

    async def test_owned_transport_stopped_with_worker(self):
        mesh = InMemoryMesh()
        worker = _worker(mesh, owns_transport=True)
        await worker.start()
        await worker.stop()
        assert not mesh._started  # owns_transport: worker stops the mesh
