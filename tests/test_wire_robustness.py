"""Wire-model robustness: randomized round-trips + forward compatibility.

The wire contract says unknown fields are TOLERATED (a newer node may add
fields an older node has not heard of — rolling upgrades over a shared
mesh), and every model must survive a to_wire/from_wire round-trip
bit-exactly on the fields it knows.
"""

from __future__ import annotations

import json
import random

from calfkit_tpu.models import (
    DataPart,
    ErrorReport,
    FaultMessage,
    FaultTypes,
    ReturnMessage,
    TextPart,
)
from calfkit_tpu.models.marker import ToolCallMarker
from calfkit_tpu.models.session_context import (
    CallFrame,
    Envelope,
    SessionContext,
    WorkflowState,
)
from calfkit_tpu.models.state import State
from calfkit_tpu.models.messages import (
    ModelRequest,
    ModelResponse,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    UserPart,
)


def _random_state(rng: random.Random) -> State:
    history = []
    for i in range(rng.randint(0, 6)):
        if rng.random() < 0.5:
            history.append(ModelRequest(parts=[
                UserPart(content=f"msg {i} " + "é中\U0001f600" * rng.randint(0, 3))
            ]))
        else:
            parts = [TextOutput(text=f"reply {i}")]
            if rng.random() < 0.5:
                parts.append(ToolCallOutput(
                    tool_call_id=f"tc{i}", tool_name=f"tool_{i}",
                    args={"n": i, "nested": {"deep": [1, 2, {"x": None}]}},
                ))
            history.append(ModelResponse(parts=parts, author=f"a{i % 2}"))
    return State(message_history=history)


def _random_envelope(rng: random.Random) -> Envelope:
    frames = [
        CallFrame(
            target_topic=f"agent.t{i}.private.input",
            callback_topic=f"agent.t{i-1}.private.return" if i else "client.inbox.x",
            route="run",
            payload=[TextPart(text=f"payload {i}"),
                     DataPart(data={"k": list(range(i))})],
            tag=f"tag-{i}" if rng.random() < 0.5 else None,
            marker=ToolCallMarker(tool_call_id=f"tc-{i}", tool_name=f"t{i}")
            if rng.random() < 0.5 else None,
        )
        for i in range(rng.randint(1, 8))
    ]
    envelope = Envelope(
        context=SessionContext(state=_random_state(rng)),
        workflow=WorkflowState(frames=frames),
    )
    if rng.random() < 0.5:
        envelope.reply = ReturnMessage(
            parts=[TextPart(text="done ✓")], frame_id=frames[-1].frame_id
        )
    elif rng.random() < 0.5:
        envelope.reply = FaultMessage(
            report=ErrorReport.build_safe(
                FaultTypes.NODE_ERROR, "x" * rng.randint(0, 2000),
                exc=ValueError("boom"),
            ),
            frame_id=frames[-1].frame_id,
        )
    return envelope


class TestRoundTrips:
    def test_randomized_envelope_roundtrips(self):
        rng = random.Random(7)
        for _ in range(50):
            envelope = _random_envelope(rng)
            wire = envelope.to_wire()
            back = Envelope.from_wire(wire)
            assert back.model_dump() == envelope.model_dump()
            # and the round-trip is stable (no lossy normalization)
            assert Envelope.from_wire(back.to_wire()).model_dump() == back.model_dump()

    def test_deep_call_stack_roundtrips(self):
        rng = random.Random(11)
        frames = [
            CallFrame(target_topic=f"agent.n{i}.private.input",
                      callback_topic="client.inbox.deep", route="run")
            for i in range(64)
        ]
        envelope = Envelope(
            context=SessionContext(state=_random_state(rng)),
            workflow=WorkflowState(frames=frames),
        )
        back = Envelope.from_wire(envelope.to_wire())
        assert len(back.workflow.frames) == 64
        assert back.workflow.frames[63].frame_id == frames[63].frame_id


class TestForwardCompat:
    def test_unknown_fields_tolerated_everywhere(self):
        """A NEWER peer's extra fields must not break decoding (rolling
        upgrades share topics across versions)."""
        envelope = _random_envelope(random.Random(3))
        doc = json.loads(envelope.to_wire())
        doc["from_the_future"] = {"shiny": True}
        doc["context"]["state"]["novel_memory"] = [1, 2, 3]
        doc["workflow"]["frames"][0]["new_frame_flag"] = "yes"
        back = Envelope.from_wire(json.dumps(doc).encode())
        assert back.workflow.frames[0].target_topic == (
            envelope.workflow.frames[0].target_topic
        )

    def test_unknown_part_kind_fails_loudly_not_silently(self):
        """Unknown discriminated-union KINDS are different from unknown
        fields: a part the decoder cannot classify must raise (it cannot be
        safely ignored — it might be the payload), not decode to garbage."""
        import pytest
        from pydantic import ValidationError

        envelope = _random_envelope(random.Random(5))
        doc = json.loads(envelope.to_wire())
        doc["workflow"]["frames"][0]["payload"] = [
            {"kind": "hologram", "beam": "blue"}
        ]
        with pytest.raises(ValidationError):
            Envelope.from_wire(json.dumps(doc).encode())

    def test_error_report_unknown_fields(self):
        report = ErrorReport.build_safe(FaultTypes.NODE_ERROR, "x")
        doc = json.loads(report.model_dump_json())
        doc["severity_from_v99"] = "catastrophic"
        parsed = ErrorReport.model_validate(doc)
        assert parsed.error_type == FaultTypes.NODE_ERROR


class TestToolReturnContentShapes:
    def test_tool_return_content_preserves_json_types(self):
        """Tool results keep their JSON shape across the wire (ints stay
        ints, nested structures intact) — the model re-reads them."""
        request = ModelRequest(parts=[ToolReturnPart(
            tool_call_id="t", tool_name="f",
            content={"a": 1, "b": [True, None, 2.5], "c": {"d": "e"}},
        )])
        envelope = Envelope(
            context=SessionContext(state=State(message_history=[request])),
            workflow=WorkflowState(frames=[CallFrame(
                target_topic="agent.x.private.input",
                callback_topic="client.inbox.y", route="run",
            )]),
        )
        back = Envelope.from_wire(envelope.to_wire())
        part = back.context.state.message_history[0].parts[0]
        assert part.content == {"a": 1, "b": [True, None, 2.5], "c": {"d": "e"}}
