"""TcpMesh over the native meshd broker: transport semantics + a full
multi-process-style agent round trip (worker and client on separate mesh
connections, broker in a real subprocess)."""

import asyncio

import pytest

from calfkit_tpu.mesh.tcp import TcpMesh, find_meshd, spawn_meshd

pytestmark = pytest.mark.skipif(
    find_meshd() is None, reason="meshd not built (make -C native)"
)

PORT = 19765


@pytest.fixture(scope="module")
def broker():
    proc = spawn_meshd(PORT)
    yield proc
    proc.terminate()
    proc.wait(timeout=5)


@pytest.fixture
def make_mesh(broker):
    meshes = []

    async def make():
        mesh = TcpMesh(f"127.0.0.1:{PORT}")
        await mesh.start()
        meshes.append(mesh)
        return mesh

    yield make
    # cleanup happens per-test via mesh.stop() calls


class TestTransportSemantics:
    async def test_pubsub_ordering_and_groups(self, make_mesh):
        mesh = await make_mesh()
        got = []

        async def handler(record):
            got.append((record.key, record.value))

        await mesh.subscribe(["t.ord"], handler, group_id="g1")
        for i in range(10):
            await mesh.publish("t.ord", f"v{i}".encode(), key=b"same-key")
        for _ in range(100):
            if len(got) == 10:
                break
            await asyncio.sleep(0.05)
        assert [v for _, v in got] == [f"v{i}".encode() for i in range(10)]
        await mesh.stop()

    async def test_work_sharing_across_connections(self, make_mesh):
        """Two members (separate TCP connections = separate 'processes')
        share partitions; per-key ordering still holds."""
        mesh1 = await make_mesh()
        mesh2 = await make_mesh()
        got1, got2 = [], []

        async def h1(r):
            got1.append(r.value)

        async def h2(r):
            got2.append(r.value)

        await mesh1.subscribe(["t.share"], h1, group_id="g")
        await mesh2.subscribe(["t.share"], h2, group_id="g")
        await asyncio.sleep(0.1)
        for i in range(40):
            await mesh1.publish("t.share", str(i).encode(), key=f"k{i}".encode())
        for _ in range(100):
            if len(got1) + len(got2) == 40:
                break
            await asyncio.sleep(0.05)
        assert len(got1) + len(got2) == 40
        assert got1 and got2  # both connections actually worked
        await mesh1.stop()
        await mesh2.stop()

    async def test_tables_fold_and_barrier(self, make_mesh):
        mesh = await make_mesh()
        writer = mesh.table_writer("t.tbl")
        reader = mesh.table_reader("t.tbl")
        await reader.start()
        await writer.put("a", b"1")
        await writer.put("a", b"2")
        await writer.put("b", b"3")
        await reader.barrier()
        assert reader.get("a") == b"2"
        assert reader.items() == {"a": b"2", "b": b"3"}
        await writer.tombstone("a")
        await reader.barrier()
        assert reader.get("a") is None
        await mesh.stop()


class TestEndToEndOverMeshd:
    async def test_agent_roundtrip_worker_and_client_separate_meshes(self, make_mesh):
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool(name="echo_tcp")
        def echo_tcp(text: str) -> str:
            """Echo.

            Args:
                text: Input.
            """
            return f"tcp:{text}"

        worker_mesh = await make_mesh()
        client_mesh = await make_mesh()
        agent = Agent(
            "tcp_agent",
            model=TestModelClient(custom_output_text="served over meshd"),
            tools=[echo_tcp],
        )
        worker = Worker([agent, echo_tcp], mesh=worker_mesh)
        await worker.start()
        client = Client.connect(client_mesh)
        result = await client.agent("tcp_agent").execute("hello", timeout=20)
        assert result.output == "served over meshd"
        # directory visible from the client's own connection
        cards = await client.mesh_directory.get_agents()
        assert [c.name for c in cards] == ["tcp_agent"]
        await client.mesh_directory.close()
        await client.close()
        await worker.stop()
        await worker_mesh.stop()
        await client_mesh.stop()


class TestSpawnPortZero:
    """Port-0 spawning (r3 advisor: no probe-then-spawn TOCTOU race) —
    the broker binds an OS port and reports it on stdout."""

    def test_meshd_port_zero_reports_and_serves(self):
        import socket

        proc = spawn_meshd(0)
        try:
            assert proc.meshd_port > 0
            with socket.create_connection(
                ("127.0.0.1", proc.meshd_port), timeout=2
            ) as s:
                s.sendall(b"PING\n")
                assert s.recv(16).startswith(b"PONG")
        finally:
            proc.terminate()
            proc.wait(timeout=5)
