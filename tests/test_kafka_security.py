"""Security threading through the native wire client (VERDICT r4 item 2).

The same ``ConnectionProfile.security`` mapping the aiokafka adapter
consumes now drives the wire client: TLS, SASL PLAIN (round-tripped
against kafkad's ``--sasl`` listener), and SCRAM-SHA-256/512 (validated
against RFC 7677 vectors + an independent in-test SCRAM server).
Unsupported security fails loudly at construction so a secured cluster
is never contacted with security silently dropped.

Reference anchor: calfkit/client/_connection.py:39-110 (security= reaches
every producer/consumer/admin the reference builds).
"""

from __future__ import annotations

import asyncio
import base64
import hashlib
import hmac
import os
import ssl
import struct
import subprocess

import pytest

from calfkit_tpu.mesh.connection import ConnectionProfile
from calfkit_tpu.mesh.kafka_wire import (
    KafkaWireClient,
    KafkaWireError,
    KafkaWireMesh,
    ScramClient,
    WireSecurity,
    find_kafkad,
    spawn_kafkad,
)


class TestWireSecurityParsing:
    def test_defaults_to_plaintext(self):
        sec = WireSecurity.from_security_kwargs({})
        assert sec.protocol == "PLAINTEXT"
        assert not sec.uses_tls and not sec.uses_sasl

    def test_unknown_keys_fail_loudly(self):
        with pytest.raises(ValueError, match="not supported by the native"):
            WireSecurity.from_security_kwargs({"ssl_cafile": "/x"})

    def test_unsupported_mechanism_fails_loudly(self):
        with pytest.raises(ValueError, match="GSSAPI"):
            WireSecurity.from_security_kwargs({
                "security_protocol": "SASL_PLAINTEXT",
                "sasl_mechanism": "GSSAPI",
            })

    def test_sasl_requires_credentials(self):
        with pytest.raises(ValueError, match="username"):
            WireSecurity.from_security_kwargs({
                "security_protocol": "SASL_PLAINTEXT",
                "sasl_mechanism": "PLAIN",
            })

    def test_mechanism_without_sasl_protocol_rejected(self):
        with pytest.raises(ValueError, match="SASL_PLAINTEXT"):
            WireSecurity.from_security_kwargs({"sasl_mechanism": "PLAIN"})

    def test_ssl_context_without_tls_protocol_rejected(self):
        """TLS material + a cleartext protocol must fail, not silently
        connect unencrypted."""
        ctx = ssl.create_default_context()
        with pytest.raises(ValueError, match="cleartext"):
            WireSecurity.from_security_kwargs({"ssl_context": ctx})
        with pytest.raises(ValueError, match="cleartext"):
            WireSecurity.from_security_kwargs({
                "security_protocol": "SASL_PLAINTEXT",
                "sasl_mechanism": "PLAIN",
                "sasl_plain_username": "u", "sasl_plain_password": "p",
                "ssl_context": ctx,
            })

    def test_mesh_parses_security_at_construction(self):
        with pytest.raises(ValueError, match="not supported"):
            KafkaWireMesh("h:9092", security={"sasl_oauth_token_provider": 1})

    def test_mesh_accepts_profile(self):
        profile = ConnectionProfile(
            bootstrap_servers="h:9092", max_message_bytes=123456,
            security={"security_protocol": "SASL_PLAINTEXT",
                      "sasl_mechanism": "SCRAM-SHA-256",
                      "sasl_plain_username": "u", "sasl_plain_password": "p"},
        )
        mesh = KafkaWireMesh(profile=profile)
        assert mesh.max_message_bytes == 123456
        assert mesh._security.sasl_mechanism == "SCRAM-SHA-256"

    def test_mesh_profile_conflicts_rejected(self):
        profile = ConnectionProfile(bootstrap_servers="h:9092")
        with pytest.raises(ValueError, match="conflicts"):
            KafkaWireMesh("other:9092", profile=profile)


class TestScramVectors:
    """RFC 7677 §3 SCRAM-SHA-256 test vector, end to end."""

    def test_rfc7677_exchange(self):
        scram = ScramClient(
            "SCRAM-SHA-256", "user", "pencil",
            cnonce="rOprNGfwEbeRWgbNEkqO",
        )
        assert scram.first() == b"n,,n=user,r=rOprNGfwEbeRWgbNEkqO"
        server_first = (
            b"r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
            b"s=W22ZaJ0SNY7soEsUEjb6gQ==,i=4096"
        )
        final = scram.final(server_first)
        assert final == (
            b"c=biws,r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
            b"p=dHzbZapWIk4jUhN+Ute9ytag9zjfMHgsqmmiz7AndVQ="
        )
        # server signature from the same vector verifies...
        scram.verify(b"v=6rriTRBi23WpRR/wtup+mMhUZUn/dB5nLTJRsjl95G4=")

    def test_forged_server_signature_rejected(self):
        scram = ScramClient(
            "SCRAM-SHA-256", "user", "pencil",
            cnonce="rOprNGfwEbeRWgbNEkqO",
        )
        scram.first()
        scram.final(
            b"r=rOprNGfwEbeRWgbNEkqO%hvYDpWUa2RaTCAfuxFIlj)hNlF$k0,"
            b"s=W22ZaJ0SNY7soEsUEjb6gQ==,i=4096"
        )
        with pytest.raises(KafkaWireError, match="signature"):
            scram.verify(b"v=" + base64.b64encode(b"f" * 32))

    def test_server_nonce_must_extend_client_nonce(self):
        scram = ScramClient("SCRAM-SHA-256", "user", "pencil", cnonce="abc")
        scram.first()
        with pytest.raises(KafkaWireError, match="nonce"):
            scram.final(b"r=STOLEN,s=" + base64.b64encode(b"salt") + b",i=4096")

    def test_username_escaping(self):
        scram = ScramClient("SCRAM-SHA-256", "a=b,c", "x", cnonce="n")
        assert scram.first() == b"n,,n=a=3Db=2Cc,r=n"


@pytest.mark.skipif(find_kafkad() is None, reason="kafkad not built")
class TestSaslPlainAgainstKafkad:
    @pytest.fixture(scope="class")
    def sasl_broker(self):
        proc = spawn_kafkad(0, sasl="alice:secret")
        yield proc.kafkad_port
        proc.terminate()
        proc.wait(timeout=5)

    def _mesh(self, port: int, password: str) -> KafkaWireMesh:
        return KafkaWireMesh(f"127.0.0.1:{port}", security={
            "security_protocol": "SASL_PLAINTEXT",
            "sasl_mechanism": "PLAIN",
            "sasl_plain_username": "alice",
            "sasl_plain_password": password,
        })

    def test_authenticated_round_trip(self, sasl_broker):
        async def run() -> None:
            mesh = self._mesh(sasl_broker, "secret")
            await mesh.start()
            try:
                await mesh.ensure_topics(["sasl.topic"])
                got = asyncio.Event()
                values = []

                async def handler(rec):
                    values.append(rec.value)
                    got.set()

                sub = await mesh.subscribe(
                    ["sasl.topic"], handler, group_id="sasl-g"
                )
                await mesh.publish("sasl.topic", b"authed", key=b"k")
                await asyncio.wait_for(got.wait(), 15)
                assert values == [b"authed"]
                await sub.stop()
            finally:
                await mesh.stop()

        asyncio.run(run())

    def test_wrong_password_rejected(self, sasl_broker):
        async def run() -> None:
            mesh = self._mesh(sasl_broker, "wrong")
            with pytest.raises(KafkaWireError) as info:
                await mesh.start()
            assert info.value.code == 58  # SASL_AUTHENTICATION_FAILED
            await mesh.stop()

        asyncio.run(run())

    def test_failed_auth_does_not_leave_connection_installed(self, sasl_broker):
        """After a SASL failure, a retry must surface the auth error
        again — not an opaque read error on a half-open connection."""

        async def run() -> None:
            client = KafkaWireClient("127.0.0.1", sasl_broker, security=(
                WireSecurity.from_security_kwargs({
                    "security_protocol": "SASL_PLAINTEXT",
                    "sasl_mechanism": "PLAIN",
                    "sasl_plain_username": "alice",
                    "sasl_plain_password": "wrong",
                })
            ))
            try:
                for _ in range(2):
                    with pytest.raises(KafkaWireError) as info:
                        await client.metadata(None)
                    assert info.value.code == 58
            finally:
                await client.close()

        asyncio.run(run())

    def test_unauthenticated_connection_is_dropped(self, sasl_broker):
        async def run() -> None:
            client = KafkaWireClient("127.0.0.1", sasl_broker)
            try:
                with pytest.raises((KafkaWireError, OSError, asyncio.IncompleteReadError)):
                    await client.metadata(None)
            finally:
                await client.close()

        asyncio.run(run())


def _make_cert(tmp_path) -> tuple[str, str]:
    """Self-signed cert for 127.0.0.1 via the openssl CLI."""
    key = str(tmp_path / "key.pem")
    crt = str(tmp_path / "cert.pem")
    subprocess.run(
        ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
         "-keyout", key, "-out", crt, "-days", "1", "-subj", "/CN=127.0.0.1",
         "-addext", "subjectAltName=IP:127.0.0.1"],
        check=True, capture_output=True,
    )
    return crt, key


@pytest.mark.skipif(find_kafkad() is None, reason="kafkad not built")
class TestTlsRoundTrip:
    """TLS termination in front of kafkad — the client's SSL path runs
    the full handshake with certificate + hostname verification."""

    def test_ssl_round_trip(self, tmp_path):
        crt, key = _make_cert(tmp_path)
        # the broker must ADVERTISE the TLS front door: leader/coordinator
        # routing dials the advertised address directly, so a terminator
        # in front of the broker needs advertised.listeners pointed at it
        # (kafkad: --advertise-port) exactly as with real Kafka
        backend = {"port": 0}

        async def run(proc_holder) -> None:
            server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            server_ctx.load_cert_chain(crt, key)

            async def proxy(reader, writer):
                up_r, up_w = await asyncio.open_connection(
                    "127.0.0.1", backend["port"]
                )

                async def pump(src, dst):
                    try:
                        while True:
                            data = await src.read(65536)
                            if not data:
                                break
                            dst.write(data)
                            await dst.drain()
                    except Exception:  # noqa: BLE001
                        pass
                    finally:
                        try:
                            dst.close()
                        except Exception:  # noqa: BLE001
                            pass

                await asyncio.gather(pump(reader, up_w), pump(up_r, writer))

            tls_server = await asyncio.start_server(
                proxy, "127.0.0.1", 0, ssl=server_ctx
            )
            tls_port = tls_server.sockets[0].getsockname()[1]
            proc = spawn_kafkad(0, advertise_port=tls_port)
            proc_holder.append(proc)
            backend["port"] = proc.kafkad_port

            client_ctx = ssl.create_default_context(cafile=crt)
            mesh = KafkaWireMesh(f"127.0.0.1:{tls_port}", security={
                "security_protocol": "SSL", "ssl_context": client_ctx,
            })
            await mesh.start()
            try:
                await mesh.ensure_topics(["tls.topic"])
                got = asyncio.Event()
                values = []

                async def handler(rec):
                    values.append(rec.value)
                    got.set()

                sub = await mesh.subscribe(
                    ["tls.topic"], handler, group_id="tls-g"
                )
                await mesh.publish("tls.topic", b"over-tls", key=b"k")
                await asyncio.wait_for(got.wait(), 15)
                assert values == [b"over-tls"]
                await sub.stop()
            finally:
                await mesh.stop()
                tls_server.close()
                await tls_server.wait_closed()

        procs: list = []
        try:
            asyncio.run(run(procs))
        finally:
            for proc in procs:
                proc.terminate()
                proc.wait(timeout=5)

    def test_untrusted_cert_rejected(self, tmp_path):
        crt, key = _make_cert(tmp_path)

        async def run() -> None:
            server_ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            server_ctx.load_cert_chain(crt, key)

            async def noop(reader, writer):
                writer.close()

            tls_server = await asyncio.start_server(
                noop, "127.0.0.1", 0, ssl=server_ctx
            )
            tls_port = tls_server.sockets[0].getsockname()[1]
            # default trust store does NOT contain the self-signed cert
            mesh = KafkaWireMesh(f"127.0.0.1:{tls_port}", security={
                "security_protocol": "SSL",
            })
            with pytest.raises(ssl.SSLError):
                await mesh.start()
            tls_server.close()
            await tls_server.wait_closed()

        asyncio.run(run())


class _ScramServer:
    """Independent RFC 5802 SCRAM-SHA-256 *server* over the Kafka SASL
    framing — validates the client against a second implementation, not
    against itself."""

    def __init__(self, username: str, password: str):
        self.username = username
        self.password = password.encode()
        self.salt = os.urandom(16)
        self.iterations = 4096
        self.fail: str | None = None

    async def serve(self, reader, writer):
        state = {"snonce": None, "client_first_bare": None}
        try:
            while True:
                szbuf = await reader.readexactly(4)
                (size,) = struct.unpack(">i", szbuf)
                blob = await reader.readexactly(size)
                api, _ver, corr = struct.unpack(">hhi", blob[:8])
                # skip client_id string
                (cid_len,) = struct.unpack(">h", blob[8:10])
                body = blob[10 + max(0, cid_len):]
                out = struct.pack(">i", corr)
                if api == 17:  # SaslHandshake
                    out += struct.pack(">h", 0) + struct.pack(">i", 1)
                    out += struct.pack(">h", 13) + b"SCRAM-SHA-256"
                elif api == 36:  # SaslAuthenticate
                    (tok_len,) = struct.unpack(">i", body[:4])
                    token = body[4:4 + tok_len]
                    reply, err = self._scram_step(token, state)
                    msg = b"\xff\xff" if not err else (
                        struct.pack(">h", len(err)) + err.encode()
                    )
                    out += struct.pack(">h", 58 if err else 0) + msg
                    out += struct.pack(">i", len(reply)) + reply
                else:
                    break
                writer.write(struct.pack(">i", len(out)) + out)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionError):
            pass
        finally:
            writer.close()

    def _scram_step(self, token: bytes, state) -> tuple[bytes, str | None]:
        text = token.decode()
        if state["snonce"] is None:  # client-first
            bare = text.split(",", 2)[2]
            fields = dict(f.split("=", 1) for f in bare.split(","))
            if fields["n"] != self.username:
                return b"", "unknown user"
            state["client_first_bare"] = bare
            state["snonce"] = fields["r"] + base64.b64encode(os.urandom(9)).decode()
            server_first = (
                f"r={state['snonce']},"
                f"s={base64.b64encode(self.salt).decode()},"
                f"i={self.iterations}"
            )
            state["server_first"] = server_first
            return server_first.encode(), None
        # client-final
        fields = dict(f.split("=", 1) for f in text.split(","))
        if fields["r"] != state["snonce"]:
            return b"", "nonce mismatch"
        salted = hashlib.pbkdf2_hmac(
            "sha256", self.password, self.salt, self.iterations
        )
        client_key = hmac.new(salted, b"Client Key", hashlib.sha256).digest()
        stored_key = hashlib.sha256(client_key).digest()
        without_proof = f"c=biws,r={state['snonce']}"
        auth_msg = ",".join([
            state["client_first_bare"], state["server_first"], without_proof,
        ]).encode()
        client_sig = hmac.new(stored_key, auth_msg, hashlib.sha256).digest()
        recovered = bytes(
            a ^ b for a, b in zip(base64.b64decode(fields["p"]), client_sig)
        )
        if hashlib.sha256(recovered).digest() != stored_key:
            return b"", "authentication failed"
        server_key = hmac.new(salted, b"Server Key", hashlib.sha256).digest()
        server_sig = hmac.new(server_key, auth_msg, hashlib.sha256).digest()
        return b"v=" + base64.b64encode(server_sig), None


class TestScramAgainstIndependentServer:
    def _connect(self, password: str) -> None:
        async def run() -> None:
            server = _ScramServer("carol", "hunter2")
            srv = await asyncio.start_server(server.serve, "127.0.0.1", 0)
            port = srv.sockets[0].getsockname()[1]
            client = KafkaWireClient("127.0.0.1", port, security=(
                WireSecurity.from_security_kwargs({
                    "security_protocol": "SASL_PLAINTEXT",
                    "sasl_mechanism": "SCRAM-SHA-256",
                    "sasl_plain_username": "carol",
                    "sasl_plain_password": password,
                })
            ))
            try:
                await client.conn.connect()
            finally:
                await client.close()
                srv.close()
                await srv.wait_closed()

        asyncio.run(run())

    def test_scram_sha256_full_exchange(self):
        self._connect("hunter2")  # raises on any step failure

    def test_scram_bad_password_rejected(self):
        with pytest.raises(KafkaWireError, match="authentication failed"):
            self._connect("wrong")
