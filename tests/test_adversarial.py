"""Adversarial kernel tests (VERDICT r1 item 6).

Reference anchors:
- oversized-fault elision over a real transport:
  /root/reference/tests/integration/test_fault_escalation_kafka.py
- hostile ``__str__``/``__repr__`` through the report harvester:
  calfkit/models/error_report.py:611 and its dedicated tests
- fan-out crash-mid-batch resume across worker instances:
  /root/reference/tests/integration/test_fault_stress_kafka.py (durable
  batch survival is the point of the compacted-table store)
"""

from __future__ import annotations

import asyncio

import pytest

from calfkit_tpu.exceptions import NodeFaultError
from calfkit_tpu.mesh.tcp import TcpMesh, find_meshd, spawn_meshd
from calfkit_tpu.models import (
    Call,
    DataPart,
    ErrorReport,
    FaultTypes,
    ReturnCall,
    TextPart,
)
from calfkit_tpu.models.error_report import safe_str
from calfkit_tpu.models.marker import ToolCallMarker

meshd_missing = find_meshd() is None

PORT = 19877


@pytest.fixture(scope="module")
def broker():
    if meshd_missing:
        yield None
        return
    proc = spawn_meshd(PORT)
    yield proc
    proc.terminate()
    proc.wait(timeout=5)


# --------------------------------------------------------------------------- #
# hostile objects through the report harvester
# --------------------------------------------------------------------------- #


class _HostileStr:
    def __str__(self):
        raise RuntimeError("str is a trap")

    def __repr__(self):
        raise ValueError("repr is a trap too")


class _HostileException(Exception):
    def __str__(self):
        raise RuntimeError("exception str explodes")


class _HostileTypeName(Exception):
    pass


_HostileTypeName.__name__ = "x" * 10_000  # absurd type name


class TestHostileObjects:
    def test_safe_str_survives_everything(self):
        assert "object" in safe_str(_HostileStr()) or "unprintable" in safe_str(
            _HostileStr()
        )
        assert len(safe_str("y" * 100_000)) <= 4096

    def test_build_safe_with_hostile_exception(self):
        report = ErrorReport.build_safe(
            FaultTypes.NODE_ERROR, exc=_HostileException("unreachable")
        )
        assert report.error_type == FaultTypes.NODE_ERROR
        assert report.exception is not None
        # message fell back to something printable, never raised
        assert isinstance(report.exception.message, str)
        assert report.model_dump_json()  # must serialize

    def test_build_safe_with_hostile_type_name_and_data(self):
        report = ErrorReport.build_safe(
            FaultTypes.NODE_ERROR,
            exc=_HostileTypeName("boom"),
            data={"weird": _HostileStr(), "k" * 5000: 1},
        )
        assert len(report.exception.type) <= 256
        assert report.model_dump_json()

    async def test_hostile_exception_through_full_agent_path(self):
        """A tool raising a hostile exception must land as a typed fault at
        the client — not crash the worker or wedge the run."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.models.messages import ModelResponse, ToolCallOutput
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool
        def landmine() -> str:
            raise _HostileException("never printable")

        def scripted(messages, params):
            return ModelResponse(parts=[
                ToolCallOutput(tool_call_id="t", tool_name="landmine", args={})
            ])

        mesh = InMemoryMesh()
        agent = Agent(
            "hostile_agent", model=FunctionModelClient(scripted),
            tools=[landmine],
        )
        async with Worker([agent, landmine], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            with pytest.raises(NodeFaultError) as exc_info:
                await client.agent("hostile_agent").execute("go", timeout=15)
            assert exc_info.value.report.error_type == FaultTypes.CALLEE_FAULT
            # worker is still alive: a normal run succeeds afterwards
            def fine(messages, params):
                from calfkit_tpu.models.messages import TextOutput

                return ModelResponse(parts=[TextOutput(text="alive")])

            agent2 = Agent("second_agent", model=FunctionModelClient(fine))
            # second agent joins the same (running) worker's mesh via a
            # second worker to prove the broker + client survived
            async with Worker([agent2], mesh=mesh):
                result = await client.agent("second_agent").execute(
                    "x", timeout=15
                )
                assert result.output == "alive"
            await client.close()


# --------------------------------------------------------------------------- #
# oversized-fault elision, end-to-end over the native broker
# --------------------------------------------------------------------------- #


@pytest.mark.skipif(meshd_missing, reason="meshd not built (make -C native)")
class TestElisionOverTcp:
    async def test_third_rung_state_elided_reaches_client(self, broker):
        """Force the elision ladder's last rung across a REAL transport:
        budget fits the call but not (report + state) → the client still
        gets a typed fault, with state_elided set."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        BUDGET = 6000

        def exploding_model(messages, params):
            raise RuntimeError("x" * 20_000)  # giant message + traceback

        worker_mesh = TcpMesh(f"127.0.0.1:{PORT}", max_message_bytes=BUDGET)
        await worker_mesh.start()
        client_mesh = TcpMesh(f"127.0.0.1:{PORT}", max_message_bytes=BUDGET)
        await client_mesh.start()
        agent = Agent("elide_agent", model=FunctionModelClient(exploding_model))
        async with Worker([agent], mesh=worker_mesh):
            client = Client.connect(client_mesh)
            # ~3 KB of conversation state: call fits the 6 KB budget, the
            # fault (report ≥ 4 KB message even without traceback) does not
            with pytest.raises(NodeFaultError) as exc_info:
                await client.agent("elide_agent").execute(
                    "y" * 3000, timeout=20
                )
            err = exc_info.value
            assert err.report.error_type == FaultTypes.MODEL_ERROR
            assert err.envelope is not None
            assert err.envelope.state_elided is True
            assert err.envelope.context.state.message_history == []
            await client.close()
        await worker_mesh.stop()
        await client_mesh.stop()


# --------------------------------------------------------------------------- #
# fan-out batch survives a worker crash mid-batch (durable tables on meshd)
# --------------------------------------------------------------------------- #


@pytest.mark.skipif(meshd_missing, reason="meshd not built (make -C native)")
class TestFanoutCrashResume:
    async def test_worker_crash_mid_batch_second_worker_closes(
        self, broker, tmp_path
    ):
        """Worker A opens a durable fan-out batch and dies before any fold;
        worker B (same node, same group) folds the sibling replies against
        the compacted tables and finishes the run.

        Determinism: the tool GATES on a sentinel file the test writes only
        after worker A is fully stopped — no fold can exist while A lives,
        so the handover cannot flake on scheduler/ktables timing (the old
        fixed-sleep version raced A's graceful drain against the first
        fold and lost under CPU contention)."""
        from calfkit_tpu import protocol
        from calfkit_tpu.nodes import agent_tool, handler
        from calfkit_tpu.nodes.base import BaseNodeDef
        from calfkit_tpu.worker import Worker
        from tests.kernel_harness import Caller

        resumed_on: list[str] = []

        class FanNode(BaseNodeDef):
            kind = "agent"

            def __init__(self, name, worker_tag):
                super().__init__(name)
                self.worker_tag = worker_tag

            def input_topics(self):
                return [protocol.agent_input_topic(self.name)]

            def return_topic(self):
                return protocol.agent_return_topic(self.name)

            def publish_topic(self):
                return protocol.agent_publish_topic(self.name)

            @handler("run")
            async def run(self, ctx):
                if ctx.delivery_kind == "call":
                    return [
                        Call(
                            target_topic="tool.slow_double.input",
                            route="run",
                            parts=[DataPart(data={"x": i})],
                            tag=f"tc-{i}",
                            marker=ToolCallMarker(
                                tool_call_id=f"tc-{i}", tool_name="slow_double"
                            ),
                        )
                        for i in range(3)
                    ]
                resumed_on.append(self.worker_tag)
                results = sorted(
                    ctx.state.tool_results[k].content for k in ctx.state.tool_results
                )
                return ReturnCall(parts=[TextPart(text=",".join(results))])

        gate = tmp_path / "worker_a_is_dead"

        @agent_tool
        async def slow_double(x: int) -> int:
            """Double, slowly.

            Args:
                x: Input.
            """
            # async gate-wait: the tool worker's loop (heartbeats, polls)
            # keeps running while folds are held back until A is stopped
            for _ in range(600):
                if gate.exists():
                    break
                await asyncio.sleep(0.05)
            return x * 2

        fan_mesh_a = TcpMesh(f"127.0.0.1:{PORT}")
        await fan_mesh_a.start()
        tool_mesh = TcpMesh(f"127.0.0.1:{PORT}")
        await tool_mesh.start()
        caller_mesh = TcpMesh(f"127.0.0.1:{PORT}")
        await caller_mesh.start()

        tool_worker = Worker([slow_double], mesh=tool_mesh)
        await tool_worker.start()

        worker_a = Worker([FanNode("crashfan", "A")], mesh=fan_mesh_a)
        await worker_a.start()

        caller = Caller(caller_mesh)
        await caller.start()

        await caller.call("agent.crashfan.private.input", [])

        # let the call delivery start; worker A's graceful stop() drains
        # the in-flight delivery, so the batch OPEN + dispatch always
        # completes before A goes down — and the gated tool guarantees no
        # fold exists yet
        await asyncio.sleep(0.3)
        await worker_a.stop()  # "crash": no folds processed on A
        await fan_mesh_a.stop()
        gate.write_text("dead")  # now the tool may reply

        fan_mesh_b = TcpMesh(f"127.0.0.1:{PORT}")
        await fan_mesh_b.start()
        worker_b = Worker([FanNode("crashfan", "B")], mesh=fan_mesh_b)
        await worker_b.start()

        headers, env = await caller.wait_reply(timeout=30)
        assert headers[protocol.HDR_KIND] == "return"
        assert env.reply.parts[0].text == "0,2,4"
        assert resumed_on == ["B"]  # the close happened on the second worker

        await worker_b.stop()
        await tool_worker.stop()
        await fan_mesh_b.stop()
        await tool_mesh.stop()
        await caller_mesh.stop()


class TestHostileClientInbox:
    async def test_inbox_junk_barrage_does_not_break_live_runs(self):
        """A hostile/buggy producer blasts the client's inbox (non-JSON,
        non-object JSON, junk step/envelope frames, a VALID envelope with
        an unknown correlation): the client's decode floor must absorb it
        all — in-flight runs complete, later runs work, nothing crashes."""
        import json
        import random

        from calfkit_tpu import Agent, Client, InMemoryMesh, Worker, protocol
        from calfkit_tpu.engine import EchoModelClient

        rng = random.Random(67)
        mesh = InMemoryMesh()
        agent = Agent(name="steady", model=EchoModelClient(),
                      instructions="reply")
        async with Worker([agent], mesh=mesh):
            client = Client.connect(mesh)
            inbox = client.inbox_topic

            async def blast() -> None:
                for i in range(60):
                    kind = i % 5
                    if kind == 0:  # non-JSON
                        value = rng.randbytes(rng.randint(1, 200))
                        headers = {protocol.HDR_WIRE: "envelope",
                                   protocol.HDR_CORRELATION: "junk"}
                    elif kind == 1:  # JSON non-object
                        value = json.dumps([1, 2, 3]).encode()
                        headers = {protocol.HDR_WIRE: "envelope",
                                   protocol.HDR_CORRELATION: "junk"}
                    elif kind == 2:  # junk step frame
                        value = b'{"steps": "not-a-list"}'
                        headers = {protocol.HDR_WIRE: "step",
                                   protocol.HDR_CORRELATION: "junk"}
                    elif kind == 3:  # headerless garbage
                        value = rng.randbytes(32)
                        headers = {}
                    else:  # VALID envelope, unknown correlation
                        from calfkit_tpu.models.session_context import Envelope
                        from calfkit_tpu.models import ReturnMessage, TextPart

                        value = Envelope(reply=ReturnMessage(
                            parts=[TextPart(text="stray")]
                        )).to_wire()
                        headers = {protocol.HDR_WIRE: "envelope",
                                   protocol.HDR_CORRELATION: f"ghost-{i}",
                                   protocol.HDR_TASK: "ghost"}
                    await mesh.publish(inbox, value, key=b"junk",
                                       headers=headers)
                    await asyncio.sleep(0)

            run = client.agent("steady").execute("are you alive", timeout=30)
            result, _ = await asyncio.gather(run, blast())
            assert result.output
            # the client keeps serving after the barrage
            again = await client.agent("steady").execute("still?", timeout=30)
            assert again.output
            await client.close()
