"""Agent-turn engine: schema extraction, turn runner, test models."""

import pytest
from pydantic import BaseModel

from calfkit_tpu.engine import (
    EchoModelClient,
    FunctionModelClient,
    ModelRequestParameters,
    TestModelClient,
    function_schema,
    run_turn,
)
from calfkit_tpu.engine.schema import ToolSchemaError, output_tool_def
from calfkit_tpu.engine.turn import FINAL_RESULT_TOOL, TurnError
from calfkit_tpu.models.messages import (
    ModelResponse,
    TextOutput,
    ToolCallOutput,
    user_message,
)


class TestFunctionSchema:
    def test_extraction_with_docstring(self):
        def get_weather(city: str, units: str = "celsius") -> str:
            """Get current weather for a city.

            Args:
                city: The city name to look up.
                units: Temperature units.
            """
            return f"{city}:{units}"

        fs = function_schema(get_weather)
        assert fs.tool_def.name == "get_weather"
        assert fs.tool_def.description == "Get current weather for a city."
        props = fs.tool_def.parameters_schema["properties"]
        assert props["city"]["description"] == "The city name to look up."
        assert props["city"]["type"] == "string"
        assert fs.tool_def.parameters_schema["required"] == ["city"]
        assert not fs.takes_ctx

    def test_ctx_param_excluded(self):
        def tool(ctx, x: int) -> int:
            return x

        fs = function_schema(tool)
        assert fs.takes_ctx
        assert list(fs.tool_def.parameters_schema["properties"]) == ["x"]

    async def test_call_validates_and_coerces(self):
        def add(a: int, b: int = 1) -> int:
            return a + b

        fs = function_schema(add)
        assert await fs.call({"a": "2", "b": 3}) == 5
        assert await fs.call({"a": 1}) == 2
        with pytest.raises(Exception):
            await fs.call({"a": "not-an-int"})

    async def test_async_fn_and_ctx_injection(self):
        async def fetch(ctx, q: str) -> str:
            return f"{ctx}:{q}"

        fs = function_schema(fetch)
        assert await fs.call({"q": "x"}, ctx="C") == "C:x"

    def test_var_args_rejected(self):
        def bad(*args): ...

        with pytest.raises(ToolSchemaError):
            function_schema(bad)


class _Weather(BaseModel):
    city: str
    temp_c: float


class TestRunTurn:
    async def test_text_final(self):
        outcome = await run_turn(EchoModelClient(), [user_message("hi")])
        assert outcome.is_final and outcome.output == "echo: hi"
        assert len(outcome.new_messages) == 1

    async def test_tool_calls_deferred(self):
        def model(messages, params):
            return ModelResponse(parts=[
                ToolCallOutput(tool_call_id="1", tool_name="get_weather",
                               args={"city": "SF"})
            ])

        fs = function_schema(lambda city: city, name="get_weather")
        outcome = await run_turn(
            FunctionModelClient(model), [user_message("weather?")],
            tool_defs=[fs.tool_def],
        )
        assert not outcome.is_final
        assert outcome.tool_calls[0].tool_name == "get_weather"

    async def test_structured_output_via_tool(self):
        def model(messages, params):
            assert params.output_tool is not None
            return ModelResponse(parts=[
                ToolCallOutput(tool_call_id="1", tool_name=FINAL_RESULT_TOOL,
                               args={"city": "SF", "temp_c": 18.0})
            ])

        outcome = await run_turn(
            FunctionModelClient(model), [user_message("weather?")],
            output_type=_Weather,
        )
        assert outcome.is_final and outcome.output.city == "SF"

    async def test_structured_output_retry_then_success(self):
        calls = {"n": 0}

        def model(messages, params):
            calls["n"] += 1
            if calls["n"] == 1:
                return ModelResponse(parts=[
                    ToolCallOutput(tool_call_id="1", tool_name=FINAL_RESULT_TOOL,
                                   args={"city": "SF"})  # missing temp_c
                ])
            return ModelResponse(parts=[
                ToolCallOutput(tool_call_id="2", tool_name=FINAL_RESULT_TOOL,
                               args={"city": "SF", "temp_c": 1.0})
            ])

        outcome = await run_turn(
            FunctionModelClient(model), [user_message("x")], output_type=_Weather,
        )
        assert outcome.output.temp_c == 1.0
        assert calls["n"] == 2
        # retry request committed to history between the two responses
        assert outcome.new_messages[1].parts[0].kind == "retry"

    async def test_structured_output_exhausted_retries(self):
        def model(messages, params):
            return ModelResponse(parts=[TextOutput(text="not json at all")])

        with pytest.raises(TurnError) as exc_info:
            await run_turn(
                FunctionModelClient(model), [user_message("x")],
                output_type=_Weather, max_output_retries=1,
            )
        assert "mesh.validation_error" in exc_info.value.report.error_type

    async def test_author_stamped(self):
        outcome = await run_turn(
            EchoModelClient(), [user_message("hi")], author="weather_agent"
        )
        assert outcome.response.author == "weather_agent"


class TestTestModel:
    async def test_calls_all_tools_then_finalizes(self):
        model = TestModelClient(custom_output_text="done")

        def get_weather(city: str) -> str:
            return city

        fs = function_schema(get_weather)
        params = ModelRequestParameters(tool_defs=[fs.tool_def])
        first = await model.request([user_message("x")], None, params)
        assert first.tool_calls()[0].tool_name == "get_weather"
        assert first.tool_calls()[0].args_dict() == {"city": "a"}
        history = [user_message("x"), first]
        second = await model.request(history, None, params)
        assert second.text() == "done"

    async def test_structured_output_stub(self):
        model = TestModelClient()
        params = ModelRequestParameters(
            output_tool=output_tool_def(_Weather), allow_text_output=False
        )
        resp = await model.request([user_message("x")], None, params)
        call = resp.tool_calls()[0]
        assert call.tool_name == FINAL_RESULT_TOOL
        assert set(call.args_dict()) == {"city", "temp_c"}
