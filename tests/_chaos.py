"""Deterministic chaos harness (ISSUE 5).

Small, composable fault-injection pieces the chaos scenarios in
``tests/test_chaos.py`` (and the shed/expire parity matrix in
``tests/test_overlap_dispatch.py``) script against:

- :class:`VirtualClock` / :func:`virtual_clock` — drives EVERY deadline
  comparison in the package (client mint, hop expiry, engine
  admission/reap) through the single ``calfkit_tpu.cancellation.
  wall_clock`` seam.  Scenarios advance time explicitly; nothing sleeps
  to make a deadline pass.
- :class:`ChaosScript` — the engine's ``_chaos`` seam: fires a scripted
  exception at the Nth visit of a named point ("tick" per scheduler
  pass, "dispatch" per decode tick), so a mid-stream engine fault lands
  on an exact, reproducible dispatch.
- :class:`BrokerChaos` — the in-memory mesh's publish hook
  (``InMemoryMesh.chaos``): drops the Nth record matching a
  topic/kind predicate ("broker loses the return"), counts everything
  it sees, and can run scripted side effects at publish time (e.g.
  advance the virtual clock between the client's mint and the node's
  delivery — the expired-on-arrival scenario).
- :func:`settle` — await a condition within a BOUNDED number of
  event-loop ticks; the harness's only waiting primitive.
- :func:`assert_engine_drained` — the no-leak oracle: no active slots,
  no in-flight dispatch, every slot on the free list, every page back
  in the pool.

Everything is plain deterministic state — no randomness, no wall-clock
dependence beyond the event loop needing to actually run.
"""

from __future__ import annotations

import asyncio
import contextlib
from typing import Any, Callable, Iterator

from calfkit_tpu import cancellation
from calfkit_tpu import protocol


class VirtualClock:
    """A controllable stand-in for ``cancellation.wall_clock``."""

    def __init__(self, start: float = 1_700_000_000.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> float:
        self.now += seconds
        return self.now


@contextlib.contextmanager
def virtual_clock(start: float = 1_700_000_000.0) -> "Iterator[VirtualClock]":
    """Install a :class:`VirtualClock` as THE package deadline clock for
    the duration of the block (every caller reads it through the module
    attribute, so one swap moves all layers in lockstep)."""
    clock = VirtualClock(start)
    previous = cancellation.wall_clock
    cancellation.wall_clock = clock
    try:
        yield clock
    finally:
        cancellation.wall_clock = previous


class ChaosScript:
    """Scripted failure points for the engine's ``_chaos`` seam.

    >>> engine._chaos = ChaosScript().fail_at("dispatch", 3, RuntimeError("x"))

    raises on the 3rd decode tick exactly; every other visit is a no-op.
    ``calls`` keeps per-point visit counts for assertions.
    """

    def __init__(self) -> None:
        self.calls: dict[str, int] = {}
        self._plan: dict[tuple[str, int], BaseException] = {}

    def fail_at(
        self, point: str, nth: int, exc: BaseException
    ) -> "ChaosScript":
        self._plan[(point, nth)] = exc
        return self

    def __call__(self, point: str) -> None:
        count = self.calls.get(point, 0) + 1
        self.calls[point] = count
        exc = self._plan.pop((point, count), None)
        if exc is not None:
            raise exc


class BrokerChaos:
    """Scripted broker misbehavior for ``InMemoryMesh.chaos``.

    Rules match on message kind (the ``x-mesh-kind`` header) and/or a
    topic substring; each drops up to ``count`` matching records.  All
    publishes are recorded in ``seen`` as ``(topic, kind)`` so scenarios
    can assert what crossed the broker (e.g. "a cancel record WAS
    published after the timeout").  ``on_publish`` hooks run for every
    record — the deterministic place to advance a virtual clock between
    a client's deadline mint and the node's delivery.
    """

    def __init__(self) -> None:
        self.seen: list[tuple[str, str]] = []
        self.dropped: list[tuple[str, str]] = []
        self._rules: list[dict[str, Any]] = []
        self.on_publish: "Callable[[str, dict[str, str]], None] | None" = None

    def drop(
        self,
        *,
        kind: "str | None" = None,
        topic_contains: "str | None" = None,
        count: int = 1,
    ) -> "BrokerChaos":
        self._rules.append(
            {"kind": kind, "topic": topic_contains, "count": count}
        )
        return self

    def kinds_seen(self, kind: str) -> int:
        return sum(1 for _, k in self.seen if k == kind)

    def __call__(self, topic: str, headers: dict[str, str]) -> "str | None":
        kind = headers.get(protocol.HDR_KIND, "")
        self.seen.append((topic, kind))
        if self.on_publish is not None:
            self.on_publish(topic, headers)
        for rule in self._rules:
            if rule["count"] <= 0:
                continue
            if rule["kind"] is not None and kind != rule["kind"]:
                continue
            if rule["topic"] is not None and rule["topic"] not in topic:
                continue
            rule["count"] -= 1
            self.dropped.append((topic, kind))
            return "drop"
        return None


async def settle(
    condition: Callable[[], bool],
    *,
    ticks: int = 400,
    interval: float = 0.01,
    message: str = "",
) -> int:
    """Await ``condition`` within a bounded number of event-loop ticks;
    returns the tick count it took.  The ONLY waiting primitive chaos
    scenarios use — an unmet condition is a bounded, attributable
    failure, never a hang."""
    for tick in range(ticks):
        if condition():
            return tick
        await asyncio.sleep(interval)
    raise AssertionError(
        message or f"condition not met within {ticks} bounded ticks"
    )


def assert_engine_drained(engine: Any, total_free_pages: "int | None" = None) -> None:
    """The no-leak oracle: every slot free, no in-flight dispatch, no
    queued entries, and (paged) every page back in the pool."""
    assert not engine._active, f"leaked active slots: {dict(engine._active)}"
    assert engine._pend is None, "a dispatch is still marked in flight"
    assert engine._inflight is None, "a chunked admission wave leaked"
    assert not engine._admitting, "an admission prefill is still in flight"
    assert not engine._pending and not engine._carry, "queued entries leaked"
    assert not engine._long_pending and engine._long is None
    assert len(engine._free) == engine.runtime.max_batch_size, (
        f"free list has {len(engine._free)} of "
        f"{engine.runtime.max_batch_size} slots"
    )
    if total_free_pages is not None and engine._page_alloc is not None:
        assert engine._page_alloc.free_pages == total_free_pages, (
            f"leaked pages: {engine._page_alloc.free_pages} free of "
            f"{total_free_pages}"
        )
