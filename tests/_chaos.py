"""DEPRECATED import shim — the chaos harness is now ``calfkit_tpu.sim``.

ISSUE 11 promoted the deterministic chaos harness (virtual clock,
scripted fault injectors, the replica death/partition transport, the
fleet topology, the serving stubs) out of this test-support module into
the first-class, mypy-gated ``calfkit_tpu/sim/`` package, where the
fleet simulator and the perf gate build on it.  Every name that ever
lived here is re-exported below so existing chaos scenarios keep
importing ``tests._chaos`` unchanged.

New code should import from ``calfkit_tpu.sim`` directly:

    from calfkit_tpu.sim import VirtualClock, virtual_clock
    from calfkit_tpu.sim import ChaosScript, BrokerChaos, settle
    from calfkit_tpu.sim import FleetTopology, ReplicaTransport
    from calfkit_tpu.sim import ServingStubModel, StreamingStubModel

This shim will stay until the chaos suites migrate their imports; do
not add new names here.
"""

from calfkit_tpu.sim.chaos import (  # noqa: F401
    BrokerChaos,
    ChaosScript,
    assert_engine_drained,
    settle,
)
from calfkit_tpu.sim.clock import VirtualClock, virtual_clock  # noqa: F401
from calfkit_tpu.sim.stubs import (  # noqa: F401
    BijectiveTokenizer,
    ServingStubModel,
    StreamingStubModel,
)
from calfkit_tpu.sim.topology import FleetTopology  # noqa: F401
from calfkit_tpu.sim.transport import (  # noqa: F401
    ReplicaTransport,
    _DeliveryGate,
    _GatedTableWriter,
)

__all__ = [
    "BrokerChaos",
    "ChaosScript",
    "assert_engine_drained",
    "settle",
    "VirtualClock",
    "virtual_clock",
    "BijectiveTokenizer",
    "ServingStubModel",
    "StreamingStubModel",
    "FleetTopology",
    "ReplicaTransport",
]
