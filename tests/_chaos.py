"""Deterministic chaos harness (ISSUE 5).

Small, composable fault-injection pieces the chaos scenarios in
``tests/test_chaos.py`` (and the shed/expire parity matrix in
``tests/test_overlap_dispatch.py``) script against:

- :class:`VirtualClock` / :func:`virtual_clock` — drives EVERY deadline
  comparison in the package (client mint, hop expiry, engine
  admission/reap) through the single ``calfkit_tpu.cancellation.
  wall_clock`` seam.  Scenarios advance time explicitly; nothing sleeps
  to make a deadline pass.
- :class:`ChaosScript` — the engine's ``_chaos`` seam: fires a scripted
  exception at the Nth visit of a named point ("tick" per scheduler
  pass, "dispatch" per decode tick), so a mid-stream engine fault lands
  on an exact, reproducible dispatch.
- :class:`BrokerChaos` — the in-memory mesh's publish hook
  (``InMemoryMesh.chaos``): drops the Nth record matching a
  topic/kind predicate ("broker loses the return"), counts everything
  it sees, and can run scripted side effects at publish time (e.g.
  advance the virtual clock between the client's mint and the node's
  delivery — the expired-on-arrival scenario).
- :func:`settle` — await a condition within a BOUNDED number of
  event-loop ticks; the harness's only waiting primitive.
- :func:`assert_engine_drained` — the no-leak oracle: no active slots,
  no in-flight dispatch, every slot on the free list, every page back
  in the pool.
- :class:`FleetTopology` (ISSUE 7) — spawns MULTI-WORKER topologies: N
  workers on one shared mesh, each hosting a replica of the same agent
  name, with fast heartbeats and per-replica delivery ledgers, so
  replica failover, drain handoff, and shed-retry storms run
  deterministically under the virtual clock.  Includes the
  heartbeat-wedge/resume seam for stale-replica scenarios (a wedged
  publisher stops re-stamping; everything else keeps serving).

Everything is plain deterministic state — no randomness, no wall-clock
dependence beyond the event loop needing to actually run.
"""

from __future__ import annotations

import asyncio
import contextlib
import threading
import time
from typing import Any, Callable, Iterator

from calfkit_tpu import cancellation
from calfkit_tpu import protocol
from calfkit_tpu.mesh.tables import TableWriter
from calfkit_tpu.mesh.transport import MeshTransport


class VirtualClock:
    """A controllable stand-in for ``cancellation.wall_clock``."""

    def __init__(self, start: float = 1_700_000_000.0):
        self.now = float(start)

    def __call__(self) -> float:
        return self.now

    def advance(self, seconds: float) -> float:
        self.now += seconds
        return self.now


@contextlib.contextmanager
def virtual_clock(start: float = 1_700_000_000.0) -> "Iterator[VirtualClock]":
    """Install a :class:`VirtualClock` as THE package deadline clock for
    the duration of the block (every caller reads it through the module
    attribute, so one swap moves all layers in lockstep)."""
    clock = VirtualClock(start)
    previous = cancellation.wall_clock
    cancellation.wall_clock = clock
    try:
        yield clock
    finally:
        cancellation.wall_clock = previous


class ChaosScript:
    """Scripted failure points for the engine's ``_chaos`` seam.

    >>> engine._chaos = ChaosScript().fail_at("dispatch", 3, RuntimeError("x"))

    raises on the 3rd decode tick exactly; every other visit is a no-op.
    ``calls`` keeps per-point visit counts for assertions.
    """

    def __init__(self) -> None:
        self.calls: dict[str, int] = {}
        self._plan: dict[tuple[str, int], BaseException] = {}
        self._blocks: dict[tuple[str, int], "threading.Event"] = {}

    def fail_at(
        self, point: str, nth: int, exc: BaseException
    ) -> "ChaosScript":
        self._plan[(point, nth)] = exc
        return self

    def block_at(
        self, point: str, nth: int, gate: "threading.Event"
    ) -> "ChaosScript":
        """On the Nth visit of ``point``, BLOCK until ``gate`` is set —
        the wedged-device-grant simulator (ISSUE 9): the decode thread
        (and with it the whole serve loop, stuck in its to_thread) hangs
        exactly like a hung device sync, and only the watchdog's own
        task can observe it.  ``gate.set()`` releases the dispatch, which
        then lands normally (the recovery path)."""
        self._blocks[(point, nth)] = gate
        return self

    def __call__(self, point: str) -> None:
        count = self.calls.get(point, 0) + 1
        self.calls[point] = count
        gate = self._blocks.pop((point, count), None)
        if gate is not None:
            gate.wait()
        exc = self._plan.pop((point, count), None)
        if exc is not None:
            raise exc


class BrokerChaos:
    """Scripted broker misbehavior for ``InMemoryMesh.chaos``.

    Rules match on message kind (the ``x-mesh-kind`` header) and/or a
    topic substring; each drops up to ``count`` matching records.  All
    publishes are recorded in ``seen`` as ``(topic, kind)`` so scenarios
    can assert what crossed the broker (e.g. "a cancel record WAS
    published after the timeout").  ``on_publish`` hooks run for every
    record — the deterministic place to advance a virtual clock between
    a client's deadline mint and the node's delivery.
    """

    def __init__(self) -> None:
        self.seen: list[tuple[str, str]] = []
        self.dropped: list[tuple[str, str]] = []
        self._rules: list[dict[str, Any]] = []
        self.on_publish: "Callable[[str, dict[str, str]], None] | None" = None

    def drop(
        self,
        *,
        kind: "str | None" = None,
        topic_contains: "str | None" = None,
        count: int = 1,
    ) -> "BrokerChaos":
        self._rules.append(
            {"kind": kind, "topic": topic_contains, "count": count}
        )
        return self

    def kinds_seen(self, kind: str) -> int:
        return sum(1 for _, k in self.seen if k == kind)

    def __call__(self, topic: str, headers: dict[str, str]) -> "str | None":
        kind = headers.get(protocol.HDR_KIND, "")
        self.seen.append((topic, kind))
        if self.on_publish is not None:
            self.on_publish(topic, headers)
        for rule in self._rules:
            if rule["count"] <= 0:
                continue
            if rule["kind"] is not None and kind != rule["kind"]:
                continue
            if rule["topic"] is not None and rule["topic"] not in topic:
                continue
            rule["count"] -= 1
            self.dropped.append((topic, kind))
            return "drop"
        return None


async def settle(
    condition: Callable[[], bool],
    *,
    ticks: int = 400,
    interval: float = 0.01,
    message: str = "",
) -> int:
    """Await ``condition`` within a bounded number of event-loop ticks;
    returns the tick count it took.  The ONLY waiting primitive chaos
    scenarios use — an unmet condition is a bounded, attributable
    failure, never a hang."""
    for tick in range(ticks):
        if condition():
            return tick
        await asyncio.sleep(interval)
    raise AssertionError(
        message or f"condition not met within {ticks} bounded ticks"
    )


class ServingStubModel:
    """A scripted model that LOOKS engine-backed to the fleet machinery:
    ``stats_snapshot`` makes its agent advertise on ``mesh.engine_stats``
    (and subscribe its replica-addressed topic) without paying for a real
    inference engine.  ``load`` feeds the queue-depth signal policies
    rank on; ``replies`` counts turns served by THIS replica."""

    def __init__(self, *, text: str = "ok", load: int = 0):
        self.text = text
        self.load = load
        self.replies = 0

    @property
    def model_name(self) -> str:
        return "serving-stub"

    def stats_snapshot(self, *, window: bool = False) -> dict:
        return {
            "model_name": self.model_name,
            "active_requests": self.load,
            "pending_requests": 0,
        }

    async def request(self, messages, settings=None, params=None):
        from calfkit_tpu.engine.testing import _estimate_tokens
        from calfkit_tpu.models.messages import (
            ModelResponse,
            TextOutput,
            Usage,
        )

        self.replies += 1
        return ModelResponse(
            parts=[TextOutput(text=self.text)],
            usage=Usage(
                input_tokens=_estimate_tokens(messages), output_tokens=1
            ),
            model_name=self.model_name,
        )


class _GatedTableWriter(TableWriter):
    """A dead replica's heartbeat puts/tombstones never reach the table —
    its last stamp stays frozen there, exactly what a killed process
    leaves behind (no tombstone: that would be a CLEAN shutdown)."""

    def __init__(self, owner: "ReplicaTransport", inner: TableWriter):
        self._owner = owner
        self._inner = inner

    async def put(self, key: str, value: bytes) -> None:
        if self._owner.dead:
            self._owner.dropped.append(("<table-put>", key))
            return
        await self._inner.put(key, value)

    async def tombstone(self, key: str) -> None:
        if self._owner.dead:
            self._owner.dropped.append(("<table-tombstone>", key))
            return
        await self._inner.tombstone(key)


class _DeliveryGate:
    """The consumption half of a process death: while dead, deliveries
    buffer (the dead process's partition backlog) instead of reaching
    the node handler; ``replay()`` on resume drains the backlog with
    cancel records FIRST — mirroring the dispatcher's express intake,
    where a cancel skips the ordered lanes and therefore lands before
    the queued work it abandons gets to execute."""

    def __init__(self, owner: "ReplicaTransport", inner: Any):
        self._owner = owner
        self._inner = inner
        self.buffered: list[Any] = []

    async def __call__(self, record: Any) -> None:
        if self._owner.dead:
            self.buffered.append(record)
            return
        await self._inner(record)

    async def replay(self) -> None:
        backlog, self.buffered = self.buffered, []
        cancels = [
            r for r in backlog
            if r.headers.get(protocol.HDR_KIND) == "cancel"
        ]
        rest = [
            r for r in backlog
            if r.headers.get(protocol.HDR_KIND) != "cancel"
        ]
        for record in cancels + rest:
            await self._inner(record)


class ReplicaTransport(MeshTransport):
    """One replica's I/O boundary over the (shared) mesh — the
    process-death seam (ISSUE 9).

    ``kill()`` models a hard kill: NOTHING the replica publishes reaches
    the mesh (heartbeats stop landing with the last stamp frozen on the
    table, a half-delivered stream just stops, terminal replies vanish)
    and nothing is consumed (deliveries buffer like the dead consumer's
    backlog).  Compute the replica had in flight keeps burning — exactly
    the zombie the cancel-tombstone law exists for.  ``resume()`` models
    that zombie coming back: publishes flow again, the backlog replays
    (cancels first, per the dispatcher's express law), and the next
    heartbeat re-stamps the advert."""

    def __init__(self, inner: MeshTransport):
        self.inner = inner
        self.dead = False
        self.dropped: list[tuple[str, str]] = []  # publishes lost while dead
        self._gates: list[_DeliveryGate] = []

    def kill(self) -> None:
        self.dead = True

    async def resume(self) -> None:
        self.dead = False
        for gate in self._gates:
            await gate.replay()

    # ------------------------------------------------------- transport
    async def start(self) -> None:
        await self.inner.start()

    async def stop(self) -> None:
        await self.inner.stop()

    @property
    def max_message_bytes(self) -> int:
        return self.inner.max_message_bytes

    async def publish(self, topic, value, *, key=None, headers=None):
        if self.dead:
            self.dropped.append(
                (topic, (headers or {}).get(protocol.HDR_KIND, ""))
            )
            return
        await self.inner.publish(topic, value, key=key, headers=headers)

    async def subscribe(self, topics, handler, **kwargs):
        gate = _DeliveryGate(self, handler)
        self._gates.append(gate)
        return await self.inner.subscribe(topics, gate, **kwargs)

    async def ensure_topics(self, names, *, compacted=False):
        await self.inner.ensure_topics(names, compacted=compacted)

    def table_reader(self, topic):
        return self.inner.table_reader(topic)

    def table_writer(self, topic):
        return _GatedTableWriter(self, self.inner.table_writer(topic))


class BijectiveTokenizer:
    """Token id ↔ character bijection for byte-exact resume tests
    (ISSUE 10): generated id ``i`` decodes to ``chr(0x100 + i)`` and
    encodes back to exactly ``i`` — so re-encoding a delivered prefix
    reproduces the original token ids and greedy decode-from-offset
    parity is literal byte equality (ByteTokenizer's UTF-8 replacement
    chars break the round trip for arbitrary model outputs).  Prompt
    characters below U+0100 encode to their ordinal, within the debug
    preset's 512-token vocab."""

    pad_id = 0
    bos_id = 1
    eos_id = 2

    def encode(self, text: str) -> "list[int]":
        return [
            ord(c) - 0x100 if ord(c) >= 0x100 else ord(c) for c in text
        ]

    def decode(self, ids: "list[int]") -> str:
        return "".join(chr(0x100 + i) for i in ids if i >= 0)


class StreamingStubModel(ServingStubModel):
    """A ServingStubModel whose ``request_stream`` yields word-sized
    deltas and PAUSES after ``pause_after`` of them until ``release`` is
    set — the deterministic mid-stream seam: a scenario observes the
    first delivered tokens, kills the replica, and knows exactly how
    much text the caller saw.  The stream keeps yielding after the kill
    (a dead replica's compute keeps burning); the transport seam drops
    the output."""

    def __init__(
        self,
        *,
        text: str = "alpha beta gamma delta",
        pause_after: int = 1,
        load: int = 0,
    ):
        super().__init__(text=text, load=load)
        self.pause_after = pause_after
        self.release = asyncio.Event()
        self.streamed: list[str] = []

    async def request_stream(self, messages, settings=None, params=None):
        from calfkit_tpu.engine.model_client import ResponseDone, TextDelta

        words = self.text.split(" ")
        deltas = [
            w + (" " if i < len(words) - 1 else "")
            for i, w in enumerate(words)
        ]
        for i, delta in enumerate(deltas):
            if i == self.pause_after:
                await self.release.wait()
            self.streamed.append(delta)
            yield TextDelta(delta)
            await asyncio.sleep(0)
        response = await super().request(messages, settings, params)
        yield ResponseDone(response)


class FleetTopology:
    """N workers hosting replicas of ONE agent name on a shared mesh.

    Each replica is its own :class:`~calfkit_tpu.worker.Worker` (own
    dispatch lanes, own control-plane publisher, own drain state) —
    exactly the multi-process fleet shape, collapsed into one event loop
    so scenarios stay deterministic.  ``delivered[i]`` ledgers the
    correlation ids whose CALLS were admitted by replica ``i`` (the
    drain/stale scenarios' "zero new calls" oracle).

    Heartbeats tick fast on the REAL event loop; liveness stamps ride
    the virtual clock (the ``wall_clock`` seam), so staleness is driven
    by ``clock.advance``, never by sleeping.
    """

    def __init__(
        self,
        mesh: Any,
        models: "list[Any]",
        *,
        name: str = "svc",
        heartbeat_interval: float = 0.05,
        stale_multiplier: float = 100.0,
        agent_kwargs: "dict | None" = None,
        meshes: "list[Any] | None" = None,
    ):
        from calfkit_tpu.controlplane import ControlPlaneConfig
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        self.mesh = mesh
        self.name = name
        self.config = ControlPlaneConfig(
            heartbeat_interval=heartbeat_interval,
            stale_multiplier=stale_multiplier,
        )
        self.delivered: "list[list[str]]" = [[] for _ in models]
        self.agents = []
        self.workers = []
        # every replica's I/O rides its own ReplicaTransport proxy — the
        # process-death seam (kill/resume).  ``meshes`` supplies a
        # per-replica INNER transport (e.g. one KafkaWireMesh connection
        # each, the real multi-process shape); default = the shared mesh.
        self.transports = [
            ReplicaTransport(inner)
            for inner in (meshes if meshes is not None else [mesh] * len(models))
        ]
        for i, model in enumerate(models):
            agent = Agent(
                name,
                model=model,
                before_node=[self._ledger(i)],
                **(agent_kwargs or {}),
            )
            self.agents.append(agent)
            self.workers.append(
                Worker(
                    [agent],
                    mesh=self.transports[i],
                    control_plane=self.config,
                    owns_transport=meshes is not None,
                )
            )

    def _ledger(self, i: int) -> Callable[[Any], None]:
        def note(ctx: Any) -> None:
            if ctx.delivery_kind == "call":
                self.delivered[i].append(ctx.correlation_id or "")
            return None

        return note

    # ------------------------------------------------------------ lifecycle
    async def __aenter__(self) -> "FleetTopology":
        for worker in self.workers:
            await worker.start()
        return self

    async def __aexit__(self, *exc: Any) -> None:
        for worker in self.workers:
            with contextlib.suppress(Exception):
                await worker.stop()

    # ------------------------------------------------------------- identity
    def instance_id(self, i: int) -> str:
        return self.agents[i].instance_id

    def replica_key(self, i: int) -> str:
        return f"{self.agents[i].node_id}@{self.instance_id(i)}"

    def index_of_lowest_key(self) -> int:
        """The replica a depth-tied least-loaded pick lands on (policies
        tie-break on the lexicographic replica key)."""
        return min(range(len(self.agents)), key=self.replica_key)

    def calls_delivered(self, i: int) -> int:
        return len(self.delivered[i])

    # ------------------------------------------------------ process death
    def kill(self, i: int) -> None:
        """Hard-kill replica ``i`` (ISSUE 9): stop consuming AND stop
        heartbeating, without drain — its advert stays on the table with
        the last stamp (staleness is then driven by ``clock.advance``),
        its in-flight output vanishes, its backlog buffers."""
        self.transports[i].kill()

    async def resume(self, i: int) -> None:
        """The killed replica returns as a ZOMBIE: backlog replays
        (cancels first, the express law), publishes flow, the next
        heartbeat re-stamps the advert fresh."""
        await self.transports[i].resume()

    # ---------------------------------------------------- heartbeat chaos
    def _publisher(self, i: int) -> Any:
        attached = self.workers[i]._advertiser
        assert attached is not None, "control plane not attached"
        return attached._publisher

    def wedge_heartbeat(self, i: int) -> None:
        """Simulate a wedged worker: the heartbeat loop dies, the record
        stays on the table with its last stamp (no tombstone — that
        would be a clean shutdown, a DIFFERENT scenario), and serving
        continues.  Advancing the virtual clock past ``stale_after``
        then makes the replica ineligible."""
        publisher = self._publisher(i)
        if publisher._task is not None:
            publisher._task.cancel()
            publisher._task = None

    async def resume_heartbeat(self, i: int) -> None:
        """The wedged worker recovers: one immediate re-advert (fresh
        stamp on the current virtual clock) and the tick loop restarts."""
        publisher = self._publisher(i)
        for advert in publisher._adverts:
            await publisher._writers[advert.topic].put(
                advert.key, publisher._record(advert).to_wire()
            )
        publisher._last_beat_at = time.monotonic()
        publisher._task = asyncio.get_running_loop().create_task(
            publisher._beat(), name=f"chaos-resumed-heartbeat-{i}"
        )


def assert_engine_drained(engine: Any, total_free_pages: "int | None" = None) -> None:
    """The no-leak oracle: every slot free, no in-flight dispatch, no
    queued entries, and (paged) every page back in the pool."""
    assert not engine._active, f"leaked active slots: {dict(engine._active)}"
    assert engine._pend is None, "a dispatch is still marked in flight"
    assert engine._inflight is None, "a chunked admission wave leaked"
    assert not engine._admitting, "an admission prefill is still in flight"
    assert not engine._pending and not engine._carry, "queued entries leaked"
    assert not engine._long_pending and engine._long is None
    assert len(engine._free) == engine.runtime.max_batch_size, (
        f"free list has {len(engine._free)} of "
        f"{engine.runtime.max_batch_size} slots"
    )
    if total_free_pages is not None and engine._page_alloc is not None:
        assert engine._page_alloc.free_pages == total_free_pages, (
            f"leaked pages: {engine._page_alloc.free_pages} free of "
            f"{total_free_pages}"
        )
