"""Messaging family: ``message_agent`` isolation, carry-forward, validation
(reference analogs: tests/test_message_agent.py,
test_messaging_carry_forwards.py, test_peers_surface.py)."""

import pytest

from calfkit_tpu.client import Client
from calfkit_tpu.engine import FunctionModelClient, TestModelClient
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models import ModelResponse, TextOutput, ToolCallOutput
from calfkit_tpu.models.agents import AgentCard
from calfkit_tpu.models.messages import ModelRequest, ToolReturnPart, UserPart
from calfkit_tpu.nodes import Agent
from calfkit_tpu.peers import Messaging
from calfkit_tpu.peers.messaging import MESSAGE_AGENT_TOOL
from calfkit_tpu.worker import Worker


def _message(cid: str, target: str, text: str) -> ToolCallOutput:
    return ToolCallOutput(
        tool_call_id=cid,
        tool_name=MESSAGE_AGENT_TOOL,
        args={"agent_name": target, "message": text},
    )


class TestSelector:
    CARDS = [
        AgentCard(name="a", description="A", input_topic="agent.a.private.input"),
        AgentCard(name="me", description="self", input_topic="agent.me.private.input"),
    ]

    def test_tool_def_has_message_and_target(self):
        tool = Messaging("a").tool_def(self.CARDS, self_name="me")
        props = tool.parameters_schema["properties"]
        assert props["agent_name"]["enum"] == ["a"]
        assert "message" in props
        assert tool.parameters_schema["required"] == ["agent_name", "message"]

    def test_curated_xor_discover(self):
        with pytest.raises(Exception):
            Messaging("a", discover=True)
        with pytest.raises(Exception):
            Messaging()


class TestMessagingEndToEnd:
    async def test_callee_sees_only_the_message_not_the_callers_history(self):
        callee_views = []

        def callee_model(messages, params):
            callee_views.append(messages)
            return ModelResponse(parts=[TextOutput(text="expert reply")])

        expert = Agent(
            "expert", model=FunctionModelClient(callee_model), description="e"
        )

        def caller_model(messages, params):
            if not any(isinstance(m, ModelResponse) for m in messages):
                return ModelResponse(
                    parts=[_message("m1", "expert", "just the question")]
                )
            returns = [
                p.content
                for m in messages
                if isinstance(m, ModelRequest)
                for p in m.parts
                if isinstance(p, ToolReturnPart)
            ]
            return ModelResponse(parts=[TextOutput(text=f"got: {returns[-1]}")])

        caller = Agent(
            "caller",
            model=FunctionModelClient(caller_model),
            peers=[Messaging("expert")],
        )
        mesh = InMemoryMesh()
        async with Worker([caller, expert], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("caller").execute(
                "SECRET caller context", timeout=15
            )
            assert "expert reply" in result.output
            await client.close()

        # the expert's view: exactly one user part, the message text —
        # never the caller's conversation (isolated state)
        assert len(callee_views) == 1
        texts = [
            p.content
            for m in callee_views[0]
            if isinstance(m, ModelRequest)
            for p in m.parts
            if isinstance(p, UserPart)
        ]
        joined = " ".join(str(t) for t in texts)
        assert "just the question" in joined
        assert "SECRET" not in joined

    async def test_caller_state_survives_the_exchange(self):
        """Carry-forward: after messaging, the caller's own history still
        contains its original user turn (state parked durably, not lost)."""
        expert = Agent(
            "expert2", model=TestModelClient(custom_output_text="ok"),
            description="e",
        )

        def caller_model(messages, params):
            if not any(isinstance(m, ModelResponse) for m in messages):
                return ModelResponse(parts=[_message("m1", "expert2", "q")])
            user_texts = [
                str(p.content)
                for m in messages
                if isinstance(m, ModelRequest)
                for p in m.parts
                if isinstance(p, UserPart)
            ]
            assert any("original prompt" in t for t in user_texts), user_texts
            return ModelResponse(parts=[TextOutput(text="done")])

        caller = Agent(
            "caller2",
            model=FunctionModelClient(caller_model),
            peers=[Messaging("expert2")],
        )
        mesh = InMemoryMesh()
        async with Worker([caller, expert], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("caller2").execute(
                "original prompt", timeout=15
            )
            assert result.output == "done"
            # the returned state carries the caller's conversation
            assert any(
                "original prompt" in str(getattr(p, "content", ""))
                for m in result.state.message_history
                if isinstance(m, ModelRequest)
                for p in m.parts
            )
            await client.close()

    async def test_message_to_dead_agent_returns_retry_to_model(self):
        turns = []

        def caller_model(messages, params):
            turns.append(1)
            if len(turns) == 1:
                return ModelResponse(parts=[_message("m1", "nobody", "hello?")])
            return ModelResponse(parts=[TextOutput(text="gave up gracefully")])

        caller = Agent(
            "caller3",
            model=FunctionModelClient(caller_model),
            peers=[Messaging(discover=True)],
        )
        mesh = InMemoryMesh()
        async with Worker([caller], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("caller3").execute("go", timeout=15)
            assert result.output == "gave up gracefully"
            assert len(turns) == 2
            await client.close()

    async def test_parallel_messages_fold_into_one_reentry(self):
        """Two message_agent calls in ONE turn: both replies present on the
        next model turn (durable fan-out fold)."""
        a = Agent("pa", model=TestModelClient(custom_output_text="alpha says"),
                  description="a")
        b = Agent("pb", model=TestModelClient(custom_output_text="beta says"),
                  description="b")

        def caller_model(messages, params):
            if not any(isinstance(m, ModelResponse) for m in messages):
                return ModelResponse(parts=[
                    _message("m1", "pa", "q1"), _message("m2", "pb", "q2"),
                ])
            returns = [
                str(p.content)
                for m in messages
                if isinstance(m, ModelRequest)
                for p in m.parts
                if isinstance(p, ToolReturnPart)
            ]
            assert len(returns) == 2, returns
            return ModelResponse(
                parts=[TextOutput(text=" | ".join(sorted(returns)))]
            )

        caller = Agent(
            "fanner", model=FunctionModelClient(caller_model),
            peers=[Messaging("pa", "pb")],
        )
        mesh = InMemoryMesh()
        async with Worker([caller, a, b], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("fanner").execute("go", timeout=20)
            assert "alpha says" in result.output and "beta says" in result.output
            await client.close()
