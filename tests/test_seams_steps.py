"""The seam-chain runner and the hop step ledger, in isolation.

Reference analogs: tests/test_seams.py, test_seam_registration.py,
test_step_ledger.py, test_step_pair_law.py, test_step_construction_sealing
(the single-mint rule) in /root/reference/tests/.
"""

import pytest

from calfkit_tpu.exceptions import NodeFaultError, SeamContractError
from calfkit_tpu.models.error_report import ErrorReport
from calfkit_tpu.nodes.steps import (
    DeniedCall,
    HandedOff,
    HopStepLedger,
    InferenceFact,
    Said,
    publish_step_message,
)
from calfkit_tpu.nodes.seams import (
    MintedFault,
    run_chain,
    run_chain_guarded,
    validate_seam_arity,
)


class TestSeamArity:
    def test_exact_arity_passes(self):
        validate_seam_arity(lambda ctx: None, 1, name="before_node")
        validate_seam_arity(lambda ctx, action: None, 2, name="after_node")

    def test_wrong_arity_rejected_loudly(self):
        with pytest.raises(SeamContractError, match="before_node"):
            validate_seam_arity(lambda ctx, extra: None, 1, name="before_node")
        with pytest.raises(SeamContractError, match="2 positional"):
            validate_seam_arity(lambda ctx: None, 2, name="after_node")

    def test_var_positional_accepted(self):
        validate_seam_arity(lambda *args: None, 2, name="on_node_error")

    def test_defaulted_params_do_not_count(self):
        # (ctx, report=None) has ONE required positional — valid for arity 1
        validate_seam_arity(
            lambda ctx, report=None: None, 1, name="before_node"
        )

    def test_uninspectable_callable_trusted(self):
        # min has no introspectable signature: arity check trusts the caller
        validate_seam_arity(min, 2, name="after_node")


class TestSeamChains:
    async def test_first_non_none_wins_in_registration_order(self):
        calls = []

        def a(x):
            calls.append("a")
            return None

        def b(x):
            calls.append("b")
            return "b-won"

        def c(x):
            calls.append("c")
            return "c-never"

        assert await run_chain([a, b, c], 1) == "b-won"
        assert calls == ["a", "b"]  # c never ran

    async def test_all_none_falls_through(self):
        assert await run_chain([lambda x: None, lambda x: None], 1) is None
        assert await run_chain([], 1) is None

    async def test_async_and_sync_seams_mix(self):
        async def slow(x):
            return x * 2

        assert await run_chain([lambda x: None, slow], 21) == 42

    async def test_guarded_chain_wraps_minted_fault(self):
        """A NodeFaultError raised in a seam is a deliberate typed-fault
        MINT, not a seam crash — the runner must carry it out tagged."""
        fault = NodeFaultError(
            ErrorReport.build_safe(error_type="calf.custom", message="deliberate")
        )

        def minting(ctx, report):
            raise fault

        with pytest.raises(MintedFault) as exc_info:
            await run_chain_guarded([minting], None, None)
        assert exc_info.value.error is fault

    async def test_guarded_chain_lets_crashes_escape_raw(self):
        def crashing(ctx, report):
            raise RuntimeError("oops")

        with pytest.raises(RuntimeError, match="oops"):
            await run_chain_guarded([crashing], None, None)

    async def test_guarded_chain_first_result_skips_minting_seam(self):
        def recovering(ctx, report):
            return "recovered"

        def minting(ctx, report):
            raise NodeFaultError(
                ErrorReport.build_safe(
                    error_type="calf.custom", message="never reached"
                )
            )

        result = await run_chain_guarded([recovering, minting], None, None)
        assert result == "recovered"


class TestHopStepLedger:
    def test_said_becomes_agent_message(self):
        ledger = HopStepLedger("agent/a")
        ledger.absorb([Said(text="hi", author="a")])
        msg = ledger.drain()
        assert [s.kind for s in msg.steps] == ["agent_message"]
        assert msg.steps[0].text == "hi"
        assert msg.emitter == "agent/a"

    def test_denied_call_is_born_closed_pair(self):
        """The pair law's degenerate case: a call rejected before dispatch
        emits its tool_call (denied) AND its tool_result (ok=False) in one
        hop — no dangling open pairs, ever."""
        ledger = HopStepLedger("agent/a")
        ledger.absorb(
            [DeniedCall(tool_call_id="t1", tool_name="f", reason="no such tool")]
        )
        msg = ledger.drain()
        kinds = [s.kind for s in msg.steps]
        assert kinds == ["tool_call", "tool_result"]
        assert msg.steps[0].denied is True
        assert msg.steps[1].ok is False
        assert msg.steps[0].tool_call_id == msg.steps[1].tool_call_id == "t1"

    def test_dispatch_and_fold_complete_the_pair(self):
        ledger = HopStepLedger("agent/a")
        ledger.note_dispatch("t9", "lookup", {"q": 1})
        ledger.folded("t9", "lookup", {"answer": 42})
        msg = ledger.drain()
        kinds = [s.kind for s in msg.steps]
        assert kinds == ["tool_call", "tool_result"]
        assert msg.steps[1].ok is True

    def test_fold_failed_closes_pair_with_report(self):
        ledger = HopStepLedger("agent/a")
        ledger.note_dispatch("t2", "boom", {})
        report = ErrorReport.build_safe(
            error_type="calf.tool.error", message="it broke"
        )
        ledger.fold_failed("t2", "boom", report)
        msg = ledger.drain()
        assert msg.steps[1].ok is False
        assert "it broke" in msg.steps[1].content

    def test_handoff_and_inference_and_token_kinds(self):
        ledger = HopStepLedger("agent/a")
        ledger.absorb(
            [
                HandedOff(to_agent="b", from_agent="a"),
                InferenceFact(model_name="m", generated_tokens=3),
            ]
        )
        ledger.token("hel", author="a")
        msg = ledger.drain()
        assert [s.kind for s in msg.steps] == ["handoff", "inference", "token"]

    def test_drain_is_idempotent(self):
        """Exactly-once flush per hop: the second drain yields nothing."""
        ledger = HopStepLedger("agent/a")
        ledger.absorb([Said(text="x")])
        assert ledger.drain() is not None
        assert ledger.drain() is None

    def test_empty_ledger_drains_none(self):
        assert HopStepLedger("agent/a").drain() is None
        assert not HopStepLedger("agent/a").has_steps

    def test_hostile_tool_content_is_contained(self):
        """A tool result whose __str__ raises must not break the ledger —
        the harvester's safe_str guard applies at the mint."""

        class Hostile:
            def __str__(self):
                raise RuntimeError("gotcha")

            def __repr__(self):
                raise RuntimeError("gotcha2")

        ledger = HopStepLedger("agent/a")
        ledger.note_dispatch("t3", "f", {})
        ledger.folded("t3", "f", Hostile())
        msg = ledger.drain()
        assert msg.steps[1].ok is True
        assert isinstance(msg.steps[1].content, str)  # contained, not raised

    def test_oversized_tool_content_truncated(self):
        ledger = HopStepLedger("agent/a")
        ledger.folded("t4", "f", "x" * 100_000)
        msg = ledger.drain()
        assert len(msg.steps[0].content) <= 2200  # budgeted, not unbounded

    async def test_flush_without_root_topic_is_noop(self):
        ledger = HopStepLedger("agent/a")
        ledger.absorb([Said(text="x")])
        await ledger.flush(
            transport=None, root_topic=None, correlation_id="c", task_id="t"
        )  # must not touch the (None) transport

    async def test_flush_publishes_once_with_identity_headers(self):
        published = []

        class FakeTransport:
            async def publish(self, topic, value, *, key=None, headers=None):
                published.append((topic, key, dict(headers or {})))

        ledger = HopStepLedger("agent/a")
        ledger.absorb([Said(text="x")])
        await ledger.flush(
            FakeTransport(), "caller.inbox", correlation_id="cid", task_id="tid"
        )
        await ledger.flush(  # second flush: already drained, no publish
            FakeTransport(), "caller.inbox", correlation_id="cid", task_id="tid"
        )
        assert len(published) == 1
        topic, key, headers = published[0]
        assert topic == "caller.inbox"


class TestSeamsEndToEnd:
    """Seam chains through a real delivery (mesh -> kernel -> seams)."""

    @staticmethod
    def _team(agent):
        from calfkit_tpu.client import Client
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.worker import Worker

        mesh = InMemoryMesh()
        return mesh, Worker([agent], mesh=mesh, owns_transport=True), Client

    async def test_before_node_short_circuits_the_body(self):
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.nodes import Agent

        def must_not_run(messages, params):
            raise AssertionError("body ran despite a short-circuiting seam")

        agent = Agent(
            "guarded",
            model=FunctionModelClient(must_not_run),
            before_node=[lambda ctx: "maintenance until 14:00"],
        )
        mesh, worker, Client = self._team(agent)
        async with worker:
            client = Client.connect(mesh)
            result = await client.agent("guarded").execute("hi", timeout=10)
            assert result.output == "maintenance until 14:00"
            await client.close()

    async def test_before_node_none_falls_through_to_body(self):
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.nodes import Agent

        seen = []

        def observe(ctx):
            seen.append(ctx.task_id)
            return None

        agent = Agent(
            "open",
            model=TestModelClient(custom_output_text="body answer"),
            before_node=[observe],
        )
        mesh, worker, Client = self._team(agent)
        async with worker:
            client = Client.connect(mesh)
            result = await client.agent("open").execute("hi", timeout=10)
            assert result.output == "body answer"
            assert len(seen) == 1
            await client.close()

    async def test_after_node_replaces_result_with_coerced_dict(self):
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.nodes import Agent

        def cap(ctx, action):
            return {"replaced": True}

        agent = Agent(
            "capped",
            model=TestModelClient(custom_output_text="raw"),
            after_node=[cap],
        )
        mesh, worker, Client = self._team(agent)
        async with worker:
            client = Client.connect(mesh)
            result = await client.agent("capped").execute("hi", timeout=10)
            # a DataPart renders as its JSON string under output_type=str
            assert "replaced" in result.output
            await client.close()

    async def test_seam_mutations_visible_to_later_stages(self):
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.nodes import Agent

        stamps = []

        def stamp(ctx):
            ctx.deps["stamped"] = "yes"

        def read(ctx, action):
            stamps.append(ctx.deps.get("stamped"))
            return None  # keep the body's action

        agent = Agent(
            "mutating",
            model=TestModelClient(custom_output_text="ok"),
            before_node=[stamp],
            after_node=[read],
        )
        mesh, worker, Client = self._team(agent)
        async with worker:
            client = Client.connect(mesh)
            result = await client.agent("mutating").execute("hi", timeout=10)
            assert result.output == "ok"
            assert stamps == ["yes"]
            await client.close()

    async def test_unpublishable_seam_return_faults_loudly(self):
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.exceptions import NodeFaultError
        from calfkit_tpu.nodes import Agent

        import pytest

        # the classic accident: an observe-only seam ending in a truthy
        # expression (setdefault returns the value)
        agent = Agent(
            "accident",
            model=TestModelClient(custom_output_text="never"),
            before_node=[lambda ctx: ctx.deps.setdefault("attempts", 3)],
        )
        mesh, worker, Client = self._team(agent)
        async with worker:
            client = Client.connect(mesh)
            with pytest.raises(NodeFaultError, match="unpublishable"):
                await client.agent("accident").execute("hi", timeout=10)
            await client.close()


class TestStepPairLawEndToEnd:
    """The pair law observed at the CLIENT: every tool_call step that
    streams out is closed by exactly one tool_result step — including the
    failing call (closed ok=False) — before the terminal event."""

    async def test_pairs_close_for_success_and_failure(self):
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.exceptions import NodeFaultError
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.models import ModelResponse, TextOutput, ToolCallOutput
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool
        def fine(x: int) -> int:
            """F.

            Args:
                x: X.
            """
            return x * 2

        @agent_tool
        def broken(x: int) -> int:
            """B.

            Args:
                x: X.
            """
            raise RuntimeError("tool died")

        def model(messages, params):
            from calfkit_tpu.models.messages import ModelRequest, ToolReturnPart

            replied = any(
                isinstance(p, ToolReturnPart)
                for m in messages
                if isinstance(m, ModelRequest)
                for p in m.parts
            )
            if not replied:
                return ModelResponse(parts=[
                    ToolCallOutput(tool_call_id="ok1", tool_name="fine",
                                   args={"x": 2}),
                    ToolCallOutput(tool_call_id="bad1", tool_name="broken",
                                   args={"x": 1}),
                ])
            return ModelResponse(parts=[TextOutput(text="survived")])

        def absorb(tool_call, ctx, report):
            return "substituted"  # recover the broken sibling

        agent = Agent(
            "paired", model=FunctionModelClient(model),
            tools=[fine, broken], on_tool_error=absorb,
        )
        mesh = InMemoryMesh()
        opened: dict[str, str] = {}
        closed: dict[str, bool] = {}
        async with Worker([agent, fine, broken], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("paired").start("go", timeout=20)
            async for event in handle.stream():
                step = getattr(event, "step", None)
                if step is None:
                    assert event.output == "survived"
                elif step.kind == "tool_call":
                    opened[step.tool_call_id] = step.tool_name
                elif step.kind == "tool_result":
                    assert step.tool_call_id in opened, "result before call"
                    closed[step.tool_call_id] = step.ok
            await client.close()
        assert set(opened) == set(closed) == {"ok1", "bad1"}
        assert closed["ok1"] is True
        assert closed["bad1"] is False  # failure closes the pair, ok=False

    async def test_firehose_sees_steps_across_runs(self):
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        agent = Agent("hose", model=TestModelClient(custom_output_text="y"))
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            stream = client.events()
            r1 = await client.agent("hose").execute("one", timeout=10)
            r2 = await client.agent("hose").execute("two", timeout=10)
            stream.close()
            cids = set()
            async for event in stream:
                cids.add(event.correlation_id)
            assert {r1.correlation_id, r2.correlation_id} <= cids
            await client.close()
