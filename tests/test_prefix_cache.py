"""Automatic prefix caching (r5): paged-KV page reuse across requests
sharing a prompt prefix — the agent-serving win (instructions + history
re-sent every turn re-prefill nothing but the new tail).

Pinned here:
- exact token parity: a reusing request generates the SAME tokens as a
  fresh engine (the reused pages hold bit-identical K/V),
- reuse actually happens (stats) and only at page+chunk alignment,
- divergent suffixes after a shared prefix stay independent,
- page accounting: no leaks across admission/retire/eviction; shared
  pages never return to the free list while readers hold them,
- eviction reclaims idle cache pages when admission runs dry,
- the cache itself (unit): chain hashing, LRU eviction, ownership.
"""

from __future__ import annotations

import asyncio

import pytest

from calfkit_tpu.inference.config import RuntimeConfig, preset
from calfkit_tpu.inference.engine import InferenceEngine
from calfkit_tpu.inference.paged import (
    PageAllocator,
    PrefixCache,
    chain_hashes,
)

CFG = preset("debug")


def _runtime(**overrides) -> RuntimeConfig:
    base = dict(
        max_batch_size=4, max_seq_len=128, prefill_chunk=16,
        decode_steps_per_dispatch=4, kv_layout="paged", page_size=16,
        num_kv_pages=64, chunked_prefill=True, prefix_cache=True,
    )
    base.update(overrides)
    return RuntimeConfig(**base)


async def _generate(engine, prompt, n=8):
    return [t async for t in engine.generate(prompt, max_new_tokens=n)]


class TestChainHashes:
    def test_position_dependence(self):
        # equal page content after different prefixes must not alias
        a = chain_hashes([1] * 32, 16)
        b = chain_hashes([2] * 16 + [1] * 16, 16)
        assert a[1] != b[1]
        assert len(a) == 2

    def test_partial_page_excluded(self):
        assert len(chain_hashes([1] * 31, 16)) == 1


class TestPrefixCacheUnit:
    def test_register_acquire_release_evict(self):
        alloc = PageAllocator(8)
        cache = PrefixCache()
        pages = alloc.alloc(0, 3)
        hashes = chain_hashes([5] * 48, 16)
        for h, p in zip(hashes, pages):
            assert cache.register(h, p)
        alloc.transfer_out(0, pages)
        cache.acquire(pages)
        alloc.free(0)  # slot frees nothing: ownership transferred
        assert alloc.free_pages == 8 - 1 - 3
        assert cache.lookup(hashes) == pages
        # held pages are not evictable
        assert cache.evict(3, alloc) == 0
        cache.release(pages)
        assert cache.evict(2, alloc) == 2
        assert alloc.free_pages == 8 - 1 - 1
        # evicting the chain head strands the tail for lookup
        assert cache.lookup(hashes) == []

    def test_duplicate_register_refused(self):
        cache = PrefixCache()
        h = chain_hashes([1] * 16, 16)[0]
        assert cache.register(h, 3)
        assert not cache.register(h, 4)
        assert cache.lookup([h]) == [3]


class TestEngineReuse:
    def test_token_parity_and_reuse(self):
        """Same prompt twice: second admission reuses pages and yields
        IDENTICAL tokens; a fresh engine agrees."""

        async def run() -> None:
            prompt = [(7 * i + 3) % CFG.vocab_size for i in range(50)]
            engine = InferenceEngine(CFG, _runtime(), seed=5)
            await engine.start()
            first = await _generate(engine, prompt)
            assert engine.stats.prefix_hits == 0
            second = await _generate(engine, prompt)
            assert second == first
            assert engine.stats.prefix_hits == 1
            # alignment: lcm(page=16, chunk=16)=16; cap at min(48, 49, 48)
            assert engine.stats.prefix_reused_tokens == 48
            await engine.stop()

            fresh = InferenceEngine(CFG, _runtime(), seed=5)
            await fresh.start()
            control = await _generate(fresh, prompt)
            await fresh.stop()
            assert control == first

        asyncio.run(run())

    def test_divergent_suffix_after_shared_prefix(self):
        """Two prompts sharing 2 pages then diverging: the shared pages
        are reused, and each result matches its own fresh-engine run."""

        async def run() -> None:
            shared = [(11 * i + 5) % CFG.vocab_size for i in range(32)]
            a = shared + [9, 9, 9, 9, 9, 9, 9, 9, 9, 9, 9, 9, 9, 9, 9, 9, 1]
            b = shared + [4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 2]
            engine = InferenceEngine(CFG, _runtime(), seed=9)
            await engine.start()
            got_a = await _generate(engine, a)
            got_b = await _generate(engine, b)
            assert engine.stats.prefix_hits == 1  # b reused a's prefix
            assert engine.stats.prefix_reused_tokens == 32
            await engine.stop()

            for prompt, got in ((a, got_a), (b, got_b)):
                fresh = InferenceEngine(CFG, _runtime(), seed=9)
                await fresh.start()
                assert await _generate(fresh, prompt) == got
                await fresh.stop()

        asyncio.run(run())

    def test_shared_scaffold_page_does_not_block_registration(self):
        """ISSUE 7 regression: N sessions sharing ONE scaffold page (an
        agent fleet's system preamble) then diverging, with a chunk too
        large for the single shared page to be reused (alignment forces
        reuse=0).  Registration used to STOP at the first already-cached
        chain position, so only the first session's chain ever entered
        the cache and every other session re-prefilled forever; now the
        collision page stays slot-private while the divergent suffix
        registers, and each session's REPEAT prompt hits."""

        async def run() -> None:
            scaffold = [(7 * i + 2) % CFG.vocab_size for i in range(16)]
            sessions = [
                scaffold + [(13 * i + offset) % CFG.vocab_size
                            for i in range(33)]
                for offset in (3, 5, 11)
            ]
            # chunk 32 > the 16-token shared page: lcm alignment makes
            # the scaffold-only match unreusable (reuse=0), which is the
            # exact shape that used to break registration
            engine = InferenceEngine(
                CFG, _runtime(prefill_chunk=32), seed=11
            )
            await engine.start()
            firsts = [await _generate(engine, p) for p in sessions]
            assert engine.stats.prefix_hits == 0  # nothing alignable yet
            repeats = [await _generate(engine, p) for p in sessions]
            # EVERY session's repeat reuses its own registered chain —
            # not just the first session's
            assert engine.stats.prefix_hits == len(sessions)
            assert repeats == firsts
            await engine.stop()

            # parity: a STITCHED chain (scaffold page from session 0's
            # registration + own divergent suffix) must be content-exact
            # — one fresh engine on the last session pins it (the other
            # sessions share the identical code path)
            fresh = InferenceEngine(CFG, _runtime(prefill_chunk=32), seed=11)
            await fresh.start()
            assert await _generate(fresh, sessions[-1]) == firsts[-1]
            await fresh.stop()

        asyncio.run(run())

    def test_no_page_leaks_across_reuse_and_retire(self):
        async def run() -> None:
            engine = InferenceEngine(CFG, _runtime(), seed=3)
            await engine.start()
            prompt = [(3 * i + 1) % CFG.vocab_size for i in range(40)]
            for _ in range(4):
                await _generate(engine, prompt, n=4)
            alloc = engine._page_alloc
            cache = engine._prefix
            # every page is either free or cache-held; nothing vanished
            assert alloc.free_pages + cache.size == 64 - 1
            assert not alloc.held_slots
            # draining the cache returns the pool to full
            cache.evict(cache.size, alloc)
            assert alloc.free_pages == 64 - 1
            await engine.stop()

        asyncio.run(run())

    def test_eviction_reclaims_idle_cache_under_pressure(self):
        """A tiny pool: cached pages from request 1 must be evicted to
        admit request 2's different prompt — loudly accounted, no
        deadlock."""

        async def run() -> None:
            engine = InferenceEngine(
                CFG, _runtime(num_kv_pages=13, max_batch_size=2), seed=7
            )
            await engine.start()
            p1 = [(5 * i + 2) % CFG.vocab_size for i in range(40)]
            p2 = [(7 * i + 3) % CFG.vocab_size for i in range(40)]
            out1 = await _generate(engine, p1, n=4)
            assert engine._prefix.size > 0
            out2 = await _generate(engine, p2, n=4)
            assert out1 and out2
            await engine.stop()

        asyncio.run(run())

    def test_concurrent_same_prompt_burst(self):
        """A burst of identical prompts (the 128-agent shape in
        miniature): all complete, all agree, pool balances."""

        async def run() -> None:
            engine = InferenceEngine(CFG, _runtime(), seed=11)
            await engine.start()
            prompt = [(13 * i + 7) % CFG.vocab_size for i in range(40)]
            results = await asyncio.gather(
                *[_generate(engine, prompt, n=5) for _ in range(6)]
            )
            assert all(r == results[0] for r in results)
            alloc, cache = engine._page_alloc, engine._prefix
            assert alloc.free_pages + cache.size == 64 - 1
            assert not alloc.held_slots
            await engine.stop()

        asyncio.run(run())

    def test_reusing_burst_batches_into_one_wave(self):
        """Once the prefix is cached, a burst of reusing requests must
        BATCH (review finding: the singleton restriction would serialize
        the feature's own headline workload)."""

        async def run() -> None:
            engine = InferenceEngine(CFG, _runtime(), seed=13)
            await engine.start()
            prompt = [(17 * i + 5) % CFG.vocab_size for i in range(40)]
            await _generate(engine, prompt, n=3)  # populate the cache
            results = await asyncio.gather(
                *[_generate(engine, prompt, n=3) for _ in range(4)]
            )
            assert all(r == results[0] for r in results)
            assert engine.stats.prefix_hits == 4
            assert engine.stats.prefix_reused_tokens == 4 * 32
            await engine.stop()

        asyncio.run(run())

    def test_trimmed_reusing_request_releases_its_acquisition(self):
        """A reusing request trimmed out of a wave (power-of-two keep)
        must release its formation-time page acquisition — a leaked
        refcount would make those pages unevictable forever (review
        finding r5)."""

        async def run() -> None:
            engine = InferenceEngine(CFG, _runtime(), seed=17)
            await engine.start()
            prompt = [(23 * i + 11) % CFG.vocab_size for i in range(40)]
            await _generate(engine, prompt, n=3)  # populate
            # 3 concurrent reusers: wave forms as 3, trims to 2, carries 1
            # (which re-plans and serves next pass)
            results = await asyncio.gather(
                *[_generate(engine, prompt, n=3) for _ in range(3)]
            )
            assert all(r == results[0] for r in results)
            alloc, cache = engine._page_alloc, engine._prefix
            assert alloc.free_pages + cache.size == 64 - 1
            assert not alloc.held_slots
            # nothing holds references anymore: the WHOLE cache drains
            assert cache.evict(cache.size, alloc) >= 1
            assert alloc.free_pages == 64 - 1
            await engine.stop()

        asyncio.run(run())

    @pytest.mark.parametrize("quantization", [None, "int4"])
    def test_reuse_on_tp_sharded_mesh(self, quantization):
        """Shared pages under GSPMD: the seed gather runs over a pool
        sharded on the KV-head axis (tp=2), with token parity — both
        plain and composed with int4 weights (the full opt-in stack)."""

        async def run() -> None:
            from calfkit_tpu.inference.sharding import make_mesh

            engine = InferenceEngine(
                CFG, _runtime(tp=2, dp=1, quantization=quantization),
                mesh=make_mesh(tp=2, dp=1), seed=19,
            )
            await engine.start()
            prompt = [(29 * i + 13) % CFG.vocab_size for i in range(50)]
            first = await _generate(engine, prompt, n=6)
            second = await _generate(engine, prompt, n=6)
            assert second == first
            assert engine.stats.prefix_hits == 1
            assert engine.stats.prefix_reused_tokens == 48
            alloc, cache = engine._page_alloc, engine._prefix
            assert alloc.free_pages + cache.size == 64 - 1
            await engine.stop()

        asyncio.run(run())

    def test_prefix_cache_requires_paged_and_chunked(self):
        with pytest.raises(ValueError, match="paged"):
            InferenceEngine(CFG, _runtime(kv_layout="dense"))
        with pytest.raises(ValueError, match="chunked"):
            InferenceEngine(CFG, _runtime(chunked_prefill=False))


class TestAgentServingReuse:
    def test_repeat_agent_runs_reuse_instruction_prefix(self):
        """The product story: two runs of the same agent re-send the same
        rendered instructions+prompt; with prefix_cache on, the second
        run's prefill reuses the first's pages — measured end-to-end
        through client -> mesh -> agent -> engine."""

        async def run() -> None:
            from calfkit_tpu import Agent, Client, InMemoryMesh, Worker
            from calfkit_tpu.inference.client import JaxLocalModelClient

            engine = InferenceEngine(
                CFG,
                _runtime(max_seq_len=512, num_kv_pages=160, max_batch_size=2),
                seed=21,
            )
            model = JaxLocalModelClient(engine=engine, max_new_tokens=4)
            agent = Agent(
                name="cached",
                model=model,
                instructions=(
                    "You are a terse assistant for the prefix-cache test. "
                    "Answer with the shortest possible reply every time. "
                    "This instruction block is deliberately long enough to "
                    "span several KV pages so reuse is measurable."
                ),
            )
            mesh = InMemoryMesh()
            async with Worker([agent], mesh=mesh):
                client = Client.connect(mesh)
                await client.agent("cached").execute("hello there", timeout=60)
                assert engine.stats.prefix_reused_tokens == 0
                await client.agent("cached").execute("hello there", timeout=60)
                assert engine.stats.prefix_hits >= 1
                assert engine.stats.prefix_reused_tokens > 0
                await client.close()
            await engine.stop()

        asyncio.run(run())


class TestMultiTenantSharedEngine:
    def test_two_agents_share_one_engine_with_distinct_prefixes(self):
        """Multi-tenant serving: two agents ride ONE model client/engine;
        each agent's instruction prefix caches independently (chained
        hashes keep them distinct) and both keep serving concurrently."""

        async def run() -> None:
            from calfkit_tpu import Agent, Client, InMemoryMesh, Worker
            from calfkit_tpu.inference.client import JaxLocalModelClient

            engine = InferenceEngine(
                CFG,
                _runtime(max_seq_len=512, num_kv_pages=200, max_batch_size=4),
                seed=29,
            )
            model = JaxLocalModelClient(engine=engine, max_new_tokens=4)
            pad = "This block spans multiple KV pages for reuse. " * 3
            alpha = Agent(name="alpha", model=model,
                          instructions="You are agent ALPHA. " + pad)
            beta = Agent(name="beta", model=model,
                         instructions="You are agent BETA.  " + pad)
            mesh = InMemoryMesh()
            async with Worker([alpha, beta], mesh=mesh):
                client = Client.connect(mesh)
                for _ in range(2):  # second round reuses BOTH prefixes
                    await asyncio.gather(
                        client.agent("alpha").execute("go", timeout=120),
                        client.agent("beta").execute("go", timeout=120),
                    )
                assert engine.stats.prefix_hits >= 2
                assert engine.stats.prefix_reused_tokens > 0
                await client.close()
            alloc, cache = engine._page_alloc, engine._prefix
            assert alloc.free_pages + cache.size == 200 - 1
            assert not alloc.held_slots
            await engine.stop()

        asyncio.run(run())

