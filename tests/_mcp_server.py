"""A tiny in-repo stdio MCP server for round-trip tests (the analog of the
reference's tests/integration/_mcp_roundtrip_server.py): newline-delimited
JSON-RPC with two tools."""

import json
import sys

TOOLS = [
    {
        "name": "add",
        "description": "Add two integers.",
        "inputSchema": {
            "type": "object",
            "properties": {"a": {"type": "integer"}, "b": {"type": "integer"}},
            "required": ["a", "b"],
        },
    },
    {
        "name": "shout",
        "description": "Uppercase a string.",
        "inputSchema": {
            "type": "object",
            "properties": {"text": {"type": "string"}},
            "required": ["text"],
        },
    },
]


def reply(rpc_id, result):
    sys.stdout.write(json.dumps({"jsonrpc": "2.0", "id": rpc_id, "result": result}) + "\n")
    sys.stdout.flush()


def main() -> None:
    for line in sys.stdin:
        try:
            message = json.loads(line)
        except ValueError:
            continue
        method = message.get("method")
        rpc_id = message.get("id")
        if method == "initialize":
            reply(rpc_id, {
                "protocolVersion": message["params"]["protocolVersion"],
                "capabilities": {"tools": {"listChanged": True}},
                "serverInfo": {"name": "test-mcp", "version": "0"},
            })
        elif method == "tools/list":
            reply(rpc_id, {"tools": TOOLS})
        elif method == "tools/call":
            name = message["params"]["name"]
            args = message["params"].get("arguments", {})
            if name == "add":
                text = str(args["a"] + args["b"])
            elif name == "shout":
                text = str(args["text"]).upper()
            else:
                sys.stdout.write(json.dumps({
                    "jsonrpc": "2.0", "id": rpc_id,
                    "error": {"code": -32601, "message": f"no tool {name}"},
                }) + "\n")
                sys.stdout.flush()
                continue
            reply(rpc_id, {"content": [{"type": "text", "text": text}]})
        elif rpc_id is not None:
            reply(rpc_id, {})


if __name__ == "__main__":
    main()
