"""A tiny in-repo stdio MCP server for round-trip tests (the analog of the
reference's tests/integration/_mcp_roundtrip_server.py): newline-delimited
JSON-RPC with three static tools (grow/add/shout); calling ``grow`` adds a
fourth (``extra_shout``) and emits notifications/tools/list_changed — the
per-connection mutable list exercises the toolbox relist path."""

import json
import sys

TOOLS = [
    {
        "name": "grow",
        "description": "Add a new tool to this server (emits list_changed).",
        "inputSchema": {"type": "object", "properties": {}},
    },
    {
        "name": "add",
        "description": "Add two integers.",
        "inputSchema": {
            "type": "object",
            "properties": {"a": {"type": "integer"}, "b": {"type": "integer"}},
            "required": ["a", "b"],
        },
    },
    {
        "name": "shout",
        "description": "Uppercase a string.",
        "inputSchema": {
            "type": "object",
            "properties": {"text": {"type": "string"}},
            "required": ["text"],
        },
    },
]


def reply(rpc_id, result):
    sys.stdout.write(json.dumps({"jsonrpc": "2.0", "id": rpc_id, "result": result}) + "\n")
    sys.stdout.flush()


GROWN = [
    {
        "name": "extra_shout",
        "description": "Uppercase twice.",
        "inputSchema": {
            "type": "object",
            "properties": {"text": {"type": "string"}},
            "required": ["text"],
        },
    },
]


def notify(method):
    sys.stdout.write(json.dumps({"jsonrpc": "2.0", "method": method}) + "\n")
    sys.stdout.flush()


def main() -> None:
    tools = list(TOOLS)
    for line in sys.stdin:
        try:
            message = json.loads(line)
        except ValueError:
            continue
        method = message.get("method")
        rpc_id = message.get("id")
        if method == "initialize":
            reply(rpc_id, {
                "protocolVersion": message["params"]["protocolVersion"],
                "capabilities": {"tools": {"listChanged": True}},
                "serverInfo": {"name": "test-mcp", "version": "0"},
            })
        elif method == "tools/list":
            reply(rpc_id, {"tools": tools})
        elif method == "tools/call":
            name = message["params"]["name"]
            args = message["params"].get("arguments", {})
            if name == "add":
                text = str(args["a"] + args["b"])
            elif name == "shout":
                text = str(args["text"]).upper()
            elif name == "grow":
                # mutate the tool list + emit the list_changed notification
                tools = TOOLS + GROWN
                reply(rpc_id, {"content": [{"type": "text", "text": "grown"}]})
                notify("notifications/tools/list_changed")
                continue
            elif name == "extra_shout" and any(
                t["name"] == "extra_shout" for t in tools
            ):
                text = str(args["text"]).upper() * 2
            else:
                sys.stdout.write(json.dumps({
                    "jsonrpc": "2.0", "id": rpc_id,
                    "error": {"code": -32601, "message": f"no tool {name}"},
                }) + "\n")
                sys.stdout.flush()
                continue
            reply(rpc_id, {"content": [{"type": "text", "text": text}]})
        elif rpc_id is not None:
            reply(rpc_id, {})


if __name__ == "__main__":
    main()
