"""Agent-node construction and per-turn resolution corners.

Reference analogs: tests/test_tools_selector.py, test_tool_selector.py,
test_discover_kernel.py, test_agent_ctor_identity.py and the instructions
checklist entry in SURVEY §7.
"""

import pytest

from calfkit_tpu.client import Client
from calfkit_tpu.engine import EchoModelClient, FunctionModelClient, TestModelClient
from calfkit_tpu.exceptions import LifecycleConfigError
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models import ModelResponse, TextOutput
from calfkit_tpu.models.capability import CapabilityRecord, ToolDef
from calfkit_tpu.nodes import Agent, StatelessAgent, agent_tool
from calfkit_tpu.nodes.tool import Tools, eager_tools
from calfkit_tpu.peers import Handoff, Messaging
from calfkit_tpu.worker import Worker


def _record(node_id: str, *tool_names: str) -> CapabilityRecord:
    return CapabilityRecord(
        node_id=node_id,
        dispatch_topic=f"tool.{node_id}.input",
        tools=[ToolDef(name=n) for n in tool_names],
    )


class TestToolsSelector:
    def test_named_resolution(self):
        records = [_record("a", "lookup"), _record("b", "convert")]
        bindings = Tools("convert").resolve(records)
        assert [b.tool.name for b in bindings] == ["convert"]
        assert bindings[0].dispatch_topic == "tool.b.input"

    def test_discover_resolves_all_minus_excluded(self):
        records = [_record("a", "lookup"), _record("b", "convert", "scale")]
        names = {b.tool.name for b in Tools(discover=True, exclude=["scale"]).resolve(records)}
        assert names == {"lookup", "convert"}

    def test_missing_named_tool_is_loud(self):
        from calfkit_tpu.models.capability import CapabilityResolutionError

        with pytest.raises(CapabilityResolutionError, match="absent"):
            Tools("absent").resolve([_record("a", "lookup")])

    def test_names_xor_discover_enforced(self):
        with pytest.raises(ValueError, match="not both"):
            Tools("x", discover=True)
        with pytest.raises(ValueError, match="requires names"):
            Tools()  # neither names nor discover

    def test_eager_tools_bind_to_input_topics(self):
        @agent_tool
        def greet(name: str) -> str:
            """Say hello."""
            return f"hi {name}"

        bindings = eager_tools(greet)
        assert bindings[0].tool.name == "greet"
        assert bindings[0].dispatch_topic == "tool.greet.input"


class TestConstruction:
    def test_duplicate_peer_kinds_rejected(self):
        with pytest.raises(LifecycleConfigError, match="one peer selector"):
            Agent(
                "a",
                model=EchoModelClient(),
                peers=[Messaging("x"), Messaging("y")],
            )

    def test_mixed_peer_kinds_accepted(self):
        agent = Agent(
            "a",
            model=EchoModelClient(),
            peers=[Messaging("x"), Handoff("y")],
        )
        assert len(agent.peers) == 2

    def test_stateless_agent_is_an_agent(self):
        agent = StatelessAgent("s", model=EchoModelClient())
        assert isinstance(agent, Agent)
        assert agent.kind == "agent"


def _instruction_probe():
    """(seen, model): a scripted model that records every instructions
    string the agent put on the request."""
    seen: list = []

    def scripted(messages, params):
        seen.extend(
            m.instructions for m in messages
            if getattr(m, "instructions", None)
        )
        return ModelResponse(parts=[TextOutput(text="ok")])

    return seen, FunctionModelClient(scripted)


class TestInstructions:
    async def _run(self, agent, prompt="hi"):
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent(agent.name).execute(prompt, timeout=30)
            await client.close()
        return result

    async def test_static_instructions_reach_the_model(self):
        seen, model = _instruction_probe()
        agent = Agent("ins", model=model, instructions="Be terse.")
        await self._run(agent)
        assert seen == ["Be terse."]

    async def test_callable_instructions_render_per_turn(self):
        seen, model = _instruction_probe()
        agent = Agent("dyn", model=model)

        @agent.instructions_fn
        def render(ctx):
            return f"You serve task {ctx.task_id[:4]}."

        await self._run(agent)
        assert len(seen) == 1 and seen[0].startswith("You serve task ")

    async def test_temp_instructions_appended(self):
        seen, model = _instruction_probe()

        def stamp_temp(ctx):
            # mid-run code (seams/tools) sets temp_instructions on the wire
            # state; the next render must append it to the base
            ctx.state.temp_instructions = "Today only: be verbose."

        agent = Agent(
            "tmp", model=model, instructions="Base.",
            before_node=[stamp_temp],
        )
        await self._run(agent)
        assert seen == ["Base.\n\nToday only: be verbose."]


class TestReservedNames:
    async def test_reserved_tool_name_faults(self):
        """A user tool named final_result collides with the structured-
        output tool — the turn must fault loudly, not shadow it."""

        @agent_tool(name="final_result")
        def impostor(x: int) -> int:
            return x

        from pydantic import BaseModel

        class Out(BaseModel):
            ok: bool

        agent = Agent(
            "guard", model=TestModelClient(), tools=[impostor],
            output_type=Out,
        )
        mesh = InMemoryMesh()
        from calfkit_tpu.exceptions import NodeFaultError

        async with Worker([agent, impostor], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            with pytest.raises(NodeFaultError):
                await client.agent("guard").execute("go", timeout=30)
            await client.close()
