"""Provisioning: topic derivation + classification corners NOT covered by
tests/test_connection_hardening.py (which owns the retry-ladder and
batch-exists suites — keep provisioner behavior pinned in ONE place each).

Reference analogs: tests/test_provisioning.py, test_startup_provisioning.py.
"""

from calfkit_tpu import protocol
from calfkit_tpu.engine import EchoModelClient
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.nodes import Agent, agent_tool
from calfkit_tpu.provisioning import (
    ProvisioningConfig,
    classify_topic_error,
    framework_topics_for_nodes,
    provision,
    topics_for_nodes,
)


class TestClassificationOrdering:
    def test_unauthorized_beats_retry_markers(self):
        """'authorization failed after connection attempt' must classify
        unauthorized, not retry — ACL markers are checked before
        retriable markers (an unauthorized cluster must not look flaky)."""

        class KafkaError(Exception):
            pass

        exc = KafkaError("topic authorization failed on connection")
        assert classify_topic_error(exc) == "unauthorized"

    def test_unauthorized_beats_existing_markers(self):
        class KafkaError(Exception):
            pass

        exc = KafkaError("already exists check denied: aclauthorization")
        assert classify_topic_error(exc) == "unauthorized"


class TestTopicDerivation:
    def _nodes(self):
        @agent_tool
        def lookup(q: str) -> str:
            """Find things."""
            return q

        return [Agent("helper", model=EchoModelClient()), lookup]

    def test_node_topics_cover_inputs_returns_publish(self):
        topics = topics_for_nodes(self._nodes())
        assert protocol.agent_input_topic("helper") in topics
        assert protocol.agent_return_topic("helper") in topics
        assert protocol.tool_input_topic("lookup") in topics
        assert topics == sorted(set(topics))  # deterministic + deduped

    def test_framework_topics_cover_controlplane_and_fanout(self):
        nodes = self._nodes()
        topics = framework_topics_for_nodes(nodes)
        assert protocol.AGENTS_TOPIC in topics
        assert protocol.CAPABILITIES_TOPIC in topics
        assert protocol.fanout_state_topic(nodes[0].node_id) in topics
        assert protocol.fanout_basestate_topic(nodes[0].node_id) in topics


class TestProvisionSurface:
    async def test_disabled_provisions_nothing(self):
        calls = []

        class Spy(InMemoryMesh):
            async def ensure_topics(self, names, *, compacted=False):
                calls.append(list(names))

        result = await provision(
            Spy(), [Agent("p", model=EchoModelClient())],
            ProvisioningConfig(enabled=False),
        )
        assert result == {"plain": [], "compacted": []}
        assert calls == []

    async def test_include_framework_false_skips_compacted(self):
        mesh = InMemoryMesh()
        await mesh.start()
        result = await provision(
            mesh, [Agent("p", model=EchoModelClient())],
            ProvisioningConfig(include_framework=False),
        )
        assert result["plain"] and result["compacted"] == []
        await mesh.stop()

    async def test_disabled_suppresses_all_admin_round_trips(self):
        """enabled=False means NO ensure_topics from anywhere in worker
        boot — not just the provisioner: the fan-out store and control
        plane must not sneak their own ensure past the operator's choice
        (ADVICE r2: pre-created topics on an ACL-restricted cluster)."""
        from calfkit_tpu.worker import Worker

        calls = []

        class Spy(InMemoryMesh):
            async def ensure_topics(self, names, *, compacted=False):
                calls.append(list(names))
                await super().ensure_topics(names, compacted=compacted)

        mesh = Spy()
        agent = Agent("quiet", model=EchoModelClient())
        async with Worker(
            [agent], mesh=mesh, owns_transport=True,
            provisioning=ProvisioningConfig(enabled=False),
        ):
            pass
        assert calls == []
