"""Transport contract suite: semantics EVERY mesh implementation must pass.

Reference anchor: the reference validates transport semantics against a real
broker (tests/integration/test_key_ordered_kafka.py and friends); here the
same contract is parameterized over all in-repo transports so `kafka.py`
is specified behavior, not dead code (VERDICT r1 item 5).

Transports:
- ``memory`` — InMemoryMesh (always runs)
- ``tcp`` — TcpMesh against a spawned native meshd broker (skips if the C++
  broker isn't built)
- ``kafka-wire`` — KafkaWireMesh (the native wire-protocol client) against
  a spawned in-repo ``kafkad`` broker: the REAL Kafka wire format
  (RecordBatch v2, consumer groups, offset commits) running in-image with
  zero external dependencies (VERDICT r3 item 4).  The aiokafka adapter
  and its self-certified in-process fake were removed in r5 (VERDICT r4
  item 3) — every shipped transport below has an executable lane.
"""

from __future__ import annotations

import asyncio
import uuid

import pytest

TRANSPORTS = ["memory", "tcp", "kafka-wire"]


@pytest.fixture(scope="module")
def meshd_broker():
    from calfkit_tpu.mesh.tcp import find_meshd, spawn_meshd

    if find_meshd() is None:
        yield None
        return
    proc = spawn_meshd(19876)
    yield proc
    proc.terminate()
    proc.wait(timeout=5)


@pytest.fixture(scope="module")
def kafkad_broker():
    from calfkit_tpu.mesh.kafka_wire import find_kafkad, spawn_kafkad

    if find_kafkad() is None:
        yield None
        return
    proc = spawn_kafkad(0)
    yield proc.kafkad_port
    proc.terminate()
    proc.wait(timeout=5)


@pytest.fixture(params=TRANSPORTS)
def transport(request, meshd_broker):
    """An async mesh factory + the transport's name; skips the unavailable."""
    kind = request.param
    made = []

    if kind == "tcp":
        from calfkit_tpu.mesh.tcp import find_meshd

        if find_meshd() is None:
            pytest.skip("meshd not built (make -C native)")
    kafkad_port = None
    if kind == "kafka-wire":
        from calfkit_tpu.mesh.kafka_wire import find_kafkad

        if find_kafkad() is None:
            pytest.skip("kafkad not built (make -C native)")
        kafkad_port = request.getfixturevalue("kafkad_broker")

    async def make():
        if kind == "memory":
            from calfkit_tpu.mesh import InMemoryMesh

            # one in-process broker world: repeated make() calls model
            # additional CONNECTIONS, not additional brokers
            if made:
                return made[0]
            mesh = InMemoryMesh()
        elif kind == "tcp":
            from calfkit_tpu.mesh.tcp import TcpMesh

            mesh = TcpMesh("127.0.0.1:19876")
        else:
            from calfkit_tpu.mesh.kafka_wire import KafkaWireMesh

            mesh = KafkaWireMesh(f"127.0.0.1:{kafkad_port}")
        await mesh.start()
        made.append(mesh)
        return mesh

    # leaked meshes (e.g. an assertion before mesh.stop()) are stopped on
    # the test's own loop before it closes
    from tests.conftest import register_async_finalizer

    async def _cleanup():
        for mesh in made:
            try:
                await mesh.stop()
            except Exception:  # noqa: BLE001 - already stopped is fine
                pass

    register_async_finalizer(_cleanup)

    # shared-broker transports need per-test-unique names; memory is isolated
    unique = kind != "memory"
    yield make, (lambda base: f"{base}.{uuid.uuid4().hex[:8]}" if unique else base)


async def _drain(predicate, timeout: float = 10.0) -> None:
    for _ in range(int(timeout / 0.05)):
        if predicate():
            return
        await asyncio.sleep(0.05)
    assert predicate(), "condition not reached before timeout"


class TestPublishSubscribeContract:
    async def test_per_key_order_across_interleaved_keys(self, transport):
        """Strictly serial per key, even with a slow handler and four keys
        interleaved on the wire."""
        make, topic = transport
        mesh = await make()
        name = topic("c.order")
        got: dict[bytes, list[bytes]] = {}

        async def handler(record):
            # stagger: without per-key serialization this scrambles order
            await asyncio.sleep(0.002 if record.key == b"k0" else 0.0)
            got.setdefault(record.key, []).append(record.value)

        await mesh.subscribe([name], handler, group_id=topic("g"))
        await asyncio.sleep(0.2)
        for i in range(40):
            key = f"k{i % 4}".encode()
            await mesh.publish(name, f"{i}".encode(), key=key)
        await _drain(lambda: sum(len(v) for v in got.values()) == 40)
        for k in (b"k0", b"k1", b"k2", b"k3"):
            vals = [int(v) for v in got[k]]
            assert vals == sorted(vals), f"key {k} out of order: {vals}"
        await mesh.stop()

    async def test_broadcast_tap_sees_only_post_attach(self, transport):
        make, topic = transport
        mesh = await make()
        name = topic("c.tap")
        await mesh.ensure_topics([name])
        await mesh.publish(name, b"before")
        await asyncio.sleep(0.1)
        got: list[bytes] = []

        async def handler(record):
            got.append(record.value)

        await mesh.subscribe([name], handler, group_id=None, ordered=False)
        await asyncio.sleep(0.3)
        await mesh.publish(name, b"after")
        await _drain(lambda: len(got) >= 1)
        assert got == [b"after"]
        await mesh.stop()

    async def test_group_work_sharing_exactly_once(self, transport):
        """Each record goes to exactly one member of a named group."""
        make, topic = transport
        mesh1 = await make()
        mesh2 = await make()
        name, group = topic("c.share"), topic("g.share")
        got1: list[bytes] = []
        got2: list[bytes] = []

        async def h1(r):
            got1.append(r.value)

        async def h2(r):
            got2.append(r.value)

        await mesh1.subscribe([name], h1, group_id=group)
        await mesh2.subscribe([name], h2, group_id=group)
        await asyncio.sleep(0.3)
        sent = [str(i).encode() for i in range(40)]
        for i, v in enumerate(sent):
            await mesh1.publish(name, v, key=f"k{i}".encode())
        await _drain(lambda: len(got1) + len(got2) == 40)
        assert sorted(got1 + got2) == sorted(sent)  # no loss, no duplication
        assert got1 and got2  # work actually shared
        await mesh1.stop()
        await mesh2.stop()

    async def test_group_rebalance_on_member_leave(self, transport):
        """After a member leaves, the survivor receives ALL new records."""
        make, topic = transport
        mesh1 = await make()
        mesh2 = await make()
        name, group = topic("c.rebal"), topic("g.rebal")
        got1: list[bytes] = []
        got2: list[bytes] = []

        async def h1(r):
            got1.append(r.value)

        async def h2(r):
            got2.append(r.value)

        sub1 = await mesh1.subscribe([name], h1, group_id=group)
        await mesh2.subscribe([name], h2, group_id=group)
        await asyncio.sleep(0.3)
        for i in range(20):
            await mesh1.publish(name, f"a{i}".encode(), key=f"k{i}".encode())
        await _drain(lambda: len(got1) + len(got2) == 20)
        await sub1.stop()
        await asyncio.sleep(0.3)
        before = len(got2)
        for i in range(20):
            await mesh2.publish(name, f"b{i}".encode(), key=f"k{i}".encode())
        await _drain(lambda: len(got2) - before == 20, timeout=15)
        assert len(got1) + len(got2) == 40
        await mesh1.stop()
        await mesh2.stop()

    async def test_headers_roundtrip(self, transport):
        make, topic = transport
        mesh = await make()
        name = topic("c.hdr")
        seen: list[dict] = []

        async def handler(record):
            seen.append(dict(record.headers))

        await mesh.subscribe([name], handler, group_id=topic("g.h"))
        await asyncio.sleep(0.2)
        await mesh.publish(
            name, b"x", key=b"k", headers={"x-calf-kind": "call", "n": "1"}
        )
        await _drain(lambda: len(seen) == 1)
        assert seen[0]["x-calf-kind"] == "call"
        assert seen[0]["n"] == "1"
        await mesh.stop()

    async def test_max_size_message_round_trips(self, transport):
        """The BIGGEST legal message (exactly max_message_bytes) must be
        deliverable — the coordinated-knob law: the consumer fetch budget
        floors at the producer budget, or the largest legal message
        could starve (reference: ConnectionProfile's fetch floor)."""
        make, topic = transport
        mesh = await make()
        name = topic("c.maxsize")
        await mesh.ensure_topics([name])
        payload = bytes(
            (i * 31 + 7) % 251 for i in range(mesh.max_message_bytes)
        )
        got: asyncio.Queue = asyncio.Queue()

        async def handler(record):
            await got.put(record.value)

        sub = await mesh.subscribe([name], handler, group_id=topic("g-max"))
        await mesh.publish(name, payload, key=b"k")
        received = await asyncio.wait_for(got.get(), timeout=30)
        assert received == payload  # intact, bit-for-bit
        await sub.stop()
        await mesh.stop()

    async def test_oversized_publish_rejected(self, transport):
        make, topic = transport
        mesh = await make()
        name = topic("c.big")
        blob = b"x" * (mesh.max_message_bytes + 1)
        with pytest.raises(ValueError, match="max_message_bytes"):
            await mesh.publish(name, blob)
        await mesh.stop()


class TestTableContract:
    async def test_catchup_gate_sees_compacted_state(self, transport):
        """A reader started AFTER the writes observes the latest value per
        key once start() returns (catch-up is a gate, not best-effort)."""
        make, topic = transport
        mesh1 = await make()
        name = topic("c.tbl1")
        writer = mesh1.table_writer(name)
        await writer.put("a", b"1")
        await writer.put("a", b"2")
        await writer.put("b", b"3")
        mesh2 = await make()
        reader = mesh2.table_reader(name)
        await reader.start()
        assert reader.get("a") == b"2"
        assert reader.get("b") == b"3"
        await mesh1.stop()
        await mesh2.stop()

    async def test_barrier_is_read_your_own_writes(self, transport):
        make, topic = transport
        mesh = await make()
        name = topic("c.tbl2")
        writer = mesh.table_writer(name)
        reader = mesh.table_reader(name)
        await reader.start()
        await writer.put("k", b"v1")
        await reader.barrier()
        assert reader.get("k") == b"v1"
        await writer.put("k", b"v2")
        await reader.barrier()
        assert reader.get("k") == b"v2"
        await mesh.stop()

    async def test_tombstone_deletes_for_late_readers(self, transport):
        """Tombstoned keys are GONE for catch-up readers — the compaction
        semantics that require real null-value records on Kafka."""
        make, topic = transport
        mesh = await make()
        name = topic("c.tbl3")
        writer = mesh.table_writer(name)
        await writer.put("keep", b"v")
        await writer.put("drop", b"v")
        await writer.tombstone("drop")
        reader_mesh = await make()
        reader = reader_mesh.table_reader(name)
        await reader.start()
        assert reader.get("keep") == b"v"
        assert reader.get("drop") is None
        assert "drop" not in reader.items()
        await mesh.stop()
        await reader_mesh.stop()
