"""Speculative decoding: drafters, batched verify, ragged acceptance.

The correctness contract under test (ISSUE 1 acceptance):

- greedy speculative output is TOKEN-EXACT vs non-speculative greedy,
  per request, across plain / chunked-prefill / prefix-cache-hit
  admission paths and both KV layouts;
- sampled speculative output keeps the target-model distribution
  (rejection sampling against the same filtered logits);
- ragged acceptance needs no physical KV rollback — rejected positions
  sit beyond the advanced length, prefix-cache pages are never touched;
- a request cancelled mid-speculation-wave reclaims its slot/pages.
"""

import asyncio

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from calfkit_tpu.inference import model as M  # noqa: E402
from calfkit_tpu.inference.config import (  # noqa: E402
    RuntimeConfig,
    SpecConfig,
    preset,
)
from calfkit_tpu.inference.engine import InferenceEngine  # noqa: E402
from calfkit_tpu.inference.sampler import (  # noqa: E402
    SamplingParams,
    filtered_logits,
    spec_accept_slots,
)
from calfkit_tpu.inference.spec import NgramDrafter  # noqa: E402

CFG = preset("debug")


@pytest.fixture(scope="module")
def params():
    return M.init_params(CFG, jax.random.key(0), dtype=jnp.float32)


def _rt(**over):
    kw = dict(
        max_batch_size=4, max_seq_len=128, prefill_chunk=16,
        decode_steps_per_dispatch=4, page_size=16,
    )
    kw.update(over)
    return RuntimeConfig(**kw)


async def _gen(engine, prompt, n, **kw):
    return [t async for t in engine.generate(prompt, max_new_tokens=n, **kw)]


class TestNgramDrafter:
    def _drafter(self, k=4, ngram_max=3, ngram_min=1):
        return NgramDrafter(
            SpecConfig(k=k, ngram_max=ngram_max, ngram_min=ngram_min)
        )

    def test_proposes_continuation_of_repeated_pattern(self):
        d = self._drafter()
        history = [9, 1, 2, 3, 4, 5, 8, 1, 2, 3]
        # tail [1, 2, 3] matched earlier -> continuation [4, 5, 8, 1]
        assert d.propose([(0, history)]) == [[4, 5, 8, 1]]

    def test_most_recent_match_wins(self):
        d = self._drafter(k=1, ngram_max=2)
        history = [1, 2, 7, 5, 1, 2, 9, 5, 1, 2]
        # [1, 2] occurs at 0 (-> 7) and 4 (-> 9); the recent one wins
        assert d.propose([(0, history)]) == [[9]]

    def test_longer_tail_preferred(self):
        d = self._drafter(k=1, ngram_max=3)
        history = [5, 1, 2, 3, 8, 0, 2, 3, 6, 1, 2, 3]
        # the 3-gram [1,2,3] (-> 8) beats the more recent 2-gram [2,3] (-> 6)
        assert d.propose([(0, history)]) == [[8]]

    def test_no_match_proposes_nothing(self):
        d = self._drafter()
        assert d.propose([(0, [1, 2, 3, 4, 5])]) == [[]]
        assert d.propose([(0, [7])]) == [[]]
        assert d.propose([(0, [])]) == [[]]

    def test_proposals_capped_at_k(self):
        d = self._drafter(k=2)
        history = [1, 2, 3, 4, 5, 6, 1, 2]
        assert d.propose([(0, history)]) == [[3, 4]]

    def test_alignment_no_false_byte_match(self):
        # int32 byte view: token 0x01020304-style overlaps must not count.
        # [258, 1] vs tail [2]: no token-level 2 anywhere earlier.
        d = self._drafter(k=2, ngram_max=1)
        assert d.propose([(0, [513, 2, 513, 3, 2])]) == [[513, 3]]


class TestSpecAcceptMath:
    """sampler.spec_accept_slots in isolation: the distribution contract."""

    def _run(self, row_logits, drafts_row, temp_val, B=8192, seed=1):
        S, V = row_logits.shape
        logits = jnp.broadcast_to(row_logits, (B, S, V))
        drafts = jnp.broadcast_to(
            jnp.asarray(drafts_row, jnp.int32)[None], (B, S - 1)
        )
        ndraft = jnp.full((B,), S - 1, jnp.int32)
        keys = jax.random.split(jax.random.key(seed), B)
        temp = jnp.full((B,), temp_val, jnp.float32)
        top_k = jnp.zeros((B,), jnp.int32)
        top_p = jnp.ones((B,), jnp.float32)
        out, emitted = spec_accept_slots(
            logits, drafts, ndraft, jnp.zeros((B,), jnp.int32), keys,
            temp, top_k, top_p, sampled=temp_val > 0,
        )
        return np.asarray(out), np.asarray(emitted)

    def test_greedy_accepts_exact_matches_only(self):
        V = 8
        row = jnp.eye(3, V) * 9.0  # argmax chain: 0, 1, 2
        out, emitted = self._run(row, [0, 1], 0.0, B=4)
        # both drafts match -> all accepted + bonus argmax(pos 2) = 2
        assert emitted.tolist() == [3] * 4
        assert out[0].tolist() == [0, 1, 2]
        out, emitted = self._run(row, [0, 5], 0.0, B=4)
        # second draft wrong -> accept 1, correct with argmax(pos 1) = 1
        assert emitted.tolist() == [2] * 4
        assert out[0][:2].tolist() == [0, 1]

    def test_sampled_marginal_matches_target(self):
        """Emitted-token marginals must equal the filtered target
        distribution — the rejection-sampling guarantee, checked
        empirically over many PRNG rows."""
        V = 8
        key = jax.random.key(3)
        row = jax.random.normal(key, (2, V)) * 1.5
        temp = 0.8
        p = np.asarray(jax.nn.softmax(filtered_logits(
            row, jnp.full((2,), temp), jnp.zeros((2,), jnp.int32),
            jnp.ones((2,), jnp.float32),
        ), axis=-1))
        # draft position 0 with a HIGH-probability token so plenty of rows
        # accept and position 1's conditional has statistics
        d0 = int(np.argmax(p[0]))
        out, emitted = self._run(row, [d0], temp)
        B = len(out)
        emp0 = np.bincount(out[:, 0], minlength=V) / B
        assert np.abs(emp0 - p[0]).max() < 0.02, (emp0, p[0])
        acc = out[out[:, 0] == d0]  # rows that accepted the draft
        assert len(acc) > B * p[0][d0] * 0.8
        emp1 = np.bincount(acc[:, 1], minlength=V) / len(acc)
        assert np.abs(emp1 - p[1]).max() < 0.03, (emp1, p[1])

    def test_sampled_rejection_resamples_off_draft(self):
        """A rejected draft's correction must come from the residual (the
        draft token itself is excluded)."""
        V = 6
        row = jnp.zeros((2, V))  # uniform target
        # draft a token, temp 1: p(d) = 1/6, ~5/6 of rows reject
        out, emitted = self._run(row, [4], 1.0)
        rejected = out[emitted == 1]
        assert len(rejected) > 0
        # the correction for a rejected point-mass draft NEVER re-emits it
        assert not (rejected[:, 0] == 4).any()

    def test_undrafted_positions_never_accepted(self):
        V = 4
        row = jnp.eye(2, V) * 9.0
        B = 4
        logits = jnp.broadcast_to(row, (B, 2, V))
        drafts = jnp.zeros((B, 1), jnp.int32)  # token 0 == argmax(pos 0)
        ndraft = jnp.zeros((B,), jnp.int32)  # but NOT actually drafted
        out, emitted = spec_accept_slots(
            logits, drafts, ndraft, jnp.zeros((B,), jnp.int32),
            jax.random.split(jax.random.key(0), B),
            jnp.zeros((B,), jnp.float32), jnp.zeros((B,), jnp.int32),
            jnp.ones((B,), jnp.float32), sampled=False,
        )
        assert emitted.tolist() == [1] * B  # only the correction token


class TestSpecGreedyParity:
    """Token-exact greedy parity, spec on vs off, across admission paths
    and KV layouts — the tentpole's pinned acceptance criterion."""

    PROMPTS = ([1, 5, 9, 13], list(range(2, 34)), [7, 8, 9] * 5)

    async def _parity(self, params, base_rt, spec_rt, prompts=None, n=20):
        base = InferenceEngine(CFG, base_rt, params=params)
        spec = InferenceEngine(CFG, spec_rt, params=params)
        await base.start()
        await spec.start()
        for prompt in prompts or self.PROMPTS:
            want = await _gen(base, prompt, n)
            got = await _gen(spec, prompt, n)
            assert got == want, f"spec diverged for prompt len {len(prompt)}"
        await base.stop()
        await spec.stop()

    async def test_dense_plain_admission(self, params):
        await self._parity(
            params, _rt(), _rt(speculative=SpecConfig(k=4))
        )

    async def test_paged_plain_admission(self, params):
        await self._parity(
            params,
            _rt(kv_layout="paged"),
            _rt(kv_layout="paged", speculative=SpecConfig(k=3)),
        )

    async def test_chunked_prefill_admission(self, params):
        kw = dict(chunked_prefill=True)
        await self._parity(
            params, _rt(**kw), _rt(speculative=SpecConfig(k=4), **kw),
            prompts=(list(range(2, 50)),),
        )

    async def test_prefix_cache_hit_admission(self, params):
        """The SECOND identical prompt admits through prefix-page reuse;
        its speculative output must still match non-speculative greedy."""
        kw = dict(kv_layout="paged", chunked_prefill=True, prefix_cache=True)
        base = InferenceEngine(CFG, _rt(**kw), params=params)
        spec = InferenceEngine(
            CFG, _rt(speculative=SpecConfig(k=4), **kw), params=params
        )
        await base.start()
        await spec.start()
        prompt = list(range(2, 50))  # two full pages: cacheable prefix
        want_cold = await _gen(base, prompt, 16)
        want_hit = await _gen(base, prompt, 16)
        got_cold = await _gen(spec, prompt, 16)
        got_hit = await _gen(spec, prompt, 16)
        assert spec.stats.prefix_hits > 0  # the hit path actually ran
        assert got_cold == want_cold
        assert got_hit == want_hit == want_cold
        await base.stop()
        await spec.stop()

    async def test_pallas_interpret_verify(self, params):
        """The Pallas verify fallback (per-position kernel decomposition)
        produces the same greedy tokens as the XLA verify."""
        spec_kw = dict(speculative=SpecConfig(k=3))
        await self._parity(
            params,
            _rt(),
            _rt(attention_impl="pallas_interpret", **spec_kw),
            prompts=([1, 5, 9],),
            n=10,
        )

    async def test_wave_shrinks_near_max_seq(self, params):
        """Rows near max_seq must shrink the verify wave instead of
        letting chunk writes clamp backward over valid history."""
        base_rt = _rt(max_seq_len=32, prefill_chunk=16)
        spec_rt = _rt(
            max_seq_len=32, prefill_chunk=16, speculative=SpecConfig(k=4)
        )
        base = InferenceEngine(CFG, base_rt, params=params)
        spec = InferenceEngine(CFG, spec_rt, params=params)
        await base.start()
        await spec.start()
        prompt = list(range(2, 18))  # 16 tokens; room for ~15 new
        want = await _gen(base, prompt, 100)  # stops at the seq bound
        got = await _gen(spec, prompt, 100)
        assert got == want
        assert len(got) < 100  # the bound actually engaged
        await base.stop()
        await spec.stop()

    async def test_mixed_batch_spec_isolation(self, params):
        """Concurrent requests (ragged per-row acceptance) must not
        perturb each other's greedy streams."""
        spec = InferenceEngine(
            CFG, _rt(speculative=SpecConfig(k=4)), params=params
        )
        await spec.start()
        solo = await _gen(spec, [7, 8, 9], 12)
        results = await asyncio.gather(
            _gen(spec, [7, 8, 9], 12),
            _gen(spec, [7, 8, 9] * 4, 12),  # self-similar: drafts fire
            _gen(spec, list(range(20, 30)), 12),
        )
        assert results[0] == solo
        await spec.stop()


class TestSpecSampled:
    async def test_seeded_spec_sampling_reproducible(self, params):
        engine = InferenceEngine(
            CFG, _rt(speculative=SpecConfig(k=3)), params=params
        )
        await engine.start()
        sp = SamplingParams(temperature=1.2, top_k=50)
        out1 = await _gen(engine, [1, 5, 9, 13], 12, sampling=sp, seed=7)
        out2 = await _gen(engine, [1, 5, 9, 13], 12, sampling=sp, seed=7)
        assert out1 == out2 and len(out1) == 12
        await engine.stop()

    async def test_mixed_greedy_and_sampled_rows(self, params):
        """A sampled neighbor in the verify wave must not perturb a greedy
        row's exact output."""
        engine = InferenceEngine(
            CFG, _rt(speculative=SpecConfig(k=3)), params=params
        )
        await engine.start()
        baseline = await _gen(engine, [2, 4, 6], 10)

        async def sampled(i):
            return await _gen(
                engine, [3 + i, 7, 11], 10,
                sampling=SamplingParams(temperature=1.5, top_p=0.9), seed=i,
            )

        crowd, *_rest = await asyncio.gather(
            _gen(engine, [2, 4, 6], 10), sampled(1), sampled(2)
        )
        assert crowd == baseline
        await engine.stop()


class TestSpecSchedulerIntegrity:
    async def test_cancel_mid_speculation_wave(self, params):
        """Abandoning a stream mid-wave reclaims slot + pages and the
        engine keeps serving (the reap crosses a spec tick in flight)."""
        engine = InferenceEngine(
            CFG,
            _rt(kv_layout="paged", speculative=SpecConfig(k=4)),
            params=params,
        )
        await engine.start()
        agen = engine.generate([7, 8, 9] * 5, max_new_tokens=64)
        got = 0
        async for _ in agen:
            got += 1
            if got >= 3:
                break  # abandon while speculation waves are in flight
        await agen.aclose()
        out = await _gen(engine, [4, 5], 6)
        assert len(out) == 6
        for _ in range(100):
            if not engine._page_alloc.held_slots:
                break
            await asyncio.sleep(0.05)
        assert not engine._page_alloc.held_slots
        assert not engine._active
        await engine.stop()

    async def test_ragged_acceptance_no_page_leaks_under_prefix_cache(
        self, params
    ):
        """Churn with speculative waves + prefix reuse: every page ends
        free or cache-owned (rollback never frees/corrupts shared
        pages)."""
        engine = InferenceEngine(
            CFG,
            _rt(kv_layout="paged", chunked_prefill=True, prefix_cache=True,
                speculative=SpecConfig(k=4)),
            params=params,
        )
        await engine.start()
        prompt = list(range(2, 50))
        for _ in range(2):
            outs = await asyncio.gather(*[
                _gen(engine, prompt, 12) for _ in range(6)
            ])
            assert all(o == outs[0] for o in outs)
        free = engine._page_alloc.free_pages
        cached = engine._prefix.size
        assert free + cached == engine.runtime.pool_pages() - 1
        await engine.stop()

    async def test_stats_counters_and_snapshot(self, params):
        from calfkit_tpu.inference.client import JaxLocalModelClient

        client = JaxLocalModelClient(
            config=CFG,
            runtime=_rt(speculative=SpecConfig(k=4)),
            max_new_tokens=16,
        )
        from calfkit_tpu.models.messages import user_message

        await client.request([user_message("abcabcabc")])
        snap = client.stats_snapshot()
        spec = snap["speculative"]
        assert spec["drafter"] == "ngram" and spec["k"] == 4
        assert spec["spec_proposed"] >= spec["spec_accepted"] >= 0
        assert 0.0 <= spec["acceptance_rate"] <= 1.0
        assert spec["tokens_per_dispatch"] >= 1.0
        engine = client._engine
        assert engine.stats.decode_tokens >= engine.stats.decode_dispatches
        await client.stop()

    async def test_spec_off_by_default(self, params):
        engine = InferenceEngine(CFG, _rt(), params=params)
        assert engine._drafter is None and engine._spec is None
        assert engine.runtime.speculative is None

    def test_invalid_k_rejected(self):
        with pytest.raises(ValueError, match="speculative.k"):
            InferenceEngine(CFG, _rt(speculative=SpecConfig(k=0)))

    def test_draft_params_without_seam_rejected(self, params):
        with pytest.raises(ValueError, match="draft_params"):
            InferenceEngine(CFG, _rt(), params=params, draft_params=params)


class TestDraftModelSeam:
    async def test_draft_model_parity_and_high_acceptance(self, params):
        """Draft == target (same params): near-total acceptance, and the
        output is still token-exact vs non-speculative greedy (the seam
        changes proposals, never the verified result)."""
        base = InferenceEngine(CFG, _rt(), params=params)
        spec = InferenceEngine(
            CFG,
            _rt(speculative=SpecConfig(k=4, draft=CFG)),
            params=params,
            draft_params=params,
        )
        await base.start()
        await spec.start()
        for prompt in ([1, 5, 9, 13], list(range(3, 20))):
            want = await _gen(base, prompt, 20)
            got = await _gen(spec, prompt, 20)
            assert got == want
        assert spec.stats.acceptance_rate > 0.9
        assert spec.stats.tokens_per_dispatch > 2.0
        await base.stop()
        await spec.stop()

    async def test_weak_draft_model_still_exact(self, params):
        """A draft model with DIFFERENT (random) weights proposes mostly
        garbage — acceptance collapses but output stays exact."""
        weak = M.init_params(CFG, jax.random.key(99), dtype=jnp.float32)
        base = InferenceEngine(CFG, _rt(), params=params)
        spec = InferenceEngine(
            CFG,
            _rt(speculative=SpecConfig(k=3, draft=CFG)),
            params=params,
            draft_params=weak,
        )
        await base.start()
        await spec.start()
        prompt = [2, 4, 6, 8]
        want = await _gen(base, prompt, 16)
        got = await _gen(spec, prompt, 16)
        assert got == want
        await base.stop()
        await spec.stop()

    async def test_wide_admission_catchup_no_draft_cache_corruption(
        self, params
    ):
        """A late admission's wide catch-up bucket must not clamp-slide
        over a mid-generation neighbor's draft KV (r6 review): with
        draft == target the neighbor's acceptance stays ~perfect, which
        it cannot if its early positions were overwritten."""
        rt = _rt(
            max_batch_size=2, max_seq_len=64, prefill_chunk=16,
            speculative=SpecConfig(k=3, draft=CFG),
        )
        base = InferenceEngine(
            CFG,
            _rt(max_batch_size=2, max_seq_len=64, prefill_chunk=16),
            params=params,
        )
        spec = InferenceEngine(CFG, rt, params=params, draft_params=params)
        await base.start()
        await spec.start()
        long_a = [(3 * i + 1) % CFG.vocab_size for i in range(40)]
        long_b = [(5 * i + 2) % CFG.vocab_size for i in range(50)]
        want_a = await _gen(base, long_a, 16)

        async def a_run():
            return await _gen(spec, long_a, 16)

        async def b_run():
            await asyncio.sleep(0.3)  # A is mid-generation when B admits
            return await _gen(spec, long_b, 8)

        got_a, _ = await asyncio.gather(a_run(), b_run())
        assert got_a == want_a
        # the neighbor's wide catch-up didn't corrupt A's draft KV:
        # acceptance across the run stays high (corruption tanks it)
        assert spec.stats.acceptance_rate > 0.8, spec.stats.acceptance_rate
        await base.stop()
        await spec.stop()

    async def test_draft_cache_catchup_across_slot_reuse(self, params):
        """Sequential requests reuse slots; the draft cache must catch up
        per occupant (stale draft state would only hurt acceptance, but
        outputs must stay exact)."""
        base = InferenceEngine(CFG, _rt(max_batch_size=1), params=params)
        spec = InferenceEngine(
            CFG,
            _rt(max_batch_size=1, speculative=SpecConfig(k=3, draft=CFG)),
            params=params,
            draft_params=params,
        )
        await base.start()
        await spec.start()
        for prompt in ([1, 2, 3], [9, 8, 7, 6], [5, 5, 5]):
            want = await _gen(base, prompt, 10)
            got = await _gen(spec, prompt, 10)
            assert got == want
        await base.stop()
        await spec.stop()


class TestSpecSharded:
    async def test_spec_paged_on_tp_mesh(self, params):
        """Speculative verify under GSPMD: paged KV on a tp=2 mesh, same
        tokens as the single-device non-speculative engine."""
        from calfkit_tpu.inference.sharding import make_mesh

        if len(jax.devices()) < 2:
            pytest.skip("needs the virtual multi-device mesh")
        base = InferenceEngine(CFG, _rt(), params=params)
        spec = InferenceEngine(
            CFG,
            _rt(kv_layout="paged", tp=2, speculative=SpecConfig(k=3)),
            params=params,
            mesh=make_mesh(tp=2),
        )
        await base.start()
        await spec.start()
        prompt = [7, 8, 9] * 4
        want = await _gen(base, prompt, 12)
        got = await _gen(spec, prompt, 12)
        assert got == want
        await base.stop()
        await spec.stop()
