"""Remote-provider clients against httpx.MockTransport — request mapping,
response parsing, error typing, and a full agent round trip over a mocked
API (reference analog: the provider sugar tests + the live lane, minus the
network)."""

import json

import httpx
import pytest

from calfkit_tpu.engine.model_client import ModelRequestParameters, ModelSettings
from calfkit_tpu.models.capability import ToolDef
from calfkit_tpu.models.messages import (
    ModelRequest,
    ModelResponse,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    UserPart,
)
from calfkit_tpu.providers import (
    AnthropicModelClient,
    ModelAPIError,
    OpenAIModelClient,
)

TOOL = ToolDef(
    name="lookup",
    description="Look things up.",
    parameters_schema={
        "type": "object",
        "properties": {"q": {"type": "string"}},
        "required": ["q"],
    },
)


def _openai(handler) -> OpenAIModelClient:
    return OpenAIModelClient(
        "gpt-test", api_key="k",
        http_client=httpx.AsyncClient(transport=httpx.MockTransport(handler)),
    )


def _anthropic(handler) -> AnthropicModelClient:
    return AnthropicModelClient(
        "claude-test", api_key="k",
        http_client=httpx.AsyncClient(transport=httpx.MockTransport(handler)),
    )


HISTORY = [
    ModelRequest(parts=[UserPart(content="find the answer")],
                 instructions="be brief"),
    ModelResponse(parts=[ToolCallOutput(
        tool_call_id="c1", tool_name="lookup", args={"q": "answer"})]),
    ModelRequest(parts=[ToolReturnPart(
        tool_call_id="c1", tool_name="lookup", content="42")]),
]


class TestOpenAI:
    async def test_request_mapping_and_parse(self):
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen["url"] = str(request.url)
            seen["auth"] = request.headers["authorization"]
            seen["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "model": "gpt-test-001",
                "choices": [{"message": {"content": "the answer is 42"}}],
                "usage": {"prompt_tokens": 30, "completion_tokens": 6},
            })

        client = _openai(handler)
        response = await client.request(
            HISTORY,
            ModelSettings(temperature=0.2, max_tokens=99, seed=7,
                          stop_sequences=["END"]),
            ModelRequestParameters(tool_defs=[TOOL]),
        )
        assert response.text() == "the answer is 42"
        assert response.usage.input_tokens == 30
        assert seen["auth"] == "Bearer k"
        payload = seen["payload"]
        assert payload["model"] == "gpt-test"
        assert payload["temperature"] == 0.2
        assert payload["max_tokens"] == 99
        assert payload["seed"] == 7
        assert payload["stop"] == ["END"]
        assert payload["tools"][0]["function"]["name"] == "lookup"
        roles = [m["role"] for m in payload["messages"]]
        assert roles == ["system", "user", "assistant", "tool"]
        assert payload["messages"][3]["tool_call_id"] == "c1"
        # the assistant turn carried its tool call with JSON-string args
        call = payload["messages"][2]["tool_calls"][0]
        assert json.loads(call["function"]["arguments"]) == {"q": "answer"}
        await client.aclose()

    async def test_tool_call_response_parsed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, json={
                "choices": [{"message": {
                    "content": None,
                    "tool_calls": [{
                        "id": "x9", "type": "function",
                        "function": {"name": "lookup",
                                     "arguments": "{\"q\": \"hi\"}"},
                    }],
                }}],
            })

        client = _openai(handler)
        response = await client.request([HISTORY[0]])
        calls = response.tool_calls()
        assert len(calls) == 1
        assert calls[0].tool_call_id == "x9"
        assert calls[0].args_dict() == {"q": "hi"}
        await client.aclose()

    async def test_structured_output_forces_tool_choice(self):
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "choices": [{"message": {"content": "x"}}]})

        client = _openai(handler)
        await client.request(
            [HISTORY[0]],
            params=ModelRequestParameters(
                output_tool=TOOL, allow_text_output=False
            ),
        )
        assert seen["payload"]["tool_choice"] == "required"
        await client.aclose()

    async def test_http_error_is_typed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(429, text="rate limited")

        client = _openai(handler)
        with pytest.raises(ModelAPIError) as exc_info:
            await client.request([HISTORY[0]])
        assert exc_info.value.status == 429
        assert "rate limited" in exc_info.value.body
        await client.aclose()


class TestAnthropic:
    async def test_request_mapping_and_parse(self):
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen["url"] = str(request.url)
            seen["key"] = request.headers["x-api-key"]
            seen["version"] = request.headers["anthropic-version"]
            seen["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "model": "claude-test-002",
                "content": [{"type": "text", "text": "it is 42"}],
                "usage": {"input_tokens": 21, "output_tokens": 4},
            })

        client = _anthropic(handler)
        response = await client.request(
            HISTORY,
            ModelSettings(temperature=0.5, top_k=40),
            ModelRequestParameters(tool_defs=[TOOL]),
        )
        assert response.text() == "it is 42"
        assert response.usage.output_tokens == 4
        assert seen["key"] == "k"
        payload = seen["payload"]
        assert payload["system"] == "be brief"
        assert payload["max_tokens"] > 0  # required by the API, defaulted
        assert payload["top_k"] == 40
        assert payload["tools"][0]["input_schema"]["required"] == ["q"]
        roles = [m["role"] for m in payload["messages"]]
        assert roles == ["user", "assistant", "user"]  # tool_result merged
        tool_result = payload["messages"][2]["content"][0]
        assert tool_result["type"] == "tool_result"
        assert tool_result["tool_use_id"] == "c1"
        await client.aclose()

    async def test_tool_use_parsed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, json={
                "content": [
                    {"type": "text", "text": "let me check"},
                    {"type": "tool_use", "id": "t7", "name": "lookup",
                     "input": {"q": "x"}},
                ],
                "usage": {"input_tokens": 1, "output_tokens": 2},
            })

        client = _anthropic(handler)
        response = await client.request([HISTORY[0]])
        assert response.text() == "let me check"
        assert response.tool_calls()[0].tool_call_id == "t7"
        await client.aclose()

    async def test_error_typed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(529, text="overloaded")

        client = _anthropic(handler)
        with pytest.raises(ModelAPIError) as exc_info:
            await client.request([HISTORY[0]])
        assert exc_info.value.status == 529
        await client.aclose()


class TestProviderThroughAgent:
    async def test_agent_round_trip_over_mocked_openai(self):
        """The provider in its real seat: an Agent on the mesh whose model
        is the OpenAI client; turn 1 calls a tool, turn 2 answers."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool
        def lookup(q: str) -> str:
            """L.

            Args:
                q: Q.
            """
            return f"result-for-{q}"

        def handler(request: httpx.Request) -> httpx.Response:
            payload = json.loads(request.content)
            has_tool_result = any(m["role"] == "tool" for m in payload["messages"])
            if not has_tool_result:
                return httpx.Response(200, json={"choices": [{"message": {
                    "content": None,
                    "tool_calls": [{
                        "id": "call1", "type": "function",
                        "function": {"name": "lookup",
                                     "arguments": "{\"q\": \"metrics\"}"},
                    }],
                }}]})
            returned = next(
                m["content"] for m in payload["messages"] if m["role"] == "tool"
            )
            return httpx.Response(200, json={"choices": [{"message": {
                "content": f"According to the tool: {returned}",
            }}]})

        model = _openai(handler)
        agent = Agent("remote_backed", model=model, tools=[lookup])
        mesh = InMemoryMesh()
        async with Worker([agent, lookup], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("remote_backed").execute("go", timeout=15)
            assert result.output == "According to the tool: result-for-metrics"
            await client.close()
        await model.aclose()

    async def test_api_failure_surfaces_as_model_fault(self):
        from calfkit_tpu.client import Client
        from calfkit_tpu.exceptions import NodeFaultError
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.models import FaultTypes
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(500, text="boom")

        model = _openai(handler)
        agent = Agent("doomed", model=model)
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            with pytest.raises(NodeFaultError) as exc_info:
                await client.agent("doomed").execute("go", timeout=15)
            assert exc_info.value.report.error_type == FaultTypes.MODEL_ERROR
            assert "HTTP 500" in exc_info.value.report.message
            await client.close()
        await model.aclose()


class TestModelFaultTyping:
    async def test_context_overflow_gets_narrower_type(self):
        """Vendor overflow phrasings classify as
        mesh.model.context_window_exceeded, not generic model_error."""
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.engine.turn import run_turn
        from calfkit_tpu.exceptions import NodeFaultError
        from calfkit_tpu.models import FaultTypes
        from calfkit_tpu.models.messages import ModelRequest, UserPart

        for phrase in (
            "This model's maximum context length is 8192 tokens",
            "prompt is too long: 210000 tokens",
            "prompt of 9000 tokens exceeds max_seq_len 8192",
        ):
            def boom(messages, params, _p=phrase):
                raise RuntimeError(_p)

            with pytest.raises(NodeFaultError) as exc_info:
                await run_turn(
                    FunctionModelClient(boom),
                    [ModelRequest(parts=[UserPart(content="hi")])],
                )
            assert exc_info.value.report.error_type == (
                FaultTypes.CONTEXT_WINDOW_EXCEEDED
            ), phrase

    async def test_hostile_model_exception_still_mints_typed_fault(self):
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.engine.turn import run_turn
        from calfkit_tpu.exceptions import NodeFaultError
        from calfkit_tpu.models import FaultTypes
        from calfkit_tpu.models.messages import ModelRequest, UserPart

        class Hostile(Exception):
            def __str__(self):
                raise RuntimeError("gotcha")

        def boom(messages, params):
            raise Hostile()

        with pytest.raises(NodeFaultError) as exc_info:
            await run_turn(
                FunctionModelClient(boom),
                [ModelRequest(parts=[UserPart(content="hi")])],
            )
        assert exc_info.value.report.error_type == FaultTypes.MODEL_ERROR


class TestStreaming:
    async def test_openai_sse_stream(self):
        sse = (
            'data: {"model":"gpt-s","choices":[{"delta":{"content":"Hel"}}]}\n\n'
            'data: {"choices":[{"delta":{"content":"lo"}}]}\n\n'
            'data: {"choices":[{"delta":{"tool_calls":[{"index":0,"id":"c5",'
            '"function":{"name":"lookup","arguments":"{\\"q\\""}}]}}]}\n\n'
            'data: {"choices":[{"delta":{"tool_calls":[{"index":0,'
            '"function":{"arguments":": \\"x\\"}"}}]}}]}\n\n'
            'data: {"usage":{"prompt_tokens":9,"completion_tokens":4},'
            '"choices":[]}\n\n'
            "data: [DONE]\n\n"
        )

        def handler(request: httpx.Request) -> httpx.Response:
            assert json.loads(request.content)["stream"] is True
            return httpx.Response(
                200, text=sse, headers={"content-type": "text/event-stream"}
            )

        from calfkit_tpu.engine.model_client import ResponseDone, TextDelta

        client = _openai(handler)
        events = [e async for e in client.request_stream([HISTORY[0]])]
        deltas = [e.text for e in events if isinstance(e, TextDelta)]
        assert deltas == ["Hel", "lo"]
        done = events[-1]
        assert isinstance(done, ResponseDone)
        assert done.response.text() == "Hello"
        calls = done.response.tool_calls()
        assert calls[0].tool_call_id == "c5"
        assert calls[0].args_dict() == {"q": "x"}
        assert done.response.usage.input_tokens == 9
        await client.aclose()

    async def test_anthropic_sse_stream(self):
        sse = (
            'data: {"type":"message_start","message":{"model":"claude-s",'
            '"usage":{"input_tokens":12}}}\n\n'
            'data: {"type":"content_block_delta","index":0,'
            '"delta":{"type":"text_delta","text":"Hi "}}\n\n'
            'data: {"type":"content_block_delta","index":0,'
            '"delta":{"type":"text_delta","text":"there"}}\n\n'
            'data: {"type":"content_block_start","index":1,'
            '"content_block":{"type":"tool_use","id":"t3","name":"lookup"}}\n\n'
            'data: {"type":"content_block_delta","index":1,'
            '"delta":{"type":"input_json_delta","partial_json":"{\\"q\\": "}}\n\n'
            'data: {"type":"content_block_delta","index":1,'
            '"delta":{"type":"input_json_delta","partial_json":"\\"y\\"}"}}\n\n'
            'data: {"type":"message_delta","usage":{"output_tokens":7}}\n\n'
            'data: {"type":"message_stop"}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(
                200, text=sse, headers={"content-type": "text/event-stream"}
            )

        from calfkit_tpu.engine.model_client import ResponseDone, TextDelta

        client = _anthropic(handler)
        events = [e async for e in client.request_stream([HISTORY[0]])]
        assert [e.text for e in events if isinstance(e, TextDelta)] == [
            "Hi ", "there",
        ]
        done = events[-1]
        assert isinstance(done, ResponseDone)
        assert done.response.text() == "Hi there"
        assert done.response.tool_calls()[0].args_dict() == {"q": "y"}
        assert done.response.usage.input_tokens == 12
        assert done.response.usage.output_tokens == 7
        await client.aclose()

    async def test_stream_error_before_first_token_is_typed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(503, text="down")

        client = _openai(handler)
        with pytest.raises(ModelAPIError) as exc_info:
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        assert exc_info.value.status == 503
        await client.aclose()

    async def test_agent_streams_tokens_from_remote_provider(self):
        """stream_tokens=True + a streaming remote model: TokenSteps arrive
        on the run's step stream before the terminal result."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        sse = (
            'data: {"choices":[{"delta":{"content":"str"}}]}\n\n'
            'data: {"choices":[{"delta":{"content":"eamed"}}]}\n\n'
            "data: [DONE]\n\n"
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(
                200, text=sse, headers={"content-type": "text/event-stream"}
            )

        model = _openai(handler)
        agent = Agent("streamy", model=model, stream_tokens=True)
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            handle = await client.agent("streamy").start("go", timeout=15)
            token_text, output = [], None
            async for event in handle.stream():
                step = getattr(event, "step", None)
                if step is not None and step.kind == "token":
                    token_text.append(step.text)
                elif step is None:
                    output = event.output
            assert output == "streamed"
            assert "".join(token_text) == "streamed"
            await client.close()
        await model.aclose()


class TestStreamMidFailure:
    async def test_openai_midstream_error_raises(self):
        sse = (
            'data: {"choices":[{"delta":{"content":"par"}}]}\n\n'
            'data: {"error":{"message":"server exploded"}}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        client = _openai(handler)
        with pytest.raises(ModelAPIError, match="mid-stream"):
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        await client.aclose()

    async def test_anthropic_midstream_error_raises(self):
        sse = (
            'data: {"type":"content_block_delta","index":0,'
            '"delta":{"type":"text_delta","text":"par"}}\n\n'
            'data: {"type":"error","error":{"type":"overloaded_error"}}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        client = _anthropic(handler)
        with pytest.raises(ModelAPIError, match="overloaded"):
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        await client.aclose()


class TestAdviceRound2Fixes:
    """Pins for the round-2 advisor findings (ADVICE.md r2)."""

    async def test_reasoning_models_get_max_completion_tokens(self):
        """o-series / gpt-5 reject the legacy max_tokens spelling."""
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen.update(json.loads(request.content))
            return httpx.Response(200, json={
                "choices": [{"message": {"content": "ok"}}],
            })

        client = OpenAIModelClient(
            "o3-mini", api_key="k",
            http_client=httpx.AsyncClient(
                transport=httpx.MockTransport(handler)),
        )
        await client.request(
            [HISTORY[0]], settings=ModelSettings(max_tokens=77))
        assert seen["max_completion_tokens"] == 77
        assert "max_tokens" not in seen
        await client.aclose()

    async def test_legacy_models_keep_max_tokens(self):
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen.update(json.loads(request.content))
            return httpx.Response(200, json={
                "choices": [{"message": {"content": "ok"}}],
            })

        client = _openai(handler)  # model name "gpt-test"
        await client.request(
            [HISTORY[0]], settings=ModelSettings(max_tokens=55))
        assert seen["max_tokens"] == 55
        assert "max_completion_tokens" not in seen
        await client.aclose()

    async def test_extra_override_never_sends_both_spellings(self):
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen.update(json.loads(request.content))
            return httpx.Response(200, json={
                "choices": [{"message": {"content": "ok"}}],
            })

        client = _openai(handler)
        await client.request(
            [HISTORY[0]],
            settings=ModelSettings(
                max_tokens=55, extra={"max_completion_tokens": 99}),
        )
        assert seen["max_completion_tokens"] == 99
        assert "max_tokens" not in seen
        await client.aclose()

    async def test_openai_stream_without_done_sentinel_raises(self):
        """A clean TCP close without [DONE] may hide truncation."""
        sse = 'data: {"choices":[{"delta":{"content":"par"}}]}\n\n'

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        client = _openai(handler)
        with pytest.raises(ModelAPIError, match=r"without \[DONE\]"):
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        await client.aclose()

    async def test_anthropic_stream_without_message_stop_raises(self):
        sse = (
            'data: {"type":"content_block_delta","index":0,'
            '"delta":{"type":"text_delta","text":"par"}}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        client = _anthropic(handler)
        with pytest.raises(ModelAPIError, match="without message_stop"):
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        await client.aclose()

    async def test_indexless_parallel_tool_deltas_stay_distinct(self):
        """Backends that omit 'index' must not merge two parallel calls
        into one slot; correlation falls back to the call id."""
        sse = (
            'data: {"choices":[{"delta":{"tool_calls":[{"id":"a1",'
            '"function":{"name":"lookup","arguments":"{\\"q\\""}}]}}]}\n\n'
            'data: {"choices":[{"delta":{"tool_calls":[{"id":"b2",'
            '"function":{"name":"lookup","arguments":"{\\"q\\""}}]}}]}\n\n'
            'data: {"choices":[{"delta":{"tool_calls":[{"id":"a1",'
            '"function":{"arguments":": \\"x\\"}"}}]}}]}\n\n'
            'data: {"choices":[{"delta":{"tool_calls":[{"id":"b2",'
            '"function":{"arguments":": \\"y\\"}"}}]}}]}\n\n'
            "data: [DONE]\n\n"
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        from calfkit_tpu.engine.model_client import ResponseDone

        client = _openai(handler)
        events = [e async for e in client.request_stream([HISTORY[0]])]
        done = events[-1]
        assert isinstance(done, ResponseDone)
        calls = done.response.tool_calls()
        assert len(calls) == 2
        by_id = {c.tool_call_id: c.args_dict() for c in calls}
        assert by_id == {"a1": {"q": "x"}, "b2": {"q": "y"}}
        await client.aclose()

    async def test_structured_overflow_code_wins_over_body_echo(self):
        """Classification prefers the provider's structured error fields:
        a 400 whose body ECHOES user text saying 'context window' but whose
        error.code is unrelated stays MODEL_ERROR; a structured
        context_length_exceeded code flips to CONTEXT_WINDOW_EXCEEDED."""
        from calfkit_tpu.engine.turn import run_turn
        from calfkit_tpu.exceptions import NodeFaultError
        from calfkit_tpu.models import FaultTypes
        from calfkit_tpu.models.messages import ModelRequest, UserPart

        async def run_with(body: dict) -> str:
            def handler(request: httpx.Request) -> httpx.Response:
                return httpx.Response(400, json=body)

            client = _openai(handler)
            try:
                with pytest.raises(NodeFaultError) as exc_info:
                    await run_turn(
                        client,
                        [ModelRequest(parts=[UserPart(content="hi")])],
                    )
                return exc_info.value.report.error_type
            finally:
                await client.aclose()

        echoed = await run_with({"error": {
            "code": "invalid_value",
            "message": "invalid 'metadata' near: 'my context window essay'",
        }})
        assert echoed == FaultTypes.MODEL_ERROR

        real = await run_with({"error": {
            "code": "context_length_exceeded",
            "message": "This model's maximum context length is 128 tokens.",
        }})
        assert real == FaultTypes.CONTEXT_WINDOW_EXCEEDED

    async def test_proxy_camelcase_overflow_code_classifies(self):
        """LiteLLM-style ContextWindowExceededError class-name codes and
        >2000-char error bodies must both still classify as overflow
        (structured fields are parsed from the UNTRUNCATED body)."""
        from calfkit_tpu.engine.turn import _is_context_overflow

        camel = ModelAPIError("x", status=400, body=json.dumps({
            "error": {"type": "ContextWindowExceededError",
                      "message": "too big"},
        }))
        assert _is_context_overflow(camel, str(camel))

        big = ModelAPIError("x", status=400, body=json.dumps({
            "error": {"code": "context_length_exceeded",
                      "message": "m" * 3000},
        }))
        assert big.error_code == "context_length_exceeded"
        assert _is_context_overflow(big, str(big))


class TestAdviceRound3Fixes:
    """Pins for the round-3 advisor findings (ADVICE.md r3)."""

    async def test_finish_reason_is_alternate_stream_termination(self):
        """Some OpenAI-compatible proxies end successful streams without
        [DONE]; a finish_reason-bearing chunk marks completion, so the
        stream must not be rejected as truncated."""
        sse = (
            'data: {"choices":[{"delta":{"content":"full"}}]}\n\n'
            'data: {"choices":[{"delta":{},"finish_reason":"stop"}]}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        from calfkit_tpu.engine.model_client import ResponseDone

        client = _openai(handler)
        events = [e async for e in client.request_stream([HISTORY[0]])]
        done = events[-1]
        assert isinstance(done, ResponseDone)
        assert done.response.text() == "full"
        await client.aclose()

    async def test_truncated_stream_still_raises_without_finish_reason(self):
        """The truncation guard survives the finish_reason alternate: no
        [DONE] AND no finish_reason is still an error."""
        sse = 'data: {"choices":[{"delta":{"content":"par"}}]}\n\n'

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        client = _openai(handler)
        with pytest.raises(ModelAPIError, match="truncated"):
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        await client.aclose()

    async def test_indexless_idless_continuation_goes_to_last_touched(self):
        """An indexless, id-less continuation delta attaches to the slot
        touched most recently in streaming order — NOT the highest index
        (which misattributes when a backend opens slot 1 before slot 0)."""
        sse = (
            # slot 1 opens FIRST, then slot 0; the id-less continuation
            # must extend slot 0 (last touched), not slot 1 (max index)
            'data: {"choices":[{"delta":{"tool_calls":[{"index":1,"id":"b2",'
            '"function":{"name":"lookup","arguments":"{\\"q\\": \\"y\\"}"}}]}}]}\n\n'
            'data: {"choices":[{"delta":{"tool_calls":[{"index":0,"id":"a1",'
            '"function":{"name":"lookup","arguments":"{\\"q\\""}}]}}]}\n\n'
            'data: {"choices":[{"delta":{"tool_calls":[{'
            '"function":{"arguments":": \\"x\\"}"}}]}}]}\n\n'
            "data: [DONE]\n\n"
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        from calfkit_tpu.engine.model_client import ResponseDone

        client = _openai(handler)
        events = [e async for e in client.request_stream([HISTORY[0]])]
        done = events[-1]
        assert isinstance(done, ResponseDone)
        by_id = {c.tool_call_id: c.args_dict() for c in done.response.tool_calls()}
        assert by_id == {"a1": {"q": "x"}, "b2": {"q": "y"}}
        await client.aclose()


class TestOpenAIResponses:
    """OpenAIResponsesModelClient parity suite (reference:
    calfkit/providers/pydantic_ai/openai.py:71)."""

    def _client(self, handler):
        from calfkit_tpu.providers import OpenAIResponsesModelClient

        return OpenAIResponsesModelClient(
            "gpt-resp", api_key="k",
            http_client=httpx.AsyncClient(
                transport=httpx.MockTransport(handler)
            ),
        )

    async def test_request_mapping_and_parse(self):
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen["url"] = str(request.url)
            seen["auth"] = request.headers["authorization"]
            seen["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "model": "gpt-resp-001",
                "status": "completed",
                "output": [{
                    "type": "message", "role": "assistant",
                    "content": [{"type": "output_text",
                                 "text": "the answer is 42"}],
                }],
                "usage": {"input_tokens": 30, "output_tokens": 6},
            })

        client = self._client(handler)
        response = await client.request(
            HISTORY,
            ModelSettings(temperature=0.2, max_tokens=99),
            ModelRequestParameters(tool_defs=[TOOL]),
        )
        assert response.text() == "the answer is 42"
        assert response.usage.input_tokens == 30
        assert seen["url"].endswith("/responses")
        assert seen["auth"] == "Bearer k"
        payload = seen["payload"]
        assert payload["model"] == "gpt-resp"
        assert payload["instructions"] == "be brief"
        assert payload["max_output_tokens"] == 99
        assert "max_tokens" not in payload
        # tools are FLAT in the Responses API (no nested "function" key)
        assert payload["tools"][0]["name"] == "lookup"
        assert payload["tools"][0]["parameters"]["required"] == ["q"]
        # history: user msg, assistant function_call, function_call_output
        kinds = [
            item.get("type") or item["role"] for item in payload["input"]
        ]
        assert kinds == ["user", "function_call", "function_call_output"]
        call_item = payload["input"][1]
        assert call_item["call_id"] == "c1"
        assert json.loads(call_item["arguments"]) == {"q": "answer"}
        assert payload["input"][2]["output"] == "42"
        await client.aclose()

    async def test_function_call_output_parsed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, json={
                "status": "completed",
                "output": [
                    {"type": "reasoning", "summary": []},
                    {"type": "function_call", "call_id": "x9",
                     "name": "lookup", "arguments": "{\"q\": \"hi\"}"},
                ],
            })

        client = self._client(handler)
        response = await client.request([HISTORY[0]])
        calls = response.tool_calls()
        assert len(calls) == 1
        assert calls[0].tool_call_id == "x9"
        assert calls[0].args_dict() == {"q": "hi"}
        await client.aclose()

    async def test_structured_output_forces_tool_choice(self):
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "status": "completed",
                "output": [{"type": "message", "role": "assistant",
                            "content": [{"type": "output_text", "text": "x"}]}],
            })

        client = self._client(handler)
        await client.request(
            [HISTORY[0]],
            params=ModelRequestParameters(
                output_tool=TOOL, allow_text_output=False
            ),
        )
        assert seen["payload"]["tool_choice"] == "required"
        await client.aclose()

    async def test_failed_status_raises_typed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, json={
                "status": "failed",
                "error": {"code": "server_error", "message": "boom"},
                "output": [],
            })

        client = self._client(handler)
        with pytest.raises(ModelAPIError, match="failed"):
            await client.request([HISTORY[0]])
        await client.aclose()

    async def test_http_error_is_typed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(429, text="rate limited")

        client = self._client(handler)
        with pytest.raises(ModelAPIError) as exc_info:
            await client.request([HISTORY[0]])
        assert exc_info.value.status == 429
        await client.aclose()

    async def test_sse_stream(self):
        sse = (
            'data: {"type":"response.created","response":{}}\n\n'
            'data: {"type":"response.output_text.delta","delta":"Hel"}\n\n'
            'data: {"type":"response.output_text.delta","delta":"lo"}\n\n'
            'data: {"type":"response.completed","response":{'
            '"model":"gpt-resp-001","output":['
            '{"type":"message","role":"assistant","content":'
            '[{"type":"output_text","text":"Hello"}]},'
            '{"type":"function_call","call_id":"c5","name":"lookup",'
            '"arguments":"{\\"q\\": \\"x\\"}"}],'
            '"usage":{"input_tokens":9,"output_tokens":3}}}\n\n'
            "data: [DONE]\n\n"
        )

        def handler(request: httpx.Request) -> httpx.Response:
            assert json.loads(request.content)["stream"] is True
            return httpx.Response(
                200, text=sse, headers={"content-type": "text/event-stream"}
            )

        from calfkit_tpu.engine.model_client import ResponseDone, TextDelta

        client = self._client(handler)
        events = [e async for e in client.request_stream([HISTORY[0]])]
        deltas = [e.text for e in events if isinstance(e, TextDelta)]
        assert deltas == ["Hel", "lo"]
        done = events[-1]
        assert isinstance(done, ResponseDone)
        assert done.response.text() == "Hello"
        calls = done.response.tool_calls()
        assert calls[0].tool_call_id == "c5"
        assert calls[0].args_dict() == {"q": "x"}
        assert done.response.usage.input_tokens == 9
        await client.aclose()

    async def test_stream_failed_event_raises(self):
        sse = (
            'data: {"type":"response.output_text.delta","delta":"par"}\n\n'
            'data: {"type":"response.failed","response":{"error":'
            '{"code":"server_error","message":"upstream died"}}}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        client = self._client(handler)
        with pytest.raises(ModelAPIError, match="mid-stream"):
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        await client.aclose()

    async def test_stream_without_completed_raises(self):
        sse = 'data: {"type":"response.output_text.delta","delta":"par"}\n\n'

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        client = self._client(handler)
        with pytest.raises(ModelAPIError, match="truncated"):
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        await client.aclose()

    async def test_agent_round_trip_over_mocked_responses_api(self):
        """The Responses client drives a full agent turn: tool call out,
        function_call_output back, final text."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        calls = {"n": 0}

        def handler(request: httpx.Request) -> httpx.Response:
            calls["n"] += 1
            payload = json.loads(request.content)
            if calls["n"] == 1:
                return httpx.Response(200, json={
                    "status": "completed",
                    "output": [{"type": "function_call", "call_id": "t1",
                                "name": "lookup",
                                "arguments": "{\"q\": \"answer\"}"}],
                })
            # second turn must carry the tool result back
            outputs = [i for i in payload["input"]
                       if i.get("type") == "function_call_output"]
            assert outputs and outputs[0]["call_id"] == "t1"
            return httpx.Response(200, json={
                "status": "completed",
                "output": [{"type": "message", "role": "assistant",
                            "content": [{"type": "output_text",
                                         "text": "it is 42"}]}],
            })

        @agent_tool
        def lookup(q: str) -> str:
            """Look things up.

            Args:
                q: the query.
            """
            return "42"

        model = self._client(handler)
        agent = Agent("resp_agent", model=model, tools=[lookup])
        mesh = InMemoryMesh()
        async with Worker([agent, lookup], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("resp_agent").execute("go", timeout=15)
            assert result.output == "it is 42"
            await client.close()
        await model.aclose()

    async def test_stream_capped_at_max_tokens_keeps_partial(self):
        """A max_output_tokens-capped stream ends with response.incomplete:
        the partial output is returned — chat-completions parity with
        finish_reason='length' (divergent handling would make the same cap
        fatal behind one provider and benign behind the other)."""
        sse = (
            'data: {"type":"response.output_text.delta","delta":"par"}\n\n'
            'data: {"type":"response.incomplete","response":{'
            '"incomplete_details":{"reason":"max_output_tokens"},'
            '"output":[{"type":"message","role":"assistant","content":'
            '[{"type":"output_text","text":"par"}]}]}}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        from calfkit_tpu.engine.model_client import ResponseDone

        client = self._client(handler)
        events = [e async for e in client.request_stream([HISTORY[0]])]
        done = events[-1]
        assert isinstance(done, ResponseDone)
        assert done.response.text() == "par"
        await client.aclose()

    async def test_stream_incomplete_content_filter_raises_typed(self):
        """Non-cap incomplete reasons (content filter) raise the typed
        error, not the generic truncation guard."""
        sse = (
            'data: {"type":"response.output_text.delta","delta":"par"}\n\n'
            'data: {"type":"response.incomplete","response":{'
            '"incomplete_details":{"reason":"content_filter"},'
            '"output":[]}}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        client = self._client(handler)
        with pytest.raises(ModelAPIError, match="content_filter"):
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        await client.aclose()

    async def test_request_capped_at_max_tokens_keeps_partial(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, json={
                "status": "incomplete",
                "incomplete_details": {"reason": "max_output_tokens"},
                "output": [{"type": "message", "role": "assistant",
                            "content": [{"type": "output_text",
                                         "text": "truncated ans"}]}],
            })

        client = self._client(handler)
        response = await client.request([HISTORY[0]])
        assert response.text() == "truncated ans"
        await client.aclose()

    def test_top_level_lazy_export(self):
        import calfkit_tpu

        assert calfkit_tpu.OpenAIResponsesModelClient is not None
        assert calfkit_tpu.FallbackModelClient is not None


class TestGemini:
    """GeminiModelClient parity suite (provider breadth, VERDICT r3
    missing #5; reference analog: the vendored google adapter)."""

    def _client(self, handler):
        from calfkit_tpu.providers import GeminiModelClient

        return GeminiModelClient(
            "gemini-test", api_key="k",
            http_client=httpx.AsyncClient(
                transport=httpx.MockTransport(handler)
            ),
        )

    async def test_request_mapping_and_parse(self):
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen["url"] = str(request.url)
            seen["key"] = request.headers["x-goog-api-key"]
            seen["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "candidates": [{
                    "content": {"role": "model",
                                "parts": [{"text": "the answer is 42"}]},
                    "finishReason": "STOP",
                }],
                "usageMetadata": {"promptTokenCount": 30,
                                  "candidatesTokenCount": 6},
                "modelVersion": "gemini-test-001",
            })

        client = self._client(handler)
        response = await client.request(
            HISTORY,
            ModelSettings(temperature=0.2, max_tokens=99, top_k=40,
                          stop_sequences=["END"]),
            ModelRequestParameters(tool_defs=[TOOL]),
        )
        assert response.text() == "the answer is 42"
        assert response.usage.input_tokens == 30
        assert response.model_name == "gemini-test-001"
        assert seen["url"].endswith("models/gemini-test:generateContent")
        assert seen["key"] == "k"
        payload = seen["payload"]
        sys_text = payload["systemInstruction"]["parts"][0]["text"]
        assert sys_text == "be brief"
        config = payload["generationConfig"]
        assert config["maxOutputTokens"] == 99
        assert config["temperature"] == 0.2
        assert config["topK"] == 40
        assert config["stopSequences"] == ["END"]
        decls = payload["tools"][0]["functionDeclarations"]
        assert decls[0]["name"] == "lookup"
        assert decls[0]["parameters"]["required"] == ["q"]
        # history: user, model functionCall, user functionResponse
        roles = [c["role"] for c in payload["contents"]]
        assert roles == ["user", "model", "user"]
        call = payload["contents"][1]["parts"][0]["functionCall"]
        assert call == {"name": "lookup", "args": {"q": "answer"}}
        fresp = payload["contents"][2]["parts"][0]["functionResponse"]
        assert fresp["name"] == "lookup"
        assert fresp["response"] == {"result": "42"}
        await client.aclose()

    async def test_function_call_parsed_with_minted_id(self):
        """Gemini has no call ids; the client mints name#index so the
        framework's id-keyed bookkeeping works."""
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, json={
                "candidates": [{
                    "content": {"role": "model", "parts": [
                        {"functionCall": {"name": "lookup",
                                          "args": {"q": "hi"}}},
                        {"functionCall": {"name": "lookup",
                                          "args": {"q": "again"}}},
                    ]},
                    "finishReason": "STOP",
                }],
            })

        client = self._client(handler)
        response = await client.request([HISTORY[0]])
        calls = response.tool_calls()
        assert [c.tool_call_id for c in calls] == ["lookup#0", "lookup#1"]
        assert calls[0].args_dict() == {"q": "hi"}
        await client.aclose()

    async def test_structured_output_forces_any_mode(self):
        seen = {}

        def handler(request: httpx.Request) -> httpx.Response:
            seen["payload"] = json.loads(request.content)
            return httpx.Response(200, json={
                "candidates": [{"content": {"role": "model",
                                            "parts": [{"text": "x"}]},
                                "finishReason": "STOP"}],
            })

        client = self._client(handler)
        await client.request(
            [HISTORY[0]],
            params=ModelRequestParameters(
                output_tool=TOOL, allow_text_output=False
            ),
        )
        mode = seen["payload"]["toolConfig"]["functionCallingConfig"]["mode"]
        assert mode == "ANY"
        await client.aclose()

    async def test_safety_finish_raises_typed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, json={
                "candidates": [{
                    "content": {"role": "model", "parts": []},
                    "finishReason": "SAFETY",
                }],
            })

        client = self._client(handler)
        with pytest.raises(ModelAPIError, match="SAFETY"):
            await client.request([HISTORY[0]])
        await client.aclose()

    async def test_blocked_prompt_raises_typed(self):
        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, json={
                "promptFeedback": {"blockReason": "SAFETY"},
            })

        client = self._client(handler)
        with pytest.raises(ModelAPIError, match="no candidates"):
            await client.request([HISTORY[0]])
        await client.aclose()

    async def test_sse_stream(self):
        sse = (
            'data: {"candidates":[{"content":{"role":"model","parts":'
            '[{"text":"Hel"}]}}]}\n\n'
            'data: {"candidates":[{"content":{"role":"model","parts":'
            '[{"text":"lo"},{"functionCall":{"name":"lookup",'
            '"args":{"q":"x"}}}]},"finishReason":"STOP"}],'
            '"usageMetadata":{"promptTokenCount":9,'
            '"candidatesTokenCount":3}}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            assert "streamGenerateContent" in str(request.url)
            assert "alt=sse" in str(request.url)
            return httpx.Response(
                200, text=sse, headers={"content-type": "text/event-stream"}
            )

        from calfkit_tpu.engine.model_client import ResponseDone, TextDelta

        client = self._client(handler)
        events = [e async for e in client.request_stream([HISTORY[0]])]
        deltas = [e.text for e in events if isinstance(e, TextDelta)]
        assert deltas == ["Hel", "lo"]
        done = events[-1]
        assert isinstance(done, ResponseDone)
        assert done.response.text() == "Hello"
        calls = done.response.tool_calls()
        assert calls[0].tool_call_id == "lookup#0"
        assert calls[0].args_dict() == {"q": "x"}
        assert done.response.usage.input_tokens == 9
        await client.aclose()

    async def test_stream_without_finish_reason_raises(self):
        sse = (
            'data: {"candidates":[{"content":{"role":"model","parts":'
            '[{"text":"par"}]}}]}\n\n'
        )

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, text=sse)

        client = self._client(handler)
        with pytest.raises(ModelAPIError, match="truncated"):
            async for _ in client.request_stream([HISTORY[0]]):
                pass
        await client.aclose()

    async def test_agent_round_trip_over_mocked_gemini(self):
        """Full agent turn: functionCall out, functionResponse back by
        NAME, final text."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        turns = {"n": 0}

        def handler(request: httpx.Request) -> httpx.Response:
            turns["n"] += 1
            payload = json.loads(request.content)
            if turns["n"] == 1:
                return httpx.Response(200, json={
                    "candidates": [{
                        "content": {"role": "model", "parts": [
                            {"functionCall": {"name": "lookup",
                                              "args": {"q": "answer"}}},
                        ]},
                        "finishReason": "STOP",
                    }],
                })
            responses = [
                part["functionResponse"]
                for content in payload["contents"]
                for part in content["parts"]
                if "functionResponse" in part
            ]
            assert responses and responses[0]["name"] == "lookup"
            return httpx.Response(200, json={
                "candidates": [{
                    "content": {"role": "model",
                                "parts": [{"text": "it is 42"}]},
                    "finishReason": "STOP",
                }],
            })

        @agent_tool
        def lookup(q: str) -> str:
            """Look things up.

            Args:
                q: the query.
            """
            return "42"

        model = self._client(handler)
        agent = Agent("gem_agent", model=model, tools=[lookup])
        mesh = InMemoryMesh()
        async with Worker([agent, lookup], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("gem_agent").execute("go", timeout=15)
            assert result.output == "it is 42"
            await client.close()
        await model.aclose()


class TestGeminiParallelCallIdentity:
    """VERDICT r4 weak #6: Gemini has no call ids — the minted ``name#n``
    identity must stay distinct and ordered when parallel calls to the
    SAME function arrive interleaved with text across streaming chunks,
    and the result rendering must keep call order so Gemini's
    name+order pairing resolves correctly."""

    async def test_interleaved_same_name_calls_stay_distinct(self):
        from calfkit_tpu.providers import GeminiModelClient

        chunks = [
            {"candidates": [{"content": {"parts": [{"text": "let me "}]}}]},
            {"candidates": [{"content": {"parts": [
                {"functionCall": {"name": "lookup", "args": {"q": "a"}}},
            ]}}]},
            {"candidates": [{"content": {"parts": [{"text": "check twice"}]}}]},
            {"candidates": [{"content": {"parts": [
                {"functionCall": {"name": "lookup", "args": {"q": "b"}}},
                {"functionCall": {"name": "other", "args": {}}},
            ]}, "finishReason": "STOP"}]},
        ]
        body = "".join(f"data: {json.dumps(c)}\r\n\r\n" for c in chunks)

        def handler(request: httpx.Request) -> httpx.Response:
            return httpx.Response(
                200, content=body.encode(),
                headers={"content-type": "text/event-stream"},
            )

        client = GeminiModelClient(
            "gemini-test", api_key="k",
            http_client=httpx.AsyncClient(
                transport=httpx.MockTransport(handler)),
        )
        from calfkit_tpu.engine.model_client import ResponseDone

        done = None
        async for item in client.request_stream([ModelRequest(
            parts=[UserPart(content="go")]
        )]):
            if isinstance(item, ResponseDone):
                done = item.response
        calls = done.tool_calls()
        ids = [c.tool_call_id for c in calls]
        assert len(ids) == len(set(ids)) == 3  # all distinct
        assert [c.tool_name for c in calls] == ["lookup", "lookup", "other"]
        # args stay attached to THEIR call despite the shared name
        assert [c.args_dict().get("q") for c in calls] == ["a", "b", None]
        await client.aclose()

    def test_duplicate_name_results_render_in_call_order(self):
        from calfkit_tpu.providers.gemini import render_gemini_contents

        _system, contents = render_gemini_contents([
            ModelResponse(parts=[
                ToolCallOutput(tool_call_id="lookup#0", tool_name="lookup",
                               args={"q": "a"}),
                ToolCallOutput(tool_call_id="lookup#1", tool_name="lookup",
                               args={"q": "b"}),
            ]),
            ModelRequest(parts=[
                ToolReturnPart(tool_call_id="lookup#0", tool_name="lookup",
                               content="first"),
                ToolReturnPart(tool_call_id="lookup#1", tool_name="lookup",
                               content="second"),
            ]),
        ])
        responses = [
            p["functionResponse"] for p in contents[-1]["parts"]
            if "functionResponse" in p
        ]
        # Gemini pairs same-name responses by ORDER: ours must match the
        # call order exactly
        assert [r["name"] for r in responses] == ["lookup", "lookup"]
        assert [r["response"]["result"] for r in responses] == [
            "first", "second",
        ]
