"""ISSUE 4: the engine flight recorder.

Covers the ring journal itself (append/overflow/dump), the fault-dump
trigger (an exception crossing the dispatch loop must produce a parseable
JSONL dump AND still tear serving down cleanly — fail-open even when the
journal writer itself is broken), and the acceptance path: ``ck
timeline`` reconstructing a request end-to-end from a real debug-engine
dump with ≥ 6 distinct event types.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from calfkit_tpu.inference.config import RuntimeConfig, preset
from calfkit_tpu.inference.engine import InferenceEngine
from calfkit_tpu.observability import flightrec
from calfkit_tpu.observability.flightrec import (
    EV_ADMIT,
    EV_DISPATCH_LAND,
    EV_DISPATCH_LAUNCH,
    EV_RETIRE,
    EV_SUBMIT,
    FlightRecorder,
)


class TestFlightRecorder:
    def test_append_and_order(self):
        fr = FlightRecorder(8)
        fr.append(EV_SUBMIT, "r1", -1, 17, 32)
        fr.append(EV_ADMIT, "r1", 3, 17, 0)
        events = fr.snapshot()
        assert [e[0] for e in events] == [0, 1]  # seq order
        assert events[0][2] == EV_SUBMIT and events[0][3] == "r1"
        assert fr.counts() == {"appended": 2, "dropped": 0, "dumped": 0}

    def test_capacity_rounds_to_power_of_two_and_overflow_counts(self):
        fr = FlightRecorder(10)
        assert fr.capacity == 16
        for _ in range(36):
            fr.append(EV_DISPATCH_LAUNCH, None, -1, 8, 4)
        counts = fr.counts()
        assert counts["appended"] == 36
        assert counts["dropped"] == 20  # overwritten, counted — not silent
        # the ring keeps the NEWEST events
        assert [e[0] for e in fr.snapshot()] == list(range(20, 36))

    def test_zero_capacity_disables(self):
        fr = FlightRecorder(0)
        fr.append(EV_SUBMIT, "r1")
        assert fr.snapshot() == []
        assert fr.counts() == {"appended": 0, "dropped": 0, "dumped": 0}
        assert fr not in flightrec.journals()

    def test_dump_is_parseable_jsonl(self, tmp_path):
        fr = FlightRecorder(8, label="debug")
        fr.append(EV_SUBMIT, "r1", -1, 17, 32)
        fr.append(EV_RETIRE, "r1", 2, 10, 0, "bye")
        path = fr.dump(reason="test", path=str(tmp_path / "d.jsonl"))
        lines = open(path).read().splitlines()
        meta = json.loads(lines[0])["flightrec"]
        assert meta["label"] == "debug" and meta["reason"] == "test"
        events = [json.loads(line) for line in lines[1:]]
        assert [e["event"] for e in events] == ["SUBMIT", "RETIRE"]
        assert events[1]["note"] == "bye"
        assert events[0]["t_s"] <= events[1]["t_s"]
        assert fr.counts()["dumped"] == 1

    def test_parse_dump_skips_garbage_and_meta(self):
        good = {"seq": 1, "t_s": 1.0, "event": "SUBMIT", "corr": "r",
                "slot": -1, "a": 0, "b": 0}
        events = flightrec.parse_dump(
            [json.dumps({"flightrec": {}}), "not json", "",
             json.dumps(good)]
        )
        assert [e["event"] for e in events] == ["SUBMIT"]

    def test_sigusr2_dumps_registered_journals(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CALFKIT_FLIGHTREC_DIR", str(tmp_path))
        fr = FlightRecorder(8, label="sig")
        fr.append(EV_SUBMIT, "r1")
        assert flightrec.install_sigusr2()
        os.kill(os.getpid(), signal.SIGUSR2)
        dumps = [p for p in os.listdir(tmp_path) if "sig" in p]
        assert dumps, "SIGUSR2 produced no dump"
        events = flightrec.parse_dump(
            open(tmp_path / dumps[0]).read().splitlines()
        )
        assert any(e["corr"] == "r1" for e in events)


class TestTimelineJoin:
    def _events(self):
        mk = lambda seq, ev, corr=None, slot=-1: {  # noqa: E731
            "seq": seq, "t_s": float(seq), "event": ev, "corr": corr,
            "slot": slot, "a": 0, "b": 0,
        }
        return [
            mk(0, "SUBMIT", "A"),
            mk(1, "SUBMIT", "B"),
            mk(2, "WAVE_FORM"),
            mk(3, "ADMIT", "A", slot=1),
            mk(4, "ADMIT", "B", slot=2),
            mk(5, "DISPATCH_LAUNCH"),
            mk(6, "RETIRE_DEFER", "A", slot=1),
            mk(7, "DISPATCH_LAND"),
            mk(8, "SLOT_FREE", slot=1),
            mk(9, "DISPATCH_LAUNCH"),  # past A's window
            mk(10, "SLOT_FREE", slot=2),
        ]

    def test_join_selects_own_batch_and_slot_events(self):
        timeline = flightrec.timeline_events(self._events(), "A")
        names = [e["event"] for e in timeline]
        # own events + batch events in window + the DEFERRED free past the
        # last own event (window extends to the slot's next SLOT_FREE)
        assert names == [
            "SUBMIT", "WAVE_FORM", "ADMIT", "DISPATCH_LAUNCH",
            "RETIRE_DEFER", "DISPATCH_LAND", "SLOT_FREE",
        ]
        # B's admission (another corr) and the post-window launch excluded
        assert all(e.get("corr") in (None, "A") for e in timeline)

    def test_unknown_corr_is_empty(self):
        assert flightrec.timeline_events(self._events(), "nope") == []

    def test_render_timeline(self):
        from calfkit_tpu.cli.obs import render_timeline

        timeline = flightrec.timeline_events(self._events(), "A")
        out = render_timeline(timeline, "A")
        assert "timeline A" in out
        assert "slot 1" in out
        assert "WAVE_FORM" in out and "(batch)" in out
        assert "SLOT_FREE" in out
        assert render_timeline([], "A") == "no events"


def _debug_engine(**overrides) -> InferenceEngine:
    rt = RuntimeConfig(
        max_batch_size=4, max_seq_len=256, kv_layout="paged",
        chunked_prefill=True, prefill_chunk=32, page_size=16,
        decode_steps_per_dispatch=4, **overrides,
    )
    return InferenceEngine(preset("debug"), rt)


class TestEngineTimelineAcceptance:
    async def test_timeline_reconstructs_request_end_to_end(self, tmp_path):
        """The ISSUE 4 acceptance bar: a dump from the REAL debug engine
        reconstructs one request with ≥ 6 distinct event types —
        admission, wave, page alloc, an overlap dispatch event,
        retirement, and the (deferred) free."""
        engine = _debug_engine()
        await engine.start()

        async def one(i: int) -> list[int]:
            out = []
            async for token in engine.generate(
                list(range(1, 20)), max_new_tokens=10, corr=f"req-{i}"
            ):
                out.append(token)
            return out

        outs = await asyncio.gather(*[one(i) for i in range(3)])
        assert all(len(o) == 10 for o in outs)
        path = engine._journal.dump(
            reason="test", path=str(tmp_path / "dump.jsonl")
        )
        await engine.stop()
        with open(path) as f:
            events = flightrec.parse_dump(f)
        timeline = flightrec.timeline_events(events, "req-1")
        kinds = {e["event"] for e in timeline}
        assert {"ADMIT", "WAVE_FORM", "PAGE_ALLOC"} <= kinds
        assert kinds & {"DISPATCH_LAUNCH", "DISPATCH_LAND", "SPEC_TICK"}
        assert kinds & {"RETIRE", "RETIRE_DEFER"}
        assert kinds & {"SLOT_FREE", "PAGE_FREE"}
        assert len(kinds) >= 6
        # the lifecycle reads in causal order: admission before dispatches
        # before the slot free
        names = [e["event"] for e in timeline]
        assert names.index("ADMIT") < names.index("DISPATCH_LAUNCH")
        assert names[-1] in ("SLOT_FREE", "PAGE_FREE", "DISPATCH_LAND")
        # and the CLI renders it
        from calfkit_tpu.cli.obs import render_timeline

        out = render_timeline(timeline, "req-1")
        assert "ADMIT" in out and "DISPATCH_LAUNCH" in out

    async def test_stats_snapshot_reports_flightrec_counts(self):
        from calfkit_tpu.inference.client import JaxLocalModelClient

        engine = _debug_engine()
        client = JaxLocalModelClient(engine=engine)
        # cold (engine built but idle) and live both carry the key set
        snap = client.stats_snapshot()
        assert snap["flightrec"] == {"appended": 0, "dropped": 0, "dumped": 0}
        await engine.start()
        async for _ in engine.generate([1, 2, 3], max_new_tokens=4):
            pass
        snap = client.stats_snapshot()
        assert snap["flightrec"]["appended"] > 0
        await engine.stop()

    async def test_flightrec_off_records_nothing(self):
        engine = _debug_engine(flightrec_events=0)
        await engine.start()
        async for _ in engine.generate([1, 2, 3], max_new_tokens=4):
            pass
        assert engine._journal.counts()["appended"] == 0
        await engine.stop()


class TestFaultDump:
    async def _run_to_fault(self, engine, tmp_path, monkeypatch) -> None:
        """Serve until the 3rd decode tick raises (so the dump holds real
        pre-fault dispatch events)."""
        monkeypatch.setenv("CALFKIT_FLIGHTREC_DIR", str(tmp_path))
        # patch whichever dispatch lane is live: the ragged unified tick
        # (chunked + overlap, the default) or the legacy decode tick
        lane = "_ragged_tick" if engine._ragged else "_decode_tick"
        original = getattr(engine, lane)
        ticks = {"n": 0}

        def exploding_tick():
            ticks["n"] += 1
            if ticks["n"] >= 3:
                raise RuntimeError("injected dispatch fault")
            return original()

        setattr(engine, lane, exploding_tick)
        await engine.start()
        out = []
        async for token in engine.generate(
            list(range(1, 20)), max_new_tokens=64, corr="doomed"
        ):
            out.append(token)
        # the fault tore serving down mid-stream: the consumer got _DONE
        # (clean early end), not a hang and not an exception
        assert len(out) < 64

    async def test_fault_produces_parseable_dump_and_clean_teardown(
        self, tmp_path, monkeypatch
    ):
        engine = _debug_engine()
        await self._run_to_fault(engine, tmp_path, monkeypatch)
        dumps = os.listdir(tmp_path)
        assert len(dumps) == 1, f"expected one fault dump, got {dumps}"
        with open(tmp_path / dumps[0]) as f:
            lines = f.read().splitlines()
        meta = json.loads(lines[0])["flightrec"]
        assert meta["reason"] == "fault"
        events = flightrec.parse_dump(lines)
        kinds = [e["event"] for e in events]
        # the dump holds the faulting window: the request's admission,
        # the dispatches that ran before the injected fault, and the
        # FAULT event carrying the exception
        assert "ADMIT" in kinds and "DISPATCH_LAUNCH" in kinds
        assert kinds[-1] == "FAULT"
        fault = events[-1]
        assert "injected dispatch fault" in fault["note"]
        # teardown completed: scheduler task finished, stop() is clean
        assert engine._running is False
        await engine.stop()

    async def test_broken_journal_writer_never_masks_the_fault(
        self, tmp_path, monkeypatch
    ):
        """Fail-open: a dump writer that itself raises must not block
        teardown or hang consumers — the original fault stays the story."""
        engine = _debug_engine()

        def broken_dump(self, **kwargs):
            raise OSError("disk full")

        # class-level patch: FlightRecorder uses __slots__ (no instance
        # attribute shadowing); monkeypatch restores the method after
        monkeypatch.setattr(flightrec.FlightRecorder, "dump", broken_dump)
        await self._run_to_fault(engine, tmp_path, monkeypatch)
        assert os.listdir(tmp_path) == []  # nothing written...
        assert engine._running is False  # ...and teardown still completed
        await engine.stop()

    async def test_fault_dump_writes_into_env_dir(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CALFKIT_FLIGHTREC_DIR", str(tmp_path / "sub"))
        assert flightrec.default_dump_dir() == str(tmp_path / "sub")
        fr = FlightRecorder(8, label="envdir")
        fr.append(EV_SUBMIT, "r")
        path = fr.dump(reason="manual")
        assert path.startswith(str(tmp_path / "sub"))
        assert os.path.exists(path)
