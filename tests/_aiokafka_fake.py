"""An in-process fake of the aiokafka subset KafkaMesh uses.

Purpose: this image has no aiokafka and no broker, so ``kafka.py`` would be
specified-but-never-executed.  Installing this module as ``aiokafka`` (see
``install()``) lets the transport contract suite drive the REAL KafkaMesh
code — its producer guard, consumer wiring, table reader catch-up/barrier
math, tombstones, and group rebalance handling — against a faithful
in-process broker model.

Modeled semantics (the ones the contract asserts):

- topics with N partitions; keyed records land on ``crc32(key) % N``
  (keyless round-robin), per-partition append logs with offsets;
- group consumers share partitions (range assignment, rebalance on member
  join/leave, resume from committed offsets — commit==consumed position,
  i.e. auto-commit ack-first);
- groupless consumers get every partition; ``auto_offset_reset`` decides
  earliest/latest start;
- ``end_offsets`` / ``assignment`` as the table reader's barrier needs;
- admin ``create_topics`` raising ``TopicAlreadyExistsError``;
- tombstones are records with ``value=None`` (compaction itself is not
  modeled: readers consume the full log, which is semantically identical
  for correctness).
"""

from __future__ import annotations

import asyncio
import itertools
import sys
import time
import types
import zlib
from dataclasses import dataclass, field
from typing import Any

NUM_PARTITIONS = 16


@dataclass(frozen=True)
class TopicPartition:
    topic: str
    partition: int


@dataclass(frozen=True)
class ConsumerRecord:
    topic: str
    partition: int
    offset: int
    key: bytes | None
    value: bytes | None
    headers: list[tuple[str, bytes]]
    timestamp: int  # ms, as aiokafka


class TopicAlreadyExistsError(Exception):
    pass


@dataclass
class _Group:
    members: list["AIOKafkaConsumer"] = field(default_factory=list)
    committed: dict[TopicPartition, int] = field(default_factory=dict)
    generation: int = 0


class _Broker:
    """One broker world per bootstrap string."""

    def __init__(self) -> None:
        self.topics: dict[str, list[list[ConsumerRecord]]] = {}
        self.groups: dict[str, _Group] = {}
        self.advanced = asyncio.Event()
        self._rr = itertools.count()

    def ensure_topic(self, name: str) -> list[list[ConsumerRecord]]:
        if name not in self.topics:
            self.topics[name] = [[] for _ in range(NUM_PARTITIONS)]
        return self.topics[name]

    def append(self, topic: str, key: bytes | None, value: bytes | None,
               headers: list[tuple[str, bytes]]) -> None:
        logs = self.ensure_topic(topic)
        if key:
            partition = zlib.crc32(key) % len(logs)
        else:
            partition = next(self._rr) % len(logs)
        log = logs[partition]
        log.append(ConsumerRecord(
            topic=topic, partition=partition, offset=len(log), key=key,
            value=value, headers=list(headers),
            timestamp=int(time.time() * 1000),
        ))
        self.advanced.set()

    def end_offset(self, tp: TopicPartition) -> int:
        logs = self.topics.get(tp.topic)
        return len(logs[tp.partition]) if logs else 0

    # ------------------------------------------------------------- groups
    def join(self, group_id: str, consumer: "AIOKafkaConsumer") -> None:
        group = self.groups.setdefault(group_id, _Group())
        group.members.append(consumer)
        self._rebalance(group)

    def leave(self, group_id: str, consumer: "AIOKafkaConsumer") -> None:
        group = self.groups.get(group_id)
        if group and consumer in group.members:
            group.members.remove(consumer)
            self._rebalance(group)

    def _rebalance(self, group: _Group) -> None:
        """Range assignment over the union of the members' topics.

        Position-cache rule (mirrors real aiokafka): a member's locally
        cached position is valid only while it holds the partition
        CONTINUOUSLY.  On revoke the position is committed; on (re)gain the
        member re-derives from the group's committed offset — otherwise a
        partition bouncing A→B→A would replay records B already processed.
        """
        group.generation += 1
        members = group.members
        if not members:
            return
        previous = {id(m): set(m._assignment) for m in members}
        topics = sorted({t for m in members for t in m._topics})
        for m in members:
            m._assignment = set()
        for topic in topics:
            self.ensure_topic(topic)
            interested = [m for m in members if topic in m._topics]
            for p in range(NUM_PARTITIONS):
                owner = interested[p % len(interested)]
                owner._assignment.add(TopicPartition(topic, p))
        for m in members:
            old = previous[id(m)]
            for tp in old - m._assignment:  # revoked: commit, drop cache
                if tp in m._positions:
                    group.committed[tp] = max(
                        group.committed.get(tp, 0), m._positions.pop(tp)
                    )
            for tp in m._assignment - old:  # gained: stale cache invalid
                m._positions.pop(tp, None)
        self.advanced.set()


_BROKERS: dict[str, _Broker] = {}


def _broker(bootstrap: Any) -> _Broker:
    key = str(bootstrap)
    if key not in _BROKERS:
        _BROKERS[key] = _Broker()
    return _BROKERS[key]


def reset() -> None:
    """Fresh broker worlds (per-test isolation when desired)."""
    _BROKERS.clear()


class AIOKafkaProducer:
    def __init__(self, *, bootstrap_servers: Any, **_ignored: Any):
        self._broker = _broker(bootstrap_servers)
        self._started = False

    async def start(self) -> None:
        self._started = True

    async def stop(self) -> None:
        self._started = False

    async def send_and_wait(
        self, topic: str, value: bytes | None = None, *,
        key: bytes | None = None,
        headers: list[tuple[str, bytes]] | None = None,
    ) -> None:
        if not self._started:
            raise RuntimeError("producer not started")
        self._broker.append(topic, key, value, headers or [])


class AIOKafkaConsumer:
    def __init__(
        self, *topics: str, bootstrap_servers: Any,
        group_id: str | None = None, auto_offset_reset: str = "latest",
        enable_auto_commit: bool = True, **_ignored: Any,
    ):
        self._broker = _broker(bootstrap_servers)
        self._topics = list(topics)
        self._group_id = group_id
        self._from_latest = auto_offset_reset == "latest"
        self._auto_commit = enable_auto_commit
        self._assignment: set[TopicPartition] = set()
        self._positions: dict[TopicPartition, int] = {}
        self._started = False

    async def start(self) -> None:
        for topic in self._topics:
            self._broker.ensure_topic(topic)
        if self._group_id is None:
            self._assignment = {
                TopicPartition(t, p)
                for t in self._topics
                for p in range(NUM_PARTITIONS)
            }
        else:
            self._broker.join(self._group_id, self)
        self._started = True

    async def stop(self) -> None:
        if not self._started:
            return
        self._started = False
        if self._group_id is not None:
            # graceful leave: commit consumed positions, then rebalance —
            # the survivor resumes exactly where this member stopped
            group = self._broker.groups.get(self._group_id)
            if group is not None:
                for tp, pos in self._positions.items():
                    group.committed[tp] = max(group.committed.get(tp, 0), pos)
            self._broker.leave(self._group_id, self)
        self._broker.advanced.set()

    def assignment(self) -> set[TopicPartition]:
        return set(self._assignment)

    async def end_offsets(
        self, partitions: list[TopicPartition]
    ) -> dict[TopicPartition, int]:
        return {tp: self._broker.end_offset(tp) for tp in partitions}

    def _position(self, tp: TopicPartition) -> int:
        if tp in self._positions:
            return self._positions[tp]
        if self._group_id is not None:
            group = self._broker.groups[self._group_id]
            start = group.committed.get(
                tp, self._broker.end_offset(tp) if self._from_latest else 0
            )
        else:
            start = self._broker.end_offset(tp) if self._from_latest else 0
        self._positions[tp] = start
        return start

    def __aiter__(self) -> "AIOKafkaConsumer":
        return self

    async def __anext__(self) -> ConsumerRecord:
        while True:
            if not self._started:
                raise StopAsyncIteration
            for tp in sorted(self._assignment, key=lambda t: (t.topic, t.partition)):
                position = self._position(tp)
                logs = self._broker.topics.get(tp.topic)
                if logs is None:
                    continue
                log = logs[tp.partition]
                if position < len(log):
                    record = log[position]
                    self._positions[tp] = position + 1
                    if self._auto_commit and self._group_id is not None:
                        # ack-first: commit cadence independent of handling
                        group = self._broker.groups.get(self._group_id)
                        if group is not None:
                            group.committed[tp] = position + 1
                    return record
            self._broker.advanced.clear()
            # re-check before parking (lost-wakeup guard), then wait with a
            # short cap so assignment changes are noticed promptly
            try:
                await asyncio.wait_for(self._broker.advanced.wait(), 0.05)
            except asyncio.TimeoutError:
                pass


class _AdminNewTopic:
    def __init__(self, *, name: str, num_partitions: int,
                 replication_factor: int, topic_configs: dict | None = None):
        self.name = name
        self.num_partitions = num_partitions
        self.topic_configs = dict(topic_configs or {})


class AIOKafkaAdminClient:
    def __init__(self, *, bootstrap_servers: Any, **_ignored: Any):
        self._broker = _broker(bootstrap_servers)

    async def start(self) -> None:
        pass

    async def close(self) -> None:
        pass

    async def create_topics(
        self, topics: list[_AdminNewTopic], validate_only: bool = False
    ) -> None:
        existing = [t.name for t in topics if t.name in self._broker.topics]
        if existing:
            raise TopicAlreadyExistsError(
                f"TopicAlreadyExistsError: {existing}"
            )
        if not validate_only:
            for t in topics:
                self._broker.ensure_topic(t.name)


def install() -> None:
    """Register this fake as ``aiokafka`` (+ ``aiokafka.admin``) in
    sys.modules.  Refuses to shadow a real install."""
    if "aiokafka" in sys.modules and not getattr(
        sys.modules["aiokafka"], "__calfkit_fake__", False
    ):
        raise RuntimeError("real aiokafka present; not shadowing it")
    root = types.ModuleType("aiokafka")
    root.__calfkit_fake__ = True
    root.AIOKafkaProducer = AIOKafkaProducer
    root.AIOKafkaConsumer = AIOKafkaConsumer
    root.TopicPartition = TopicPartition
    root.ConsumerRecord = ConsumerRecord
    admin = types.ModuleType("aiokafka.admin")
    admin.__calfkit_fake__ = True
    admin.AIOKafkaAdminClient = AIOKafkaAdminClient
    admin.NewTopic = _AdminNewTopic
    errors = types.ModuleType("aiokafka.errors")
    errors.__calfkit_fake__ = True
    errors.TopicAlreadyExistsError = TopicAlreadyExistsError
    root.admin = admin
    root.errors = errors
    sys.modules["aiokafka"] = root
    sys.modules["aiokafka.admin"] = admin
    sys.modules["aiokafka.errors"] = errors


def uninstall() -> None:
    for name in ("aiokafka", "aiokafka.admin", "aiokafka.errors"):
        mod = sys.modules.get(name)
        if mod is not None and getattr(mod, "__calfkit_fake__", False):
            sys.modules.pop(name, None)
