"""FallbackModelClient: provider composition semantics (reference analog:
the vendored FallbackModel's request/stream fallback + exception-group
behavior, calfkit/_vendor/pydantic_ai/models/fallback.py)."""

import json

import httpx
import pytest

from calfkit_tpu.engine import EchoModelClient, FunctionModelClient
from calfkit_tpu.engine.model_client import ResponseDone, TextDelta
from calfkit_tpu.models.messages import ModelRequest, ModelResponse, TextOutput, UserPart
from calfkit_tpu.providers import (
    FallbackExhaustedError,
    FallbackModelClient,
    ModelAPIError,
    OpenAIModelClient,
)

MSGS = [ModelRequest(parts=[UserPart(content="hi")])]


def _failing(name="primary", exc=None):
    def boom(messages, params):
        raise exc or ModelAPIError("backend down", status=503)

    return FunctionModelClient(boom, name=name)


class TestRequestFallback:
    async def test_primary_failure_rolls_to_secondary(self):
        fb = FallbackModelClient(_failing(), EchoModelClient(name="backup"))
        response = await fb.request(MSGS)
        assert response.text() == "echo: hi"
        assert fb.model_name == "fallback:primary,backup"

    async def test_non_matching_exception_propagates_immediately(self):
        fb = FallbackModelClient(
            _failing(exc=ValueError("schema bug")),
            EchoModelClient(name="backup"),
        )
        with pytest.raises(ValueError, match="schema bug"):
            await fb.request(MSGS)

    async def test_all_failed_raises_exhausted_with_every_cause(self):
        fb = FallbackModelClient(
            _failing("a", ModelAPIError("a down", status=500)),
            _failing("b", ConnectionError("b unreachable")),
        )
        with pytest.raises(FallbackExhaustedError) as exc_info:
            await fb.request(MSGS)
        err = exc_info.value
        assert len(err.exceptions) == 2
        assert "a down" in str(err) and "b unreachable" in str(err)

    async def test_custom_predicate(self):
        fb = FallbackModelClient(
            _failing(exc=RuntimeError("quota")),
            EchoModelClient(name="backup"),
            fallback_on=lambda e: "quota" in str(e),
        )
        response = await fb.request(MSGS)
        assert response.text() == "echo: hi"

    async def test_remote_to_remote_over_mock_transport(self):
        """The parity shape: a 503 OpenAI primary falls back to a healthy
        OpenAI-compatible secondary."""
        def down(request: httpx.Request) -> httpx.Response:
            return httpx.Response(503, text="overloaded")

        def up(request: httpx.Request) -> httpx.Response:
            return httpx.Response(200, json={
                "choices": [{"message": {"content": "from backup"}}],
            })

        primary = OpenAIModelClient(
            "gpt-a", api_key="k",
            http_client=httpx.AsyncClient(transport=httpx.MockTransport(down)),
        )
        backup = OpenAIModelClient(
            "gpt-b", api_key="k",
            http_client=httpx.AsyncClient(transport=httpx.MockTransport(up)),
        )
        fb = FallbackModelClient(primary, backup)
        response = await fb.request(MSGS)
        assert response.text() == "from backup"
        await fb.aclose()


class TestStreamFallback:
    async def test_prestream_failure_falls_back(self):
        fb = FallbackModelClient(_failing(), EchoModelClient(name="backup"))
        events = [e async for e in fb.request_stream(MSGS)]
        assert isinstance(events[-1], ResponseDone)
        assert events[-1].response.text() == "echo: hi"

    async def test_midstream_failure_propagates_not_retries(self):
        class MidFail(EchoModelClient):
            async def request_stream(self, messages, settings=None, params=None):
                yield TextDelta("par")
                raise ModelAPIError("cut mid-stream")

        fb = FallbackModelClient(MidFail(), EchoModelClient(name="backup"))
        got = []
        with pytest.raises(ModelAPIError, match="mid-stream"):
            async for event in fb.request_stream(MSGS):
                got.append(event)
        # the partial token reached the consumer exactly once (no dupes)
        assert [e.text for e in got if isinstance(e, TextDelta)] == ["par"]

    async def test_all_streams_failed_raises_exhausted(self):
        fb = FallbackModelClient(_failing("a"), _failing("b"))
        with pytest.raises(FallbackExhaustedError):
            async for _ in fb.request_stream(MSGS):
                pass


class TestAgentIntegration:
    async def test_agent_serves_through_fallback_and_mints_typed_fault(self):
        """End-to-end over the mesh: an agent on a fallback model answers
        via the backup; with all models down the client sees the typed
        mesh.model_error fault (the round-2 fault vocabulary)."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.exceptions import NodeFaultError
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.models import FaultTypes
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        fb = FallbackModelClient(_failing(), EchoModelClient(name="backup"))
        agent = Agent("resilient", model=fb)
        mesh = InMemoryMesh()
        async with Worker([agent], mesh=mesh, owns_transport=True):
            client = Client.connect(mesh)
            result = await client.agent("resilient").execute("ping", timeout=15)
            assert result.output == "echo: ping"
            await client.close()

        dead = FallbackModelClient(_failing("a"), _failing("b"))
        agent2 = Agent("doomed", model=dead)
        mesh2 = InMemoryMesh()
        async with Worker([agent2], mesh=mesh2, owns_transport=True):
            client = Client.connect(mesh2)
            with pytest.raises(NodeFaultError) as exc_info:
                await client.agent("doomed").execute("ping", timeout=15)
            assert exc_info.value.report.error_type == FaultTypes.MODEL_ERROR
            assert "fallback models failed" in exc_info.value.report.message
            await client.close()
