"""Node kernel: delivery pipeline, fault rail, fan-out fold/close, steps."""

import asyncio

import pytest

from calfkit_tpu import protocol
from calfkit_tpu.mesh import InMemoryMesh
from calfkit_tpu.models import (
    Call,
    DataPart,
    ErrorReport,
    FaultMessage,
    FaultTypes,
    Next,
    ReturnCall,
    ReturnMessage,
    TextPart,
    ToolCallStep,
    ToolResultStep,
)
from calfkit_tpu.models.marker import ToolCallMarker
from calfkit_tpu.models.payload import render_parts_as_text
from calfkit_tpu.exceptions import NodeFaultError
from calfkit_tpu.nodes import BaseNodeDef, ModelRetry, agent_tool, consumer, handler
from calfkit_tpu.nodes.steps import Observed, Said

from tests.kernel_harness import INBOX, Caller, deploy


@pytest.fixture
def mesh_and_caller():
    async def make():
        mesh = InMemoryMesh()
        await mesh.start()
        caller = Caller(mesh)
        await caller.start()
        return mesh, caller

    return make


# --------------------------------------------------------------------------- #
# scripted node kinds for kernel-level tests
# --------------------------------------------------------------------------- #


class ScriptedNode(BaseNodeDef):
    kind = "agent"

    def __init__(self, name, script, **kw):
        super().__init__(name, **kw)
        self.script = script  # async fn(ctx) -> NodeResult

    def input_topics(self):
        return [protocol.agent_input_topic(self.name)]

    def return_topic(self):
        return protocol.agent_return_topic(self.name)

    def publish_topic(self):
        return protocol.agent_publish_topic(self.name)

    @handler("run")
    async def run(self, ctx):
        return await self.script(ctx)


class TestToolRoundTrip:
    async def test_call_return(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        @agent_tool
        def get_weather(city: str) -> dict:
            """Weather lookup."""
            return {"city": city, "temp_c": 18.0}

        await deploy(mesh, get_weather)
        await caller.call(
            "tool.get_weather.input", [DataPart(data={"city": "SF"})]
        )
        headers, env = await caller.wait_reply()
        assert headers[protocol.HDR_KIND] == "return"
        assert isinstance(env.reply, ReturnMessage)
        assert env.reply.parts[0].data == {"city": "SF", "temp_c": 18.0}
        assert env.workflow.depth == 0  # frame unwound
        await mesh.stop()

    async def test_model_retry_becomes_retry_part(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        @agent_tool
        def fussy(x: int) -> str:
            raise ModelRetry("need a bigger x")

        await deploy(mesh, fussy)
        await caller.call("tool.fussy.input", [DataPart(data={"x": 1})])
        _, env = await caller.wait_reply()
        from calfkit_tpu.models import is_retry

        assert is_retry(env.reply.parts[0])
        assert "bigger x" in env.reply.parts[0].text
        await mesh.stop()

    async def test_bad_args_become_retry_not_fault(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        @agent_tool
        def typed(x: int) -> int:
            return x

        await deploy(mesh, typed)
        await caller.call("tool.typed.input", [DataPart(data={"x": "zzz"})])
        _, env = await caller.wait_reply()
        assert isinstance(env.reply, ReturnMessage)
        from calfkit_tpu.models import is_retry

        assert is_retry(env.reply.parts[0])
        await mesh.stop()

    async def test_tool_crash_faults_with_tag_echo(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        @agent_tool
        def boom() -> str:
            raise RuntimeError("kaboom")

        await deploy(mesh, boom)
        # tag on the frame must echo on the fault
        from calfkit_tpu.models import CallFrame, Envelope, WorkflowState
        from calfkit_tpu.keying import partition_key

        env = Envelope(
            workflow=WorkflowState(frames=[
                CallFrame(target_topic="tool.boom.input", callback_topic=INBOX,
                          tag="tc-9", marker=ToolCallMarker(tool_call_id="tc-9",
                                                            tool_name="boom"))
            ])
        )
        await mesh.publish("tool.boom.input", env.to_wire(), key=partition_key("t1"),
                           headers={protocol.HDR_KIND: "call", protocol.HDR_TASK: "t1"})
        headers, reply_env = await caller.wait_reply()
        assert headers[protocol.HDR_KIND] == "fault"
        assert headers[protocol.HDR_ERROR_TYPE] == FaultTypes.TOOL_ERROR
        assert isinstance(reply_env.reply, FaultMessage)
        assert reply_env.reply.tag == "tc-9"
        assert reply_env.reply.marker.tool_call_id == "tc-9"
        assert "kaboom" in reply_env.reply.report.message
        await mesh.stop()

    async def test_non_wire_safe_result_faults(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        @agent_tool
        def leaky() -> object:
            return object()

        await deploy(mesh, leaky)
        await caller.call("tool.leaky.input", [])
        headers, env = await caller.wait_reply()
        assert headers[protocol.HDR_KIND] == "fault"
        assert "wire-safe" in env.reply.report.message
        await mesh.stop()


class TestFaultRail:
    async def test_declined_reply_owing_autofaults(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def decline(ctx):
            return Next()

        node = ScriptedNode("decliner", decline)
        await deploy(mesh, node)
        await caller.call("agent.decliner.private.input", [TextPart(text="x")])
        headers, env = await caller.wait_reply()
        assert env.reply.report.error_type == FaultTypes.DECLINED
        await mesh.stop()

    async def test_minted_fault_propagates_type(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def mint(ctx):
            raise NodeFaultError(ErrorReport.build_safe(
                FaultTypes.CAPABILITY_UNAVAILABLE, "no such tool"))

        await deploy(mesh, ScriptedNode("minter", mint))
        await caller.call("agent.minter.private.input", [])
        headers, env = await caller.wait_reply()
        assert env.reply.report.error_type == FaultTypes.CAPABILITY_UNAVAILABLE
        await mesh.stop()

    async def test_on_node_error_recovers(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def broken(ctx):
            raise ValueError("internal")

        async def recover(ctx, report):
            return ReturnCall(parts=[TextPart(text=f"recovered:{report.error_type}")])

        node = ScriptedNode("healer", broken, on_node_error=[recover])
        await deploy(mesh, node)
        await caller.call("agent.healer.private.input", [])
        headers, env = await caller.wait_reply()
        assert headers[protocol.HDR_KIND] == "return"
        assert "recovered:mesh.node_error" in env.reply.parts[0].text
        await mesh.stop()

    async def test_callee_fault_escalates_through_caller(self, mesh_and_caller):
        """A calls B; B crashes; A has no recovery -> caller sees CALLEE_FAULT
        wrapping B's NODE_ERROR (the escalation ladder)."""
        mesh, caller = await mesh_and_caller()

        async def call_b(ctx):
            if ctx.delivery_kind == "call":
                return Call(target_topic="agent.b.private.input", route="run")
            pytest.fail("A should have escalated before re-entering body")

        async def crash(ctx):
            raise RuntimeError("B died")

        await deploy(mesh, ScriptedNode("a", call_b), ScriptedNode("b", crash))
        await caller.call("agent.a.private.input", [])
        headers, env = await caller.wait_reply()
        assert headers[protocol.HDR_KIND] == "fault"
        report = env.reply.report
        assert report.error_type == FaultTypes.CALLEE_FAULT
        assert report.causes and report.causes[0].error_type == FaultTypes.NODE_ERROR
        assert "B died" in report.root_cause().message
        await mesh.stop()

    async def test_on_callee_error_recovery_resumes_body(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()
        seen = {}

        async def call_then_return(ctx):
            if ctx.delivery_kind == "call":
                return Call(
                    target_topic="agent.b2.private.input",
                    route="run",
                    marker=ToolCallMarker(tool_call_id="t1", tool_name="b2"),
                )
            seen["resumed"] = True
            seen["tool_results"] = dict(ctx.state.tool_results)
            return ReturnCall(parts=[TextPart(text="done")])

        async def crash(ctx):
            raise RuntimeError("B died")

        async def substitute(ctx, report):
            return [TextPart(text="fallback-value")]

        await deploy(
            mesh,
            ScriptedNode("a2", call_then_return, on_callee_error=[substitute]),
            ScriptedNode("b2", crash),
        )
        await caller.call("agent.a2.private.input", [])
        headers, env = await caller.wait_reply()
        assert headers[protocol.HDR_KIND] == "return"
        assert env.reply.parts[0].text == "done"
        assert seen["resumed"]
        assert seen["tool_results"]["t1"].content == "fallback-value"
        await mesh.stop()

    async def test_oversized_fault_elides_state(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()
        mesh._max_bytes = 6000  # tiny wire budget

        async def crash(ctx):
            raise RuntimeError("x" * 20000)  # giant message → giant traceback

        await deploy(mesh, ScriptedNode("big", crash))
        from calfkit_tpu.models import State, ModelRequest, UserPart

        fat_state = State(message_history=[
            ModelRequest(parts=[UserPart(content="y" * 3000)])])
        await caller.call("agent.big.private.input", [], state=fat_state)
        headers, env = await caller.wait_reply()
        assert headers[protocol.HDR_KIND] == "fault"
        assert env.state_elided
        assert env.context.state.message_history == []
        assert env.reply.report.error_type == FaultTypes.NODE_ERROR
        await mesh.stop()


class TestFanout:
    async def test_open_fold_close_resumes_with_all_slots(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()
        resumed = {}

        async def fan(ctx):
            if ctx.delivery_kind == "call":
                return [
                    Call(target_topic="tool.double.input", route="run",
                         parts=[DataPart(data={"x": i})],
                         tag=f"tc-{i}",
                         marker=ToolCallMarker(tool_call_id=f"tc-{i}",
                                               tool_name="double"))
                    for i in range(3)
                ]
            resumed["tool_results"] = {
                k: v.content for k, v in ctx.state.tool_results.items()
            }
            return ReturnCall(parts=[TextPart(text="all-done")])

        @agent_tool
        def double(x: int) -> int:
            return x * 2

        await deploy(mesh, ScriptedNode("fan", fan), double)
        await caller.call("agent.fan.private.input", [])
        headers, env = await caller.wait_reply(timeout=10)
        assert env.reply.parts[0].text == "all-done"
        assert resumed["tool_results"] == {"tc-0": "0", "tc-1": "2", "tc-2": "4"}
        await mesh.stop()

    async def test_fanout_with_fault_aborts_batch(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def fan(ctx):
            if ctx.delivery_kind == "call":
                return [
                    Call(target_topic="tool.ok.input", route="run",
                         parts=[DataPart(data={})],
                         marker=ToolCallMarker(tool_call_id="t-ok", tool_name="ok")),
                    Call(target_topic="tool.bad.input", route="run",
                         parts=[DataPart(data={})],
                         marker=ToolCallMarker(tool_call_id="t-bad", tool_name="bad")),
                ]
            pytest.fail("must abort, not resume")

        @agent_tool
        def ok() -> str:
            return "fine"

        @agent_tool
        def bad() -> str:
            raise RuntimeError("sibling died")

        await deploy(mesh, ScriptedNode("fan2", fan), ok, bad)
        await caller.call("agent.fan2.private.input", [])
        headers, env = await caller.wait_reply(timeout=10)
        assert headers[protocol.HDR_KIND] == "fault"
        assert env.reply.report.error_type == FaultTypes.FANOUT_ABORTED
        assert "sibling died" in env.reply.report.root_cause().message
        await mesh.stop()

    async def test_duplicate_sibling_reply_is_idempotent(self, mesh_and_caller):
        """Replay a sibling reply record: the fold must classify duplicate and
        the batch must still close exactly once."""
        mesh, caller = await mesh_and_caller()
        resumes = []

        async def fan(ctx):
            if ctx.delivery_kind == "call":
                return [
                    Call(target_topic="tool.once.input", route="run",
                         parts=[DataPart(data={})],
                         marker=ToolCallMarker(tool_call_id="t1", tool_name="once")),
                    Call(target_topic="tool.twice.input", route="run",
                         parts=[DataPart(data={})],
                         marker=ToolCallMarker(tool_call_id="t2", tool_name="twice")),
                ]
            resumes.append(1)
            return ReturnCall(parts=[TextPart(text="closed")])

        @agent_tool
        def once() -> str:
            return "a"

        @agent_tool
        def twice() -> str:
            return "b"

        node = ScriptedNode("fan3", fan)
        await deploy(mesh, node, once, twice)
        await caller.call("agent.fan3.private.input", [])
        await caller.wait_reply(timeout=10)
        # replay every record that landed on fan3's return topic
        topic = mesh._topic("agent.fan3.private.return")
        records = [r for p in topic.partitions for r in p]
        for r in records:
            await mesh.publish(r.topic, r.value, key=r.key, headers=r.headers)
        await asyncio.sleep(0.3)
        assert len(resumes) == 1  # no double close, no double resume
        assert len(caller.replies) == 1
        await mesh.stop()


class TestStepsAndMirror:
    async def test_steps_flush_to_root_callback(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def speak(ctx):
            return Observed(
                action=ReturnCall(parts=[TextPart(text="hi")]),
                facts=[Said(text="hi", author="speaker")],
            )

        await deploy(mesh, ScriptedNode("speaker", speak))
        await caller.call("agent.speaker.private.input", [])
        await caller.wait_reply()
        await asyncio.sleep(0.1)
        assert caller.steps, "no StepMessage reached the root callback"
        steps = caller.steps[0].steps
        assert steps[0].kind == "agent_message" and steps[0].text == "hi"
        await mesh.stop()

    async def test_tool_call_step_pair_minted(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def one_call(ctx):
            if ctx.delivery_kind == "call":
                return Call(target_topic="tool.t.input", route="run",
                            parts=[DataPart(data={"tool_name": "t", "args": {}})],
                            marker=ToolCallMarker(tool_call_id="tc", tool_name="t"))
            return ReturnCall(parts=[TextPart(text="fin")])

        @agent_tool(name="t")
        def t() -> str:
            return "res"

        await deploy(mesh, ScriptedNode("pairs", one_call), t)
        await caller.call("agent.pairs.private.input", [])
        await caller.wait_reply()
        await asyncio.sleep(0.2)
        kinds = [s.kind for m in caller.steps for s in m.steps]
        assert "tool_call" in kinds and "tool_result" in kinds
        await mesh.stop()

    async def test_broadcast_mirror(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def simple(ctx):
            return ReturnCall(parts=[TextPart(text="ok")])

        node = ScriptedNode("mirrored", simple)
        await deploy(mesh, node)
        mirrored = []

        async def tap(record):
            mirrored.append(record)

        await mesh.subscribe(["agent.mirrored.events"], tap, group_id=None,
                             from_latest=False, ordered=False)
        await caller.call("agent.mirrored.private.input", [])
        await caller.wait_reply()
        await asyncio.sleep(0.1)
        assert mirrored, "hop outcome not mirrored to publish topic"
        await mesh.stop()


class TestConsumer:
    async def test_observes_without_replying(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()
        seen = []

        @consumer(topics=["agent.obs.events"])
        async def observer(cctx):
            seen.append((cctx.topic, cctx.envelope is not None))

        async def simple(ctx):
            return ReturnCall(parts=[TextPart(text="ok")])

        await deploy(mesh, ScriptedNode("obs", simple), observer)
        await caller.call("agent.obs.private.input", [])
        await caller.wait_reply()
        await asyncio.sleep(0.1)
        assert seen and seen[0][0] == "agent.obs.events" and seen[0][1]
        assert len(caller.replies) == 1  # consumer added no traffic to caller
        await mesh.stop()

    async def test_consumer_error_floor(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        @consumer(topics=["agent.obs2.events"], name="obs2c")
        async def observer(cctx):
            raise RuntimeError("observer bug")

        async def simple(ctx):
            return ReturnCall(parts=[TextPart(text="ok")])

        await deploy(mesh, ScriptedNode("obs2", simple), observer)
        await caller.call("agent.obs2.private.input", [])
        _, env = await caller.wait_reply()
        assert env.reply.parts[0].text == "ok"  # run unaffected
        await mesh.stop()


class TestReviewRegressions:
    """Regressions for reproduced review findings."""

    async def test_empty_list_action_declines_not_strands(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def empty(ctx):
            return []  # zero tool calls: must not open an uncloseable batch

        from tests.kernel_harness import deploy as _deploy
        await _deploy(mesh, ScriptedNode("empty", empty))
        await caller.call("agent.empty.private.input", [TextPart(text="x")])
        headers, env = await caller.wait_reply()
        assert env.reply.report.error_type == FaultTypes.DECLINED
        await mesh.stop()

    async def test_failed_recovery_publishes_original_fault(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def broken(ctx):
            raise ValueError("original failure")

        async def bad_recovery(ctx, report):
            return [object()]  # not Calls: recovery publish raises

        node = ScriptedNode("badheal", broken, on_node_error=[bad_recovery])
        from tests.kernel_harness import deploy as _deploy
        await _deploy(mesh, node)
        await caller.call("agent.badheal.private.input", [])
        headers, env = await caller.wait_reply()
        assert headers[protocol.HDR_KIND] == "fault"
        assert "original failure" in env.reply.report.message
        await mesh.stop()

    async def test_close_hop_steps_reach_root(self, mesh_and_caller):
        mesh, caller = await mesh_and_caller()

        async def fan(ctx):
            if ctx.delivery_kind == "call":
                return [
                    Call(target_topic="tool.s1.input", route="run",
                         parts=[DataPart(data={})],
                         marker=ToolCallMarker(tool_call_id="a", tool_name="s1")),
                    Call(target_topic="tool.s2.input", route="run",
                         parts=[DataPart(data={})],
                         marker=ToolCallMarker(tool_call_id="b", tool_name="s2")),
                ]
            return Observed(action=ReturnCall(parts=[TextPart(text="done")]),
                            facts=[Said(text="closing words")])

        @agent_tool(name="s1")
        def s1() -> str:
            return "1"

        @agent_tool(name="s2")
        def s2() -> str:
            return "2"

        from tests.kernel_harness import deploy as _deploy
        await _deploy(mesh, ScriptedNode("fanstep", fan), s1, s2)
        await caller.call("agent.fanstep.private.input", [])
        await caller.wait_reply(timeout=10)
        await asyncio.sleep(0.2)
        texts = [s.text for m in caller.steps for s in m.steps
                 if s.kind == "agent_message"]
        assert "closing words" in texts  # close-hop facts must stream
        await mesh.stop()


class TestFanoutTuning:
    async def test_fanout_config_threads_to_store_timeouts(self):
        """Worker(fanout=FanoutConfig) bounds catch-up and barriers
        (reference: tuning.py KTableReaderTuning/FanoutConfig)."""
        from calfkit_tpu.tuning import FanoutConfig, TableTuning

        seen: dict[str, float] = {}

        class SpyReader:
            def __init__(self, inner):
                self._inner = inner

            async def start(self, *, timeout=30.0):
                seen["catchup"] = timeout
                await self._inner.start(timeout=timeout)

            async def barrier(self, *, timeout=30.0):
                seen["barrier"] = timeout
                await self._inner.barrier(timeout=timeout)

            def __getattr__(self, name):
                return getattr(self._inner, name)

        class SpyMesh(InMemoryMesh):
            def table_reader(self, topic):
                return SpyReader(super().table_reader(topic))

        from calfkit_tpu.nodes.fanout_store import KtablesFanoutBatchStore

        mesh = SpyMesh()
        await mesh.start()
        config = FanoutConfig(
            table=TableTuning(catchup_timeout_s=7.5, barrier_timeout_s=3.25)
        )
        store = KtablesFanoutBatchStore(mesh, "agent.tuned", config)
        await store.start()
        assert seen["catchup"] == 7.5
        await store.load("nonexistent")
        assert seen["barrier"] == 3.25
        await store.stop()
        await mesh.stop()

    def test_worker_rejects_wrong_fanout_type(self):
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.exceptions import LifecycleConfigError
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        agent = Agent("t", model=TestModelClient())
        with pytest.raises(LifecycleConfigError, match="FanoutConfig"):
            Worker([agent], mesh=InMemoryMesh(), fanout={"nope": 1})
