"""Capacity observatory suite (ISSUE 19).

Five law groups and one end-to-end acceptance drill:

- :class:`PageLedger` attribution laws: the in-use identity
  (``private + shared = in use``), transfer/acquire/release refcount
  mirroring, eviction accounting, and the never-fault tolerance for
  pages the ledger has not seen;
- :class:`CapacitySampler` ring laws (flightrec's discipline: power-of
  two capacity, counted overflow, 0=off) plus the JSONL dump/parse
  round trip and the ``GET /capacity`` endpoint;
- the ``ck capacity`` render functions and the fleet table's HEADROOM
  column (pure, no mesh required);
- the advert half: :attr:`Replica.headroom_pages` None-vs-zero
  semantics;
- THE acceptance drill: a REAL debug paged engine serves requests with
  sampling on, ``stats_snapshot()["capacity"]`` attributes live pages,
  the dump renders a timeline + breakdown through the CLI renderers,
  and after drain the ledger attributes every page to NO owner
  (:func:`assert_engine_drained`'s attribution oracle);
- the sim half: the ``capacity_churn`` geometry under pressure — pool
  bites (evictions), no page leak, deterministic capacity metrics.
"""

from __future__ import annotations

import asyncio
import json
import os

import jax

jax.config.update("jax_platforms", "cpu")

import pytest

from calfkit_tpu.cli.obs import (
    render_capacity_breakdown,
    render_capacity_table,
    render_capacity_timeline,
    render_fleet_table,
    sparkline,
)
from calfkit_tpu.models.records import EngineStatsRecord
from calfkit_tpu.observability import capacity
from calfkit_tpu.observability.capacity import (
    SAMPLE_FIELDS,
    CapacitySampler,
    PageLedger,
    hbm_bytes_per_token,
    hbm_constants,
    lane_kind,
)


# -------------------------------------------------------------- ledger laws
class TestPageLedgerLaws:
    def test_alloc_free_balance_and_idempotence(self):
        ledger = PageLedger(64)
        ledger.alloc(3, 5, "corr-a", "run-a", "decode")
        ledger.alloc(7, 2, "corr-b", None, "spec")
        assert ledger.pages_in_use == 7
        assert ledger.headroom_pages == 57
        # re-alloc of a live slot REPLACES its grant (admission retry),
        # never double-counts
        ledger.alloc(3, 4, "corr-a2", None, "decode")
        assert ledger.pages_in_use == 6
        ledger.free(3)
        ledger.free(3)  # idempotent, like PageAllocator.free
        ledger.free(99)  # unknown slot: tolerated, never a fault
        ledger.free(7)
        assert ledger.pages_in_use == 0

    def test_transfer_moves_private_to_chain_at_refcount_one(self):
        ledger = PageLedger(32)
        ledger.alloc(0, 6, "corr-a", "run-a", "decode")
        ledger.transfer(0, [10, 11, 12], [b"h1", b"h1", b"h1"])
        # in-use total unchanged: the registering request still holds the
        # pages, just as shared instead of private
        assert ledger.pages_in_use == 6
        assert ledger.prefix_resident_pages == 3
        bd = ledger.breakdown()
        assert bd["private_pages"] == 3
        assert bd["shared_referenced_pages"] == 3
        # release to zero-ref: resident but NOT in use (evictable on
        # demand = headroom)
        ledger.release([10, 11, 12])
        ledger.free(0)
        assert ledger.pages_in_use == 0
        assert ledger.prefix_resident_pages == 3
        assert ledger.headroom_pages == 32

    def test_acquire_release_refcounts_and_tolerance(self):
        ledger = PageLedger(16)
        ledger.alloc(0, 2, "c", None, "decode")
        ledger.transfer(0, [1, 2], [b"h", b"h"])
        ledger.release([1, 2])
        assert ledger.pages_in_use == 0
        ledger.acquire([1, 2, 999])  # 999 not chain-owned: skipped
        assert ledger.pages_in_use == 2
        ledger.acquire([1])
        ledger.release([1])
        assert ledger.pages_in_use == 2  # still one holder of page 1
        ledger.release([1, 2])
        ledger.release([1, 2])  # below zero: clamped, never negative
        assert ledger.pages_in_use == 0

    def test_eviction_accounting(self):
        ledger = PageLedger(8)
        ledger.alloc(0, 3, "c", None, "decode")
        ledger.transfer(0, [5, 6, 7], [b"x", b"x", b"x"])
        ledger.release([5, 6, 7])
        ledger.evicted(5)
        ledger.evicted(5)  # already gone: tolerated, counted once
        ledger.evicted(42)  # never chain-owned: tolerated
        assert ledger.evicted_pages == 1
        assert ledger.prefix_resident_pages == 2
        ledger.note_stall()
        assert ledger.alloc_stalls == 1
        # evicting a REFERENCED page (forced reclaim) drops in-use too
        ledger.acquire([6])
        assert ledger.pages_in_use == 1
        ledger.evicted(6)
        assert ledger.pages_in_use == 0

    def test_breakdown_caps_rows_and_counts_remainder(self):
        ledger = PageLedger(128)
        for slot in range(10):
            ledger.alloc(slot, slot + 1, f"corr-{slot}", None, "decode")
        bd = ledger.breakdown(top=3)
        # top owners by pages desc, remainder summed — never silent
        assert [o["pages"] for o in bd["by_owner"]] == [10, 9, 8]
        assert bd["by_owner_other_pages"] == sum(range(1, 8))
        assert bd["pages_in_use"] == sum(range(1, 11))
        assert bd["by_lane"]["decode"] == bd["pages_in_use"]

    def test_breakdown_lane_and_chain_rollups(self):
        ledger = PageLedger(64)
        ledger.alloc(0, 4, "c0", "run-a", "long")
        ledger.alloc(1, 2, "c1", None, "spec")
        ledger.transfer(0, [1, 2], [b"\xaa\xbb", b"\xaa\xbb"])
        bd = ledger.breakdown()
        assert bd["by_lane"] == {"long": 2, "spec": 2, "shared": 2}
        assert bd["by_chain"][0]["chain"] == "aabb"  # bytes render hex
        assert bd["by_chain"][0]["refs"] == 1
        # owner rows carry the run tag for `ck capacity` attribution
        assert any(o["run"] == "run-a" for o in bd["by_owner"])

    def test_lane_kind_vocabulary(self):
        assert lane_kind() == "decode"
        assert lane_kind(history=object()) == "spec"
        assert lane_kind(long_lane=True) == "long"

    def test_hbm_model_agrees_with_roofline_shape(self):
        class M:
            param_count = 1_000_000
            n_layers = 4
            n_kv_heads = 2
            head_dim = 64

        weight_bytes, kv_per_token = hbm_constants(M())
        assert weight_bytes == 2_000_000.0  # bf16
        assert hbm_constants(M(), "int8")[0] == 1_000_000.0
        assert kv_per_token == 2.0 * 4 * 2 * 64 * 2.0
        # amortization law: doubling the batch halves the weight share
        one = hbm_bytes_per_token((weight_bytes, kv_per_token), 128.0, 1.0)
        two = hbm_bytes_per_token((weight_bytes, kv_per_token), 128.0, 2.0)
        assert one - kv_per_token * 128.0 == pytest.approx(
            2 * (two - kv_per_token * 128.0)
        )


# ------------------------------------------------------------- sampler ring
class TestCapacitySamplerRing:
    def test_capacity_rounds_to_power_of_two_and_overflow_counts(self):
        sampler = CapacitySampler(10, label="ring")
        assert sampler.capacity == 16
        for i in range(36):
            sampler.append(i, 0, 0, 0, 0, 0.0, 0.0, t=float(i))
        counts = sampler.counts()
        assert counts["appended"] == 36
        assert counts["dropped"] == 20  # overwritten, counted — not silent
        # the ring keeps the NEWEST samples, ordered by sequence
        assert [e[0] for e in sampler.snapshot()] == list(range(20, 36))

    def test_zero_capacity_disables_and_stays_unregistered(self):
        sampler = CapacitySampler(0, label="off")
        sampler.append(1, 2, 3, 4, 5, 6.0, 7.0)
        assert sampler.snapshot() == []
        assert sampler.counts() == {"appended": 0, "dropped": 0, "dumped": 0}
        assert sampler not in capacity.samplers()

    def test_negative_capacity_rejected(self):
        with pytest.raises(ValueError):
            CapacitySampler(-1)

    def test_dump_parse_round_trip_with_breakdown(self):
        ledger = PageLedger(32)
        ledger.alloc(0, 4, "corr-x", "run-x", "decode")
        sampler = CapacitySampler(
            8, label="rt", ledger=ledger, wall_anchor=False
        )
        sampler.append(4, 28, 0, 1, 0, 32.0, 1.5, t=10.0)
        sampler.append(6, 26, 2, 2, 1, 32.0, 1.5, t=11.0)
        meta, samples = capacity.parse_dump(
            sampler.dump_lines(reason="test")
        )
        assert meta["label"] == "rt" and meta["reason"] == "test"
        assert meta["fields"] == list(SAMPLE_FIELDS)
        assert meta["appended"] == 2 and meta["dropped"] == 0
        # the attached ledger's attribution snapshot rides the header
        assert meta["breakdown"]["pages_in_use"] == 4
        assert [s["pages_in_use"] for s in samples] == [4, 6]
        assert samples[0]["t_s"] == 10.0  # wall_anchor=False: virtual time
        assert samples[1]["hbm_bytes_per_token"] == 1.5

    def test_parse_dump_skips_garbage(self):
        good = {"seq": 1, "t_s": 1.0, "pages_in_use": 3}
        meta, samples = capacity.parse_dump(
            ["not json", "", "[1,2]", json.dumps({"capacity": {"label": "x"}}),
             json.dumps({"seq": "no"}), json.dumps(good)]
        )
        assert meta == {"label": "x"}
        assert [s["pages_in_use"] for s in samples] == [3]

    def test_dump_writes_capacity_prefixed_file(self, tmp_path, monkeypatch):
        monkeypatch.setenv("CALFKIT_FLIGHTREC_DIR", str(tmp_path))
        sampler = CapacitySampler(4, label="disk")
        sampler.append(1, 3, 0, 1, 0, 8.0, 0.0)
        path = sampler.dump(reason="test")
        assert os.path.basename(path).startswith("capacity-disk-")
        with open(path) as f:
            meta, samples = capacity.parse_dump(f)
        assert meta["label"] == "disk" and len(samples) == 1
        assert sampler.counts()["dumped"] == 1

    def test_dump_all_text_concatenates_registered(self):
        a = CapacitySampler(4, label="all-a")
        b = CapacitySampler(4, label="all-b")
        a.append(1, 0, 0, 0, 0, 0.0, 0.0, t=1.0)
        b.append(2, 0, 0, 0, 0, 0.0, 0.0, t=1.0)
        text = capacity.dump_all_text(reason="test")
        labels = {
            json.loads(line)["capacity"]["label"]
            for line in text.splitlines()
            if '"capacity"' in line and "all-" in line
        }
        assert {"all-a", "all-b"} <= labels
        assert a.dumped == 1 and b.dumped == 1

    async def test_capacity_endpoint_serves_ndjson(self):
        from calfkit_tpu.observability.http import MetricsServer

        sampler = CapacitySampler(8, label="http-cap")
        sampler.append(3, 5, 1, 2, 0, 16.0, 0.0, t=1.0)

        async def get(port: int, path: str) -> tuple[str, str]:
            reader, writer = await asyncio.open_connection("127.0.0.1", port)
            writer.write(f"GET {path} HTTP/1.0\r\n\r\n".encode())
            await writer.drain()
            raw = await reader.read(262144)
            writer.close()
            head, _, body = raw.decode().partition("\r\n\r\n")
            return head.splitlines()[0], body

        async with MetricsServer(port=0) as server:
            status, body = await get(server.port, "/capacity")
        assert status == "HTTP/1.0 200 OK"
        ours = [
            line for line in body.splitlines() if '"http-cap"' in line
        ]
        assert ours, "endpoint body missing our sampler's header"
        assert sampler.dumped == 1


# ---------------------------------------------------------------- renders
def _replica(pages_total=0, pages_in_use=0, **stats_kw):
    """A minimal Replica via the real record (the renderer's input)."""
    from calfkit_tpu.fleet.registry import Replica

    stats = EngineStatsRecord(
        node_id="agent.svc", model_name="debug", instance_id="i0",
        pages_total=pages_total, pages_in_use=pages_in_use, **stats_kw,
    )
    return Replica(
        key="agent.svc@i0", node_id="agent.svc", instance_id="i0",
        heartbeat_at=100.0, stats=stats,
    )


class TestCapacityRenderers:
    def test_sparkline_laws(self):
        assert sparkline([]) == ""
        assert sparkline([0, 0, 0]) == "▁▁▁"  # drained = flat, not empty
        line = sparkline([0, 4, 8])
        assert line[-1] == "█" and line[0] == "▁"
        assert len(sparkline(range(100), width=60)) == 60

    def test_capacity_table_rows_and_dense_dashes(self):
        paged = _replica(
            pages_total=64, pages_in_use=40, prefix_resident_pages=12,
            evictions_window=3, alloc_stalls=1,
        )
        dense = _replica()  # no pool: dashes, never zeros
        out = render_capacity_table([paged, dense])
        assert "HEADROOM" in out and "STALLS" in out
        row = next(line for line in out.splitlines() if " 64 " in line)
        assert " 40 " in row and " 24 " in row and " 12 " in row
        assert any(
            line.count("-") >= 6 for line in out.splitlines()
        ), "dense replica must render dashes across the page columns"
        assert "no advertised replicas" in render_capacity_table([])

    def test_fleet_table_headroom_column(self):
        out = render_fleet_table(
            [
                _replica(pages_total=64, pages_in_use=40, ready=True),
                _replica(ready=True),
            ],
            stale_after=15.0,
            now=100.0,
        )
        lines = out.splitlines()
        # the table is column-aligned: slice each row at the header's
        # HEADROOM offset (multi-word headers make split() unusable)
        idx = lines[0].index("HEADROOM")
        assert lines[1][idx:].split()[0] == "24"
        assert lines[2][idx:].split()[0] == "-"  # no pool ≠ zero headroom

    def test_breakdown_render(self):
        ledger = PageLedger(32)
        ledger.alloc(0, 5, "corr-aaa", "run-bbb", "decode")
        ledger.transfer(0, [1, 2], [b"\xab\xcd", b"\xab\xcd"])
        ledger.note_stall()
        out = render_capacity_breakdown(ledger.breakdown())
        assert "pages 5/32 in use" in out
        assert "(private 3 + shared 2; resident 2)" in out
        assert "headroom 27" in out and "stalls 1" in out
        assert "corr-aaa" in out and "run-bbb" in out
        assert "lanes" in out and "shared=2" in out
        assert "abcd×1" in out

    def test_timeline_render(self):
        sampler = CapacitySampler(8, label="tl", wall_anchor=False)
        sampler.append(4, 28, 0, 1, 0, 32.0, 0.0, t=1.0)
        sampler.append(8, 24, 2, 2, 1, 32.0, 0.0, t=2.0)
        meta, samples = capacity.parse_dump(sampler.dump_lines())
        out = render_capacity_timeline(meta, samples)
        assert "capacity tl" in out and "2 samples" in out
        for field in SAMPLE_FIELDS:
            assert field in out
        assert "max 8" in out and "last 8" in out
        assert "no capacity samples" in render_capacity_timeline(None, [])

    def test_newest_dump_ignores_flightrec_files(self, tmp_path):
        from calfkit_tpu.cli.obs import _newest_capacity_dump

        (tmp_path / "flightrec-engine-1.jsonl").write_text("{}\n")
        assert _newest_capacity_dump(str(tmp_path)) is None
        target = tmp_path / "capacity-engine-1.jsonl"
        target.write_text("{}\n")
        assert _newest_capacity_dump(str(tmp_path)) == str(target)


# ------------------------------------------------------------- end to end
class TestCapacityEngineE2E:
    def _engine(self, **overrides):
        from calfkit_tpu.inference.config import RuntimeConfig, preset
        from calfkit_tpu.inference.engine import InferenceEngine

        rt = RuntimeConfig(
            max_batch_size=4, max_seq_len=256, kv_layout="paged",
            chunked_prefill=True, prefill_chunk=32, page_size=16,
            decode_steps_per_dispatch=4, **overrides,
        )
        return InferenceEngine(preset("debug"), rt)

    async def test_live_attribution_then_drained_attributes_nothing(
        self, tmp_path, monkeypatch
    ):
        """THE ISSUE 19 acceptance drill: a REAL debug engine with
        sampling on serves concurrent requests; mid-flight the snapshot
        attributes pages to live owners; the dump renders a timeline +
        breakdown through the `ck capacity` renderers; after drain the
        ledger attributes every page to NO owner."""
        from calfkit_tpu.inference.client import JaxLocalModelClient
        from calfkit_tpu.sim.chaos import assert_engine_drained

        monkeypatch.setenv("CALFKIT_FLIGHTREC_DIR", str(tmp_path))
        engine = self._engine(capacity_samples=64)
        client = JaxLocalModelClient(engine=engine)
        await engine.start()
        peak = {"pages": 0, "snap": None}

        async def one(i: int) -> int:
            n = 0
            async for _ in engine.generate(
                list(range(1, 24)), max_new_tokens=12, corr=f"req-{i}"
            ):
                n += 1
                if engine._ledger.pages_in_use > peak["pages"]:
                    peak["pages"] = engine._ledger.pages_in_use
                    peak["snap"] = client.stats_snapshot()
            return n

        outs = await asyncio.gather(*[one(i) for i in range(3)])
        assert all(n == 12 for n in outs)

        # ---- mid-flight: pages attributed to live request owners
        assert peak["pages"] > 0
        snap = peak["snap"]
        assert snap["pages_total"] == engine._ledger.pages_total > 0
        assert snap["pages_in_use"] > 0
        bd = snap["capacity"]
        assert bd["pages_in_use"] == snap["pages_in_use"]
        owners = {o["corr"] for o in bd["by_owner"]}
        assert any(corr and corr.startswith("req-") for corr in owners)

        # ---- the sampler recorded one sample per dispatch landing
        # (read AFTER the gather: the landing's append can race the
        # consumer's mid-stream snapshot by one tick)
        assert client.stats_snapshot()["capacity_samples"]["appended"] > 0
        path = engine._sampler.dump(reason="test")
        await engine.stop()
        with open(path) as f:
            meta, samples = capacity.parse_dump(f)
        assert samples, "dump carried no samples"
        assert max(s["pages_in_use"] for s in samples) > 0
        out = render_capacity_timeline(meta, samples)
        assert "pages_in_use" in out and "▁" in out or "█" in out
        assert render_capacity_breakdown(meta["breakdown"])

        # ---- drained: every page back, attributed to no one
        assert_engine_drained(engine)
        assert engine._ledger.pages_in_use == 0
        final = client.stats_snapshot()
        assert final["pages_in_use"] == 0
        assert final["capacity"]["by_owner"] == []

    async def test_sampling_off_is_default_and_records_nothing(self):
        engine = self._engine()  # capacity_samples defaults to 0
        await engine.start()
        async for _ in engine.generate([1, 2, 3], max_new_tokens=4):
            pass
        assert engine._sampler.counts()["appended"] == 0
        # attribution still runs (always on for paged): the ledger saw
        # the request come and go
        assert engine._ledger.pages_in_use == 0
        assert engine._ledger.pages_total > 0
        await engine.stop()

    async def test_cold_snapshot_carries_capacity_keys(self):
        from calfkit_tpu.inference.client import JaxLocalModelClient
        from calfkit_tpu.inference.config import RuntimeConfig

        cold = JaxLocalModelClient(
            config="debug",
            runtime=RuntimeConfig(
                max_batch_size=4, max_seq_len=256, kv_layout="paged",
                page_size=16,
            ),
        )
        snap = cold.stats_snapshot()
        assert snap["pages_total"] > 0 and snap["pages_in_use"] == 0
        assert snap["capacity"]["headroom_pages"] == snap["pages_total"]
        assert snap["capacity_samples"] == {
            "appended": 0, "dropped": 0, "dumped": 0,
        }
        # the advert record accepts the snapshot wholesale
        record = EngineStatsRecord(node_id="agent.x", **snap)
        assert record.pages_total == snap["pages_total"]

    def test_capacity_churn_scaled_pressures_pool_without_leaking(self):
        """The sim half: the pinned geometry under 0.15 scale still
        bites the pool (evictions observed), stays leak-free (residual
        attribution zero after drain), samples the timeline, and its
        capacity metrics are deterministic."""
        from calfkit_tpu.sim import SimRunner
        from calfkit_tpu.sim.suite import CAPACITY_CHURN

        scenario = CAPACITY_CHURN.scaled(0.15)

        def run():
            return asyncio.run(SimRunner(scenario).run())

        a, b = run(), run()
        assert a.passed, [c for c in a.checks if not c.ok]
        cap = a.metrics["capacity"]
        assert cap["pages_total"] > 0
        assert cap["evicted_pages"] >= 1  # the pool actually churned
        assert cap["peak_pages_in_use"] >= 1
        assert cap["residual_pages_in_use"] == 0  # the leak oracle
        assert cap["samples"] >= 1
        # prefix churn is VISIBLE: evictions cost hit rate by design
        assert a.metrics["prefix"]["hit_rate"] < 0.95
        assert a.metrics["capacity"] == b.metrics["capacity"]
