"""kafkad restart semantics (VERDICT r4 item 5).

kafkad is memory-only: a restart loses offsets, records, and compacted
tables.  The pinned contract is that consumers observe this as a LOUD
reset (OFFSET_OUT_OF_RANGE → warning log → re-resolve) or a clean
rejoin — never a silent forever-stall — and the mesh keeps working for
traffic produced after the restart.
"""

from __future__ import annotations

import asyncio
import logging
import socket

import pytest

from calfkit_tpu.mesh.kafka_wire import (
    ERR_OFFSET_OUT_OF_RANGE,
    KafkaWireClient,
    KafkaWireMesh,
    decode_record_batches,
    encode_record_batch,
    find_kafkad,
    spawn_kafkad,
)

pytestmark = pytest.mark.skipif(
    find_kafkad() is None, reason="kafkad not built (make -C native)"
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestOffsetBeyondLog:
    def test_fetch_past_log_end_is_out_of_range(self):
        """A position beyond the high watermark (the restart signature)
        answers OFFSET_OUT_OF_RANGE, not a silent empty long-poll."""

        async def run(port: int) -> None:
            client = KafkaWireClient("127.0.0.1", port)
            try:
                await client.create_topics(["oor"], 1)
                await client.produce(
                    "oor", 0, encode_record_batch([(b"k", b"v", [])], 1)
                )
                results = await client.fetch([("oor", 0, 5)], max_wait_ms=50)
                assert results[0][2] == ERR_OFFSET_OUT_OF_RANGE
                # caught-up position (== hwm) stays the normal quiet wait
                results = await client.fetch([("oor", 0, 1)], max_wait_ms=50)
                assert results[0][2] == 0 and results[0][3] == b""
            finally:
                await client.close()

        proc = spawn_kafkad(0)
        try:
            asyncio.run(run(proc.kafkad_port))
        finally:
            proc.terminate()
            proc.wait(timeout=5)


class TestBrokerRestart:
    def test_consumer_survives_restart_with_loud_reset(self, caplog):
        """Kill + restart the broker under a live subscription: the
        consumer must log the reset and deliver post-restart traffic."""
        port = _free_port()
        proc = spawn_kafkad(port)

        async def run() -> None:
            nonlocal proc
            mesh = KafkaWireMesh(f"127.0.0.1:{port}")
            await mesh.start()
            got: list[bytes] = []
            arrived = asyncio.Event()

            async def handler(rec):
                got.append(rec.value)
                arrived.set()

            try:
                await mesh.ensure_topics(["restart.topic"])
                sub = await mesh.subscribe(
                    ["restart.topic"], handler, group_id="restart-g"
                )
                await mesh.publish("restart.topic", b"before", key=b"k")
                await asyncio.wait_for(arrived.wait(), 15)
                assert got == [b"before"]
                arrived.clear()

                # hard-kill and restart on the SAME port: memory-only log
                # is gone, group state is gone
                proc.kill()
                proc.wait(timeout=5)
                proc = spawn_kafkad(port)

                # publish resumes (producer reconnects; retry during the
                # startup race) and the consumer must receive it
                deadline = asyncio.get_running_loop().time() + 30
                while True:
                    try:
                        await mesh.publish(
                            "restart.topic", b"after", key=b"k"
                        )
                        break
                    except Exception:  # noqa: BLE001 — broker coming up
                        if asyncio.get_running_loop().time() > deadline:
                            raise
                        await asyncio.sleep(0.3)
                await asyncio.wait_for(arrived.wait(), 30)
                assert got[-1] == b"after"
                await sub.stop()
            finally:
                await mesh.stop()

        with caplog.at_level(logging.WARNING, logger="calfkit_tpu.mesh.kafka_wire"):
            try:
                asyncio.run(run())
            finally:
                proc.terminate()
                proc.wait(timeout=5)
        # the loss was LOUD: either the group rejoined (join logs nothing
        # but positions came from a fresh world) or the tap/fetch path
        # warned about the rewind; at minimum the consumer-error retry or
        # out-of-range warning must have fired
        assert any(
            "out of range" in rec.message or "consumer error" in rec.message
            or "heartbeat" in rec.message
            for rec in caplog.records
        ), [rec.message for rec in caplog.records]

    def test_wal_makes_restart_lossless(self, tmp_path):
        """With --log-dir, records + committed offsets survive the
        restart: the consumer resumes exactly where it left off and
        nothing is redelivered or lost."""
        port = _free_port()
        proc = spawn_kafkad(port, log_dir=str(tmp_path))

        async def run() -> None:
            nonlocal proc
            mesh = KafkaWireMesh(f"127.0.0.1:{port}")
            await mesh.start()
            got: list[bytes] = []
            arrived = asyncio.Event()

            async def handler(rec):
                got.append(rec.value)
                arrived.set()

            try:
                await mesh.ensure_topics(["wal.topic"])
                sub = await mesh.subscribe(
                    ["wal.topic"], handler, group_id="wal-g"
                )
                await mesh.publish("wal.topic", b"one", key=b"k")
                await asyncio.wait_for(arrived.wait(), 15)
                arrived.clear()
                # let the ACK-first auto-commit land before the kill
                await asyncio.sleep(1.5)

                proc.kill()
                proc.wait(timeout=5)
                proc = spawn_kafkad(port, log_dir=str(tmp_path))

                deadline = asyncio.get_running_loop().time() + 30
                while True:
                    try:
                        await mesh.publish("wal.topic", b"two", key=b"k")
                        break
                    except Exception:  # noqa: BLE001
                        if asyncio.get_running_loop().time() > deadline:
                            raise
                        await asyncio.sleep(0.3)
                await asyncio.wait_for(arrived.wait(), 30)
                # no loss AND no redelivery: the committed offset survived
                assert got == [b"one", b"two"], got
                await sub.stop()
            finally:
                await mesh.stop()

        try:
            asyncio.run(run())
        finally:
            proc.terminate()
            proc.wait(timeout=5)

    def test_wal_survives_torn_tail(self, tmp_path):
        """A crash mid-append leaves a torn frame; replay must stop at
        the last good frame and the broker must serve normally."""
        port = _free_port()
        proc = spawn_kafkad(port, log_dir=str(tmp_path))

        async def seed() -> None:
            client = KafkaWireClient("127.0.0.1", port)
            try:
                await client.create_topics(["torn"], 1)
                await client.produce(
                    "torn", 0, encode_record_batch([(b"k", b"kept", [])], 1)
                )
            finally:
                await client.close()

        asyncio.run(seed())
        proc.kill()
        proc.wait(timeout=5)
        with open(tmp_path / "wal.log", "ab") as wal:
            wal.write(b"\x00\x00\x00\x20TORNFRAME")  # length promises more

        proc = spawn_kafkad(port, log_dir=str(tmp_path))

        async def check(expect: list[bytes], *, produce: bytes | None) -> None:
            client = KafkaWireClient("127.0.0.1", port)
            try:
                results = await client.fetch([("torn", 0, 0)], max_wait_ms=200)
                from calfkit_tpu.mesh.kafka_wire import decode_record_batches

                records = decode_record_batches(results[0][3])
                assert [v for *_x, v, _h in records] == expect
                if produce is not None:
                    await client.produce(
                        "torn", 0,
                        encode_record_batch([(b"k", produce, [])], 2),
                    )
            finally:
                await client.close()

        try:
            # restart 1: tail truncated, pre-crash record intact, and a
            # POST-crash write lands after the cut...
            asyncio.run(check([b"kept"], produce=b"after-crash"))
            proc.terminate()
            proc.wait(timeout=5)
            # ...restart 2: the post-crash write SURVIVES (the torn tail
            # was cut, not appended after — review finding r5)
            proc = spawn_kafkad(port, log_dir=str(tmp_path))
            asyncio.run(check([b"kept", b"after-crash"], produce=None))
        finally:
            proc.terminate()
            proc.wait(timeout=5)

    def test_wal_mid_file_corruption_cuts_at_last_good_frame(self, tmp_path):
        """A flipped byte INSIDE an early WAL frame: replay keeps every
        frame before it, drops everything after (chain integrity — a
        half-trusted log is worse than a short one), and truncates so
        post-restart writes land cleanly."""
        port = _free_port()

        async def fetch_values(*, produce: bytes | None = None) -> list[bytes]:
            client = KafkaWireClient("127.0.0.1", port)
            try:
                results = await client.fetch([("mid", 0, 0)], max_wait_ms=200)
                values = [
                    v for *_x, v, _h in decode_record_batches(results[0][3])
                ]
                if produce is not None:
                    await client.produce(
                        "mid", 0,
                        encode_record_batch([(b"k", produce, [])], 2),
                    )
                return values
            finally:
                await client.close()

        async def seed() -> None:
            client = KafkaWireClient("127.0.0.1", port)
            try:
                await client.create_topics(["mid"], 1)
                for value in (b"one", b"two", b"three"):
                    await client.produce(
                        "mid", 0, encode_record_batch([(b"k", value, [])], 1)
                    )
            finally:
                await client.close()

        proc = spawn_kafkad(port, log_dir=str(tmp_path))
        try:
            asyncio.run(seed())
            proc.kill()
            proc.wait(timeout=5)
            wal = (tmp_path / "wal.log").read_bytes()
            # flip a byte ~60% in: inside the frame holding "two"
            corrupt = bytearray(wal)
            corrupt[int(len(corrupt) * 0.6)] ^= 0xFF
            (tmp_path / "wal.log").write_bytes(bytes(corrupt))

            proc = spawn_kafkad(port, log_dir=str(tmp_path))
            values = asyncio.run(fetch_values(produce=b"post"))
            # a strict prefix survived; nothing after the corruption
            assert values in ([b"one"], [b"one", b"two"]), values

            proc.terminate()
            proc.wait(timeout=5)
            proc = spawn_kafkad(port, log_dir=str(tmp_path))
            assert asyncio.run(fetch_values())[-1] == b"post"
        finally:
            proc.terminate()
            proc.wait(timeout=5)

    def test_table_reader_recovers_after_restart(self):
        """Compacted-table views re-resolve from the new (empty) world
        and keep serving writes made after the restart."""
        port = _free_port()
        proc = spawn_kafkad(port)

        async def run() -> None:
            nonlocal proc
            mesh = KafkaWireMesh(f"127.0.0.1:{port}")
            await mesh.start()
            try:
                await mesh.ensure_topics(["restart.table"], compacted=True)
                writer = mesh.table_writer("restart.table")
                await writer.put("k1", b"v1")
                reader = mesh.table_reader("restart.table")
                await reader.start()
                assert reader.get("k1") == b"v1"

                proc.kill()
                proc.wait(timeout=5)
                proc = spawn_kafkad(port)

                deadline = asyncio.get_running_loop().time() + 30
                while True:
                    try:
                        await writer.put("k2", b"v2")
                        break
                    except Exception:  # noqa: BLE001
                        if asyncio.get_running_loop().time() > deadline:
                            raise
                        await asyncio.sleep(0.3)
                while reader.get("k2") is None:
                    if asyncio.get_running_loop().time() > deadline:
                        raise AssertionError("table never saw post-restart write")
                    await asyncio.sleep(0.2)
                await reader.stop()
            finally:
                await mesh.stop()

        try:
            asyncio.run(run())
        finally:
            proc.terminate()
            proc.wait(timeout=5)
