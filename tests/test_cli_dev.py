"""CLI dev loop: --reload, connect-or-spawn broker lock, detached daemons.

Reference anchors: /root/reference/calfkit/cli/run.py:37 (--reload),
cli/_dev_broker.py:1-22 (spawn-race file lock), cli/_dev_agents.py +
cli/dev.py:41-51 (daemon status/stop/down).
"""

from __future__ import annotations

import asyncio
import sys
import textwrap
import time
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

import pytest

from calfkit_tpu.mesh.tcp import find_meshd

meshd_missing = find_meshd() is None

PORT = 19878


@pytest.fixture
def dev_env(tmp_path, monkeypatch):
    monkeypatch.setenv("CALFKIT_DEV_DIR", str(tmp_path / "devstate"))
    return tmp_path


class TestReload:
    def test_restart_on_file_change(self, tmp_path):
        from calfkit_tpu.cli._reload import serve_with_reload

        watched = tmp_path / "app.py"
        watched.write_text("x = 1\n")
        marker = tmp_path / "starts.txt"
        child = tmp_path / "child.py"
        child.write_text(textwrap.dedent(f"""
            import time
            with open({str(marker)!r}, "a") as f:
                f.write("start\\n")
            if open({str(marker)!r}).read().count("start") >= 2:
                raise SystemExit(0)  # restarted successfully: exit clean
            time.sleep(60)
        """))

        def touch_later():
            # wait for the child to have started once, then edit the file
            for _ in range(100):
                if marker.exists() and marker.read_text().count("start") >= 1:
                    break
                time.sleep(0.05)
            watched.write_text("x = 2\n")

        with ThreadPoolExecutor(1) as pool:
            pool.submit(touch_later)
            code = serve_with_reload(
                [sys.executable, str(child)],
                [tmp_path],
                poll_interval=0.1,
                echo=lambda *_: None,
            )
        assert code == 0
        assert marker.read_text().count("start") >= 2  # original + restart

    def test_snapshot_skips_hidden_and_pycache(self, tmp_path):
        from calfkit_tpu.cli._reload import snapshot

        (tmp_path / "real.py").write_text("1")
        (tmp_path / "__pycache__").mkdir()
        (tmp_path / "__pycache__" / "real.cpython-312.pyc.py").write_text("1")
        (tmp_path / ".hidden").mkdir()
        (tmp_path / ".hidden" / "x.py").write_text("1")
        seen = snapshot([tmp_path])
        assert list(seen) == [str(tmp_path / "real.py")]

    def test_watch_roots_for_specs(self, tmp_path):
        from calfkit_tpu.cli._reload import watch_roots_for_specs

        nested = tmp_path / "pkg"
        nested.mkdir()
        (tmp_path / "a.py").write_text("1")
        (nested / "b.py").write_text("1")
        roots = watch_roots_for_specs(
            [f"{tmp_path}/a.py:agent", f"{nested}/b.py:agent"]
        )
        assert roots == [tmp_path]  # parent swallows child


@pytest.mark.skipif(meshd_missing, reason="meshd not built (make -C native)")
class TestBrokerLock:
    def test_concurrent_ensure_broker_spawns_exactly_one(self, dev_env):
        from calfkit_tpu.cli._dev_state import ensure_broker, stop_broker

        try:
            with ThreadPoolExecutor(4) as pool:
                infos = list(
                    pool.map(lambda _: ensure_broker(PORT), range(4))
                )
            assert sum(info.spawned for info in infos) == 1
            assert all(info.port == PORT for info in infos)
        finally:
            stop_broker(PORT)

    def test_stop_broker_only_stops_managed(self, dev_env):
        from calfkit_tpu.cli._dev_state import (
            broker_status,
            ensure_broker,
            stop_broker,
        )

        info = ensure_broker(PORT)
        assert info.spawned
        assert broker_status(PORT)["up"]
        assert stop_broker(PORT) is True
        for _ in range(50):
            if not broker_status(PORT)["up"]:
                break
            time.sleep(0.1)
        assert not broker_status(PORT)["up"]
        assert stop_broker(PORT) is False  # nothing managed anymore


@pytest.mark.skipif(meshd_missing, reason="meshd not built (make -C native)")
class TestDaemons:
    async def test_daemon_serve_status_stop(self, dev_env, tmp_path):
        from calfkit_tpu.cli._dev_state import (
            ensure_broker,
            get_daemon,
            list_daemons,
            spawn_daemon,
            stop_broker,
            stop_daemon,
        )

        agent_file = tmp_path / "devagent.py"
        agent_file.write_text(textwrap.dedent("""
            from calfkit_tpu.engine import TestModelClient
            from calfkit_tpu.nodes import Agent

            agent = Agent(
                "daemon_agent",
                model=TestModelClient(custom_output_text="from-daemon"),
            )
        """))
        try:
            broker = ensure_broker(PORT)
            info = spawn_daemon(
                "daemon_agent", [f"{agent_file}:agent"], broker.url
            )
            assert info.alive
            assert [d.name for d in list_daemons()] == ["daemon_agent"]

            # the daemon actually serves: execute through a fresh client
            from calfkit_tpu.client import Client
            from calfkit_tpu.mesh.tcp import TcpMesh

            mesh = TcpMesh(f"127.0.0.1:{PORT}")
            await mesh.start()
            client = Client.connect(mesh)
            result = None
            for attempt in range(40):  # daemon boot is async
                try:
                    result = await client.agent("daemon_agent").execute(
                        "hi", timeout=5
                    )
                    break
                except Exception:
                    await asyncio.sleep(0.5)
            assert result is not None and result.output == "from-daemon"
            await client.close()
            await mesh.stop()

            # duplicate name is rejected while alive
            with pytest.raises(RuntimeError, match="already running"):
                spawn_daemon("daemon_agent", [f"{agent_file}:agent"], broker.url)

            assert stop_daemon("daemon_agent") is True
            assert get_daemon("daemon_agent") is None
            assert Path(info.log_path).exists()
        finally:
            for d in list_daemons():
                stop_daemon(d.name)
            stop_broker(PORT)


class TestBareFileSpecs:
    """``ck run file.py`` with no :attr collects top-level nodes."""

    def test_bare_file_collects_nodes(self, tmp_path):
        from calfkit_tpu.cli._common import load_nodes

        node_file = tmp_path / "my_nodes.py"
        node_file.write_text(
            "from calfkit_tpu.nodes import Agent, agent_tool\n"
            "from calfkit_tpu.engine import TestModelClient\n"
            "@agent_tool\n"
            "def t(x: int) -> int:\n"
            "    \"\"\"T.\n\n    Args:\n        x: x.\n    \"\"\"\n"
            "    return x\n"
            "a = Agent('bare_a', model=TestModelClient())\n"
            "alias = a\n"  # alias must not duplicate the node
        )
        nodes = load_nodes((str(node_file),))
        assert sorted(n.name for n in nodes) == ["bare_a", "t"]

    def test_bare_file_without_nodes_fails_loudly(self, tmp_path):
        import click
        import pytest

        from calfkit_tpu.cli._common import load_nodes

        empty = tmp_path / "empty_mod.py"
        empty.write_text("x = 1\n")
        with pytest.raises(click.ClickException, match="no nodes"):
            load_nodes((str(empty),))

    def test_bare_file_skips_imported_nodes(self, tmp_path):
        """A node imported from another file belongs to ITS file's spec."""
        from calfkit_tpu.cli._common import load_nodes

        (tmp_path / "shared_nodes.py").write_text(
            "from calfkit_tpu.nodes import Agent\n"
            "from calfkit_tpu.engine import TestModelClient\n"
            "shared = Agent('shared_x', model=TestModelClient())\n"
        )
        (tmp_path / "team_file.py").write_text(
            "from shared_nodes import shared\n"
            "from calfkit_tpu.nodes import Agent\n"
            "from calfkit_tpu.engine import TestModelClient\n"
            "mine = Agent('mine_x', model=TestModelClient())\n"
        )
        both = load_nodes(
            (str(tmp_path / "shared_nodes.py"), str(tmp_path / "team_file.py"))
        )
        assert sorted(n.name for n in both) == ["mine_x", "shared_x"]

    def test_missing_dependency_named_not_spec_grammar(self, tmp_path):
        import click
        import pytest

        from calfkit_tpu.cli._common import load_nodes

        pkg = tmp_path / "depmod.py"
        pkg.write_text("import nonexistent_dep_xyz\n")
        import sys
        sys.path.insert(0, str(tmp_path))
        try:
            with pytest.raises(click.ClickException,
                               match="nonexistent_dep_xyz"):
                load_nodes(("depmod:x",))
        finally:
            sys.path.remove(str(tmp_path))
            sys.modules.pop("depmod", None)

    def test_factory_built_nodes_are_collected(self, tmp_path):
        """Nodes constructed via a helper module still belong to the spec
        that assigns them at top level."""
        from calfkit_tpu.cli._common import load_nodes

        (tmp_path / "node_factory.py").write_text(
            "from calfkit_tpu.nodes import Agent\n"
            "from calfkit_tpu.engine import TestModelClient\n"
            "def make(name):\n"
            "    return Agent(name, model=TestModelClient())\n"
        )
        (tmp_path / "factory_team.py").write_text(
            "from node_factory import make\n"
            "lead = make('factory_lead')\n"
        )
        nodes = load_nodes((str(tmp_path / "factory_team.py"),))
        assert [n.name for n in nodes] == ["factory_lead"]

    def test_same_name_from_two_factories_first_seen_wins(self, tmp_path):
        """Reference loader semantics (dedupe_by_node_id): first seen wins,
        in spec order."""
        from calfkit_tpu.cli._common import load_nodes

        (tmp_path / "mk.py").write_text(
            "from calfkit_tpu.nodes import Agent\n"
            "from calfkit_tpu.engine import TestModelClient\n"
            "def make(text):\n"
            "    return Agent('shared_lead',\n"
            "                 model=TestModelClient(custom_output_text=text))\n"
        )
        (tmp_path / "team_a2.py").write_text(
            "from mk import make\nlead = make('alpha')\n"
        )
        (tmp_path / "team_b2.py").write_text(
            "from mk import make\nlead = make('beta')\n"
        )
        nodes = load_nodes(
            (str(tmp_path / "team_a2.py"), str(tmp_path / "team_b2.py"))
        )
        assert len(nodes) == 1  # one node_id -> one serving instance


class TestKafkadDevBroker:
    """`ck dev --kafka`: the managed kafkad broker (the real Kafka wire
    protocol as the dev mesh, mirroring the reference's Kafka-compatible
    bundled dev broker)."""

    def test_ensure_and_stop_kafkad(self, dev_env):
        from calfkit_tpu.cli._dev_state import (
            broker_status,
            ensure_broker,
            stop_broker,
        )
        from calfkit_tpu.mesh.kafka_wire import find_kafkad

        if find_kafkad() is None:
            import pytest

            pytest.skip("kafkad not built")
        info = ensure_broker(19393, "kafkad")
        try:
            assert info.kind == "kafkad"
            assert info.url == "kafka+wire://127.0.0.1:19393"
            assert broker_status(19393, "kafkad")["up"]
            # connect-or-spawn: a second ensure connects, doesn't respawn
            again = ensure_broker(19393, "kafkad")
            assert not again.spawned
            assert again.pid == info.pid
            # the meshd registry is independent: its metadata file knows
            # nothing about the kafkad pid even on the same port
            assert broker_status(19393, "meshd")["pid"] is None
        finally:
            assert stop_broker(19393, "kafkad")
        assert not broker_status(19393, "kafkad")["up"]

    async def test_worker_and_client_over_managed_kafkad(self, dev_env):
        from calfkit_tpu.cli._dev_state import ensure_broker, stop_broker
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.mesh.kafka_wire import find_kafkad
        from calfkit_tpu.mesh.urls import mesh_from_url
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        if find_kafkad() is None:
            import pytest

            pytest.skip("kafkad not built")
        info = ensure_broker(19394, "kafkad")
        try:
            mesh = mesh_from_url(info.url)
            client_mesh = mesh_from_url(info.url)
            await client_mesh.start()
            agent = Agent(
                "dev_kafka_agent",
                model=TestModelClient(custom_output_text="dev over kafka"),
            )
            async with Worker([agent], mesh=mesh, owns_transport=True):
                client = Client.connect(client_mesh)
                result = await client.agent("dev_kafka_agent").execute(
                    "hi", timeout=60
                )
                assert result.output == "dev over kafka"
                await client.close()
            await client_mesh.stop()
        finally:
            stop_broker(19394, "kafkad")


class TestDurableDevBroker:
    def test_durable_kafkad_survives_restart(self, dev_env):
        """`ck dev mesh --kafka --durable`: records + offsets live in the
        dev dir's WAL, so a broker restart keeps the dev mesh's state."""
        import asyncio

        from calfkit_tpu.cli._dev_state import ensure_broker, stop_broker
        from calfkit_tpu.mesh.kafka_wire import (
            KafkaWireClient,
            encode_record_batch,
            find_kafkad,
        )

        if find_kafkad() is None:
            pytest.skip("kafkad not built")
        port = 19893
        info = ensure_broker(port, "kafkad", durable=True)
        assert info.spawned

        async def produce() -> None:
            client = KafkaWireClient("127.0.0.1", port)
            try:
                await client.create_topics(["dev.durable"], 1)
                await client.produce(
                    "dev.durable", 0,
                    encode_record_batch([(b"k", b"sticky", [])], 1),
                )
            finally:
                await client.close()

        asyncio.run(produce())
        assert stop_broker(port, "kafkad")
        from calfkit_tpu.cli._dev_state import broker_status

        for _ in range(50):
            if not broker_status(port, "kafkad")["up"]:
                break
            time.sleep(0.1)

        info = ensure_broker(port, "kafkad", durable=True)
        assert info.spawned

        async def check() -> None:
            from calfkit_tpu.mesh.kafka_wire import decode_record_batches

            client = KafkaWireClient("127.0.0.1", port)
            try:
                results = await client.fetch(
                    [("dev.durable", 0, 0)], max_wait_ms=300
                )
                records = decode_record_batches(results[0][3])
                assert [v for *_x, v, _h in records] == [b"sticky"]
            finally:
                await client.close()

        try:
            asyncio.run(check())
        finally:
            stop_broker(port, "kafkad")

    def test_unstated_durability_inherits_recorded(self, dev_env):
        """A respawn WITHOUT the flag (durable=None — what `ck dev serve`
        passes) must inherit the port's recorded durability instead of
        silently demoting a durable broker (review finding r5)."""
        from calfkit_tpu.cli._dev_state import (
            _recorded_durable,
            ensure_broker,
            stop_broker,
        )
        from calfkit_tpu.mesh.kafka_wire import find_kafkad

        if find_kafkad() is None:
            pytest.skip("kafkad not built")
        import os
        import signal as _signal

        port = 19894
        info = ensure_broker(port, "kafkad", durable=True)
        assert info.spawned and _recorded_durable(port, "kafkad")
        # CRASH (not a clean `ck dev stop`, which forgets the record):
        # the broker dies, the meta survives, and a respawn must inherit
        os.kill(info.pid, _signal.SIGKILL)
        for _ in range(50):
            from calfkit_tpu.cli._dev_state import broker_status

            if not broker_status(port, "kafkad")["up"]:
                break
            time.sleep(0.1)
        # respawn with durability UNSTATED: meta must keep durable=True
        info = ensure_broker(port, "kafkad")
        try:
            assert info.spawned
            assert _recorded_durable(port, "kafkad")
        finally:
            stop_broker(port, "kafkad")
