"""Adversarial depth on the Kafka wire codec (VERDICT r4 item 8).

Property/fuzz coverage of the RecordBatch v2 codec and the kafkad
broker's frame reader: randomized round-trips, truncation at every byte
boundary, single-byte corruption at every offset (the client must raise
the typed :class:`RecordBatchError` or skip cleanly — never a raw
struct/index error), compressed-batch handling, and a corrupt-frame
barrage against a live kafkad (the broker must survive and keep
serving).

Reference anchor: the reference's test corpus earns its 48k LoC on
exactly this class of seam (tests/unit/ codec suites); here the seam is
the in-repo wire implementation.
"""

from __future__ import annotations

import asyncio
import gzip
import random
import socket
import struct

import pytest

from calfkit_tpu.mesh.kafka_wire import (
    KafkaWireClient,
    RecordBatchError,
    crc32c,
    decode_record_batches,
    encode_record_batch,
    find_kafkad,
    spawn_kafkad,
)


def _random_records(rng: random.Random, *, max_size: int = 2048):
    records = []
    for _ in range(rng.randint(1, 12)):
        key = None
        if rng.random() < 0.7:
            key = rng.randbytes(rng.randint(0, max_size))
        value = None
        if rng.random() < 0.8:
            value = rng.randbytes(rng.randint(0, max_size))
        headers = [
            (
                "".join(rng.choices("abcxyz-._", k=rng.randint(0, 12))),
                rng.randbytes(rng.randint(0, 64)),
            )
            for _ in range(rng.randint(0, 4))
        ]
        records.append((key, value, headers))
    return records


class TestRoundTripProperties:
    def test_randomized_round_trips(self):
        rng = random.Random(17)
        for _ in range(200):
            records = _random_records(rng)
            ts = rng.randint(0, 2**41)
            blob = encode_record_batch(records, ts)
            out = decode_record_batches(blob)
            assert [(k, v, h) for _o, _t, k, v, h in out] == records
            assert [o for o, *_ in out] == list(range(len(records)))
            assert all(t == ts for _o, t, *_ in out)

    def test_large_payload_round_trip(self):
        rng = random.Random(23)
        big = rng.randbytes(3 * 1024 * 1024)
        blob = encode_record_batch([(b"k", big, [])], 1)
        (_o, _t, _k, value, _h) = decode_record_batches(blob)[0]
        assert value == big

    def test_multi_batch_blob(self):
        a = encode_record_batch([(b"a", b"1", [])], 10)
        b = encode_record_batch([(b"b", b"2", []), (None, b"3", [])], 20)
        out = decode_record_batches(a + b)
        assert [v for *_, v, _h in out] == [b"1", b"2", b"3"]

    def test_fuzzed_trace_headers_round_trip_or_degrade(self):
        """ISSUE 2 satellite fuzz: trace headers with arbitrary byte
        values always survive the codec byte-exactly, and the consumer
        side (header_map + TraceContext.from_headers) either yields a
        valid context or degrades to untraced — never raises."""
        from calfkit_tpu import protocol
        from calfkit_tpu.observability.trace import TraceContext

        rng = random.Random(99)
        for _ in range(200):
            # mix of valid utf-8 ids, arbitrary bytes, empty values, and
            # a missing span/trace header in some iterations
            headers: list[tuple[str, bytes]] = []
            if rng.random() < 0.8:
                value = (
                    rng.randbytes(rng.randint(0, 48))
                    if rng.random() < 0.5
                    else ("corr-%d" % rng.randint(0, 9999)).encode()
                )
                headers.append((protocol.HDR_TRACE, value))
            if rng.random() < 0.8:
                headers.append(
                    (protocol.HDR_SPAN, rng.randbytes(rng.randint(0, 32)))
                )
            headers.extend(
                (
                    "".join(rng.choices("abcxyz-._", k=rng.randint(1, 12))),
                    rng.randbytes(rng.randint(0, 64)),
                )
                for _ in range(rng.randint(0, 3))
            )
            blob = encode_record_batch(
                [(rng.randbytes(4) or None, b"v", headers)],
                rng.randint(0, 2**40),
            )
            [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
            assert decoded == headers  # codec is byte-exact
            ctx = TraceContext.from_headers(protocol.header_map(dict(decoded)))
            if ctx is not None:
                assert ctx.trace_id  # never an empty trace id
                # a context implies the trace header decoded as utf-8
                raw = dict(decoded)[protocol.HDR_TRACE]
                assert ctx.trace_id == raw.decode("utf-8")


    def test_fuzzed_run_headers_parse_or_unlink(self):
        """ISSUE 17 satellite fuzz: arbitrary ``x-mesh-run`` bytes
        survive the codec byte-exactly, and ``parse_run`` either yields
        the exact round-trip identity or None (un-linked) — never
        raises, and a corrupt value can never alias two requests onto a
        shared bogus run id: whatever parses echoes the value's OWN
        prefix."""
        from calfkit_tpu import protocol

        rng = random.Random(99)
        for _ in range(200):
            if rng.random() < 0.5:
                run_id = "%032x" % rng.getrandbits(128)
                attempt = rng.randint(0, 12)
                raw = protocol.format_run(run_id, attempt).encode()
                expect = (run_id, attempt)
            else:
                raw = rng.randbytes(rng.randint(0, 48))
                expect = None  # fuzz bytes: parse is allowed either way
            blob = encode_record_batch(
                [(b"k", b"v", [(protocol.HDR_RUN, raw)])], 1
            )
            [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
            assert dict(decoded)[protocol.HDR_RUN] == raw  # byte-exact
            parsed = protocol.parse_run(
                protocol.header_map(dict(decoded)).get(protocol.HDR_RUN)
            )
            if expect is not None:
                assert parsed == expect
            elif parsed is not None:
                # an accepted fuzz value must carry its own identity:
                # non-empty run id that IS this value's prefix, and a
                # non-negative integer attempt — no shared constant
                run_id, attempt = parsed
                assert run_id and attempt >= 0
                assert raw.decode("utf-8").startswith(run_id + ":")


    def test_fuzzed_priority_headers_parse_or_default(self):
        """ISSUE 20 satellite fuzz: arbitrary ``x-mesh-priority`` bytes
        survive the codec byte-exactly, and the receiving resolve law
        (``parse_priority`` → ``qos.resolve_priority``) always yields a
        class FROM THE VOCABULARY — never raises, never a third class,
        and a valid class always round-trips exactly."""
        from calfkit_tpu import protocol, qos

        rng = random.Random(99)
        for _ in range(200):
            if rng.random() < 0.5:
                cls = rng.choice(protocol.PRIORITY_CLASSES)
                raw = protocol.format_priority(cls).encode()
                expect = cls
            else:
                raw = rng.randbytes(rng.randint(0, 32))
                expect = None  # fuzz bytes: whatever parses must be exact
            blob = encode_record_batch(
                [(b"k", b"v", [(protocol.HDR_PRIORITY, raw)])], 1
            )
            [(_o, _t, _k, _v, decoded)] = decode_record_batches(blob)
            assert dict(decoded)[protocol.HDR_PRIORITY] == raw  # byte-exact
            parsed = protocol.parse_priority(
                protocol.header_map(dict(decoded)).get(protocol.HDR_PRIORITY)
            )
            resolved = qos.resolve_priority(parsed)
            assert resolved in protocol.PRIORITY_CLASSES
            if expect is not None:
                assert parsed == expect and resolved == expect
            elif parsed is not None:
                # an accepted fuzz value can only be an exact vocabulary
                # word — parse_priority never normalizes or guesses
                assert raw.decode("utf-8") == parsed


class TestCorruption:
    def test_truncation_at_every_boundary(self):
        """A truncated record_set never raises a raw error: the trailing
        partial batch is dropped per the Kafka max_bytes contract."""
        blob = encode_record_batch(
            [(b"key", b"value", [("h", b"x")]), (None, None, [])], 99
        )
        full = decode_record_batches(blob)
        for i in range(len(blob)):
            out = decode_record_batches(blob[:i])
            assert out == [] or out == full[: len(out)]

    def test_single_byte_corruption_at_every_offset(self):
        """Any one-byte flip must yield typed RecordBatchError, a clean
        skip, or an offset-field change — never struct.error/IndexError
        and never silently-garbled record CONTENT (crc catches those)."""
        records = [(b"key", b"some value", [("trace", b"t")])]
        blob = encode_record_batch(records, 1234)
        # crc covers attrs..end, i.e. everything past byte 21
        crc_covered_start = 8 + 4 + 4 + 1 + 4
        for i in range(len(blob)):
            corrupt = bytearray(blob)
            corrupt[i] ^= 0x5A
            try:
                out = decode_record_batches(bytes(corrupt))
            except RecordBatchError:
                continue
            if i >= crc_covered_start:
                # decoded without error despite a flip in the crc-covered
                # region — impossible unless the batch was skipped whole
                assert out == []
            else:
                # header-field flips (baseOffset/length/epoch/magic/crc)
                # may shift offsets or drop the batch, but content survives
                for _o, _t, key, value, headers in out:
                    assert (key, value, headers) == records[0]

    def test_crc_mismatch_is_typed(self):
        blob = bytearray(encode_record_batch([(b"k", b"v", [])], 1))
        blob[-1] ^= 0xFF
        with pytest.raises(RecordBatchError, match="crc"):
            decode_record_batches(bytes(blob))

    def test_random_garbage_never_raises_raw_errors(self):
        rng = random.Random(31)
        for _ in range(500):
            junk = rng.randbytes(rng.randint(61, 400))
            try:
                decode_record_batches(junk)
            except RecordBatchError:
                pass  # typed — acceptable


def _gzip_batch(records, timestamp_ms: int, codec_attrs: int = 1) -> bytes:
    """Build a COMPRESSED RecordBatch v2 the way a real broker would."""
    plain = encode_record_batch(records, timestamp_ms)
    # records section starts after the 61-byte v2 header
    header, recblob = plain[:61], plain[61:]
    payload = gzip.compress(recblob) if codec_attrs == 1 else recblob
    body = bytearray(header[21:61])  # attrs..count
    struct.pack_into(">h", body, 0, codec_attrs)
    crcbody = bytes(body) + payload
    out = bytearray(header[:21])
    struct.pack_into(">i", out, 8, 4 + 1 + 4 + len(crcbody))  # batchLength
    crc = crc32c(crcbody)
    struct.pack_into(">i", out, 17, crc - (1 << 32) if crc >= (1 << 31) else crc)
    return bytes(out) + crcbody


class TestCompression:
    def test_gzip_batch_decodes(self):
        records = [(b"k", b"compressed value", [("h", b"1")]), (None, b"x", [])]
        blob = _gzip_batch(records, 777)
        out = decode_record_batches(blob)
        assert [(k, v, h) for _o, _t, k, v, h in out] == records
        assert all(t == 777 for _o, t, *_ in out)

    @pytest.mark.parametrize("codec,name", [(2, "snappy"), (3, "lz4"), (4, "zstd")])
    def test_unsupported_codecs_raise_loudly(self, codec, name):
        blob = _gzip_batch([(b"k", b"v", [])], 1, codec_attrs=codec)
        with pytest.raises(RecordBatchError, match=name):
            decode_record_batches(blob)

    def test_corrupt_gzip_payload_is_typed(self):
        blob = bytearray(_gzip_batch([(b"k", b"v" * 100, [])], 1))
        blob[-3] ^= 0xFF  # inside the compressed stream (crc catches it)
        with pytest.raises(RecordBatchError):
            decode_record_batches(bytes(blob))

    def test_valid_crc_but_corrupt_gzip_is_typed(self):
        """crc32c can be VALID over a broken gzip stream (buggy producer
        compressor): the decompression failure itself must stay typed —
        an escaped BadGzipFile would rebalance-thrash group consumers."""
        records = [(b"k", b"v" * 50, [])]
        plain = encode_record_batch(records, 1)
        header, recblob = plain[:61], plain[61:]
        broken = bytearray(gzip.compress(recblob))
        broken[-2] ^= 0xFF  # corrupt, then crc computed over the corruption
        body = bytearray(header[21:61])
        struct.pack_into(">h", body, 0, 1)
        crcbody = bytes(body) + bytes(broken)
        out = bytearray(header[:21])
        struct.pack_into(">i", out, 8, 9 + len(crcbody))
        crc = crc32c(crcbody)
        struct.pack_into(
            ">i", out, 17, crc - (1 << 32) if crc >= (1 << 31) else crc
        )
        with pytest.raises(RecordBatchError, match="gzip"):
            decode_record_batches(bytes(out) + crcbody)


class TestLegacyFormats:
    def test_small_legacy_v1_entry_is_skipped_not_poison(self):
        """A pre-0.11 v0/v1 message-set entry (magic != 2, smaller than
        the v2 header) must skip cleanly — raising would stall the
        partition forever on old segments."""
        # v1 entry: offset(8) size(4) crc(4) magic=1 attrs(1) ts(8) key(-1) val(-1)
        legacy = struct.pack(">qi", 0, 22) + struct.pack(">i", 0) + b"\x01\x00"
        legacy += struct.pack(">q", 123) + struct.pack(">ii", -1, -1)
        follow = encode_record_batch([(b"k", b"modern", [])], 5)
        out = decode_record_batches(legacy + follow)
        assert [v for *_x, v, _h in out] == [b"modern"]


@pytest.mark.skipif(find_kafkad() is None, reason="kafkad not built")
class TestBrokerBarrage:
    """kafkad must survive corrupt frames and keep serving (VERDICT #8)."""

    @pytest.fixture()
    def broker_port(self):
        proc = spawn_kafkad(0)
        yield proc.kafkad_port
        proc.terminate()
        proc.wait(timeout=5)

    def _alive(self, port: int) -> bool:
        async def check() -> bool:
            client = KafkaWireClient("127.0.0.1", port)
            try:
                meta = await client.metadata(None)
                return isinstance(meta["brokers"], list)
            finally:
                await client.close()

        return asyncio.run(check())

    def test_corrupt_frame_barrage(self, broker_port):
        rng = random.Random(41)
        for _ in range(100):
            with socket.create_connection(("127.0.0.1", broker_port), 5) as s:
                kind = rng.randint(0, 3)
                if kind == 0:  # random garbage with plausible length prefix
                    body = rng.randbytes(rng.randint(0, 512))
                    s.sendall(struct.pack(">i", len(body)) + body)
                elif kind == 1:  # truncated frame: length promises more
                    s.sendall(struct.pack(">i", 1 << 20) + rng.randbytes(64))
                elif kind == 2:  # negative / absurd length prefix
                    s.sendall(struct.pack(">i", rng.choice([-1, -(1 << 30), 1 << 30])))
                else:  # valid header, garbage body (api 0 = produce)
                    body = struct.pack(">hhi", 0, 3, 1) + b"\x00\x00" + rng.randbytes(200)
                    s.sendall(struct.pack(">i", len(body) + 10) + body)
                # half-close and move on; broker must not wedge or die
        assert self._alive(broker_port)

    def test_corrupt_record_batch_in_valid_produce(self, broker_port):
        """A structurally-valid Produce carrying a garbage RecordBatch
        must come back as an error (or parse failure), not kill kafkad."""

        async def run() -> None:
            client = KafkaWireClient("127.0.0.1", broker_port)
            try:
                await client.create_topics(["barrage"], 1)
                for seed in range(20):
                    junk = random.Random(seed).randbytes(random.Random(seed).randint(61, 200))
                    try:
                        await client.produce("barrage", 0, junk)
                    except Exception:  # noqa: BLE001 — error is acceptable
                        pass
                # broker still serves real traffic afterwards
                blob = encode_record_batch([(b"k", b"v", [])], 1)
                base = await client.produce("barrage", 0, blob)
                assert base >= 0
            finally:
                await client.close()

        asyncio.run(run())
