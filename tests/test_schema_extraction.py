"""Schema extraction corners: signature → JSON schema → validated call.

Reference analogs: tests/test_args_schema.py,
test_schema_roundtrip_validation.py, test_model_settings.py — the vendored
``function_schema`` behaviors the owned extractor must keep.
"""

from typing import Literal, Optional

import pytest
from pydantic import BaseModel, ValidationError

from calfkit_tpu.engine.schema import (
    ToolSchemaError,
    function_schema,
    output_tool_def,
)


class TestSignatureExtraction:
    def test_defaults_become_optional(self):
        def f(city: str, units: str = "metric") -> str:
            return city

        schema = function_schema(f)
        params = schema.tool_def.parameters_schema
        assert params["required"] == ["city"]
        assert params["properties"]["units"]["default"] == "metric"

    def test_optional_annotation(self):
        def f(q: str, limit: Optional[int] = None) -> str:
            return q

        params = function_schema(f).tool_def.parameters_schema
        assert "limit" in params["properties"]
        assert params["required"] == ["q"]

    def test_literal_becomes_enum(self):
        def f(mode: Literal["fast", "slow"]) -> str:
            return mode

        params = function_schema(f).tool_def.parameters_schema
        assert set(params["properties"]["mode"]["enum"]) == {"fast", "slow"}

    def test_nested_pydantic_model_schema(self):
        class Filters(BaseModel):
            tags: list[str]
            min_score: float = 0.0

        def f(filters: Filters) -> str:
            return "ok"

        params = function_schema(f).tool_def.parameters_schema
        prop = params["properties"]["filters"]
        # nested model surfaces as an object schema (inline or $ref)
        assert "$ref" in prop or prop.get("type") == "object"

    def test_sphinx_docstring_descriptions(self):
        def f(city: str) -> str:
            """Get weather.

            :param city: The city to look up.
            """
            return city

        schema = function_schema(f)
        assert schema.tool_def.description.startswith("Get weather")
        assert "look up" in schema.tool_def.parameters_schema["properties"]["city"][
            "description"
        ]

    def test_google_docstring_descriptions(self):
        def f(city: str, units: str = "metric") -> str:
            """Get weather.

            Args:
                city: Which city.
                units (str): Unit system.
            """
            return city

        params = function_schema(f).tool_def.parameters_schema
        assert params["properties"]["city"]["description"] == "Which city."
        assert params["properties"]["units"]["description"] == "Unit system."


class TestValidatedCall:
    def test_coercion_and_extra_args_rejected(self):
        def f(n: int) -> int:
            return n * 2

        schema = function_schema(f)
        assert schema.validate_args({"n": "21"}) == {"n": 21}  # coerced
        # MUST be ValidationError specifically: ToolNodeDef.run only turns
        # ValidationError into a model retry — anything else faults the run
        with pytest.raises(ValidationError):
            schema.validate_args({"n": 1, "zzz": 2})

    def test_missing_required_rejected(self):
        def f(n: int) -> int:
            return n

        with pytest.raises(ValidationError):
            function_schema(f).validate_args({})

    async def test_nested_model_instantiated_not_dict(self):
        class Point(BaseModel):
            x: int
            y: int

        def f(p: Point) -> int:
            assert isinstance(p, Point)
            return p.x + p.y

        schema = function_schema(f)
        assert await schema.call({"p": {"x": 1, "y": 2}}) == 3


class TestOutputTool:
    def test_output_tool_from_model(self):
        class Answer(BaseModel):
            """The final answer."""

            value: int

        tool = output_tool_def(Answer)
        assert tool.name == "final_result"
        assert "value" in tool.parameters_schema["properties"]

    def test_output_tool_custom_name(self):
        class Answer(BaseModel):
            value: int

        assert output_tool_def(Answer, name="submit").name == "submit"


class TestRejectedSignatures:
    def test_var_positional_rejected(self):
        def f(*args: int) -> int:
            return 0

        with pytest.raises(ToolSchemaError):
            function_schema(f)

    def test_var_keyword_rejected(self):
        def f(**kwargs: int) -> int:
            return 0

        with pytest.raises(ToolSchemaError):
            function_schema(f)
