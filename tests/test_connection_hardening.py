"""ConnectionProfile discipline + provisioning error classification.

Reference anchors: calfkit/client/_connection.py:39-110 (profile threading,
producer guard + consumer fetch floor), caller.py:148-165 (reject-by-name),
calfkit/provisioning/provisioner.py:13-18 (created/existing/unauthorized/
retry classification).
"""

from __future__ import annotations

import pytest

from calfkit_tpu.exceptions import ProvisioningError
from calfkit_tpu.mesh.connection import ConnectionProfile
from calfkit_tpu.provisioning.provisioner import (
    ProvisioningConfig,
    classify_topic_error,
    provision,
)


class TestConnectionProfile:
    def test_producer_guard_and_consumer_floor(self):
        """max_message_bytes is BOTH the producer guard and the consumer
        fetch floor — the wire client derives its fetch budget from it so
        the biggest legal record is always fetchable."""
        from calfkit_tpu.mesh.kafka_wire import KEY_HEADERS_CAP, fetch_floor

        assert fetch_floor(10_000_000) >= 10_000_000 + KEY_HEADERS_CAP
        # small budgets still get the 4 MiB floor (multi-record batches)
        assert fetch_floor(1) == 4 * 1024 * 1024
        # monotone: a bigger budget never shrinks the fetch budget
        assert fetch_floor(100_000_000) > fetch_floor(10_000_000)

    def test_idempotence_rejected_loudly_by_wire_mesh(self):
        """The native client's retry-once produce cannot guarantee
        exactly-once sequencing; a profile asking for idempotence must
        fail at construction, never be silently honored as
        at-least-once."""
        from calfkit_tpu.mesh.kafka_wire import KafkaWireMesh

        profile = ConnectionProfile("h:9", enable_idempotence=True)
        with pytest.raises(ValueError, match="enable_idempotence"):
            KafkaWireMesh(profile=profile)
        # tri-state: None (default) and explicit False are fine
        KafkaWireMesh(profile=ConnectionProfile("h:9"))
        KafkaWireMesh(
            profile=ConnectionProfile("h:9", enable_idempotence=False)
        )

    def test_security_and_client_id_thread_to_the_wire_client(self):
        from calfkit_tpu.mesh.kafka_wire import KafkaWireMesh

        profile = ConnectionProfile(
            "h:9",
            client_id="svc-x",
            security={
                "security_protocol": "SASL_PLAINTEXT",
                "sasl_mechanism": "PLAIN",
                "sasl_plain_username": "u",
                "sasl_plain_password": "p",
            },
        )
        mesh = KafkaWireMesh(profile=profile)
        assert mesh._security.uses_sasl
        assert mesh._security.username == "u"
        assert mesh._profile.client_id == "svc-x"

    @pytest.mark.parametrize(
        "kwarg",
        ["max_request_size", "enable_idempotence", "acks", "group_id",
         "auto_offset_reset", "enable_auto_commit", "fetch_max_bytes"],
    )
    def test_coordinated_kwargs_rejected_by_name(self, kwarg):
        with pytest.raises(ValueError, match=kwarg):
            ConnectionProfile("h:9", security={kwarg: "x"})


class _NamedError(Exception):
    pass


def _named(name: str, message: str = "") -> Exception:
    err_type = type(name, (_NamedError,), {})
    return err_type(message)


class TestClassification:
    def test_existing(self):
        assert classify_topic_error(_named("TopicAlreadyExistsError")) == "existing"
        assert classify_topic_error(Exception("Topic already exists")) == "existing"

    def test_unauthorized(self):
        assert (
            classify_topic_error(_named("TopicAuthorizationFailedError"))
            == "unauthorized"
        )
        assert (
            classify_topic_error(_named("ClusterAuthorizationFailedError"))
            == "unauthorized"
        )
        assert classify_topic_error(PermissionError("no")) == "unauthorized"

    def test_retriable(self):
        assert classify_topic_error(_named("RequestTimedOutError")) == "retry"
        assert classify_topic_error(_named("NotControllerError")) == "retry"
        assert classify_topic_error(_named("LeaderNotAvailableError")) == "retry"
        assert classify_topic_error(TimeoutError()) == "retry"
        assert classify_topic_error(ConnectionRefusedError()) == "retry"

    def test_fatal(self):
        assert classify_topic_error(_named("InvalidTopicError")) == "fatal"
        assert classify_topic_error(ValueError("bad")) == "fatal"


class _FlakyTransport:
    """ensure_topics fails ``failures`` times, then succeeds."""

    def __init__(self, failures: int, exc: Exception):
        self.failures = failures
        self.exc = exc
        self.calls = 0
        self.ensured: list[list[str]] = []

    async def ensure_topics(self, names, *, compacted=False):
        self.calls += 1
        if self.calls <= self.failures:
            raise self.exc
        self.ensured.append(list(names))


class _Node:
    def __init__(self, name):
        self.name = name
        self.node_id = f"agent.{name}"

    def all_topics(self):
        return [f"agent.{self.name}.private.input"]


class TestProvisionRetry:
    async def test_transient_errors_retried_to_success(self):
        transport = _FlakyTransport(2, _named("RequestTimedOutError", "slow"))
        result = await provision(
            transport, [_Node("a")],
            ProvisioningConfig(retry_backoff_s=0.01, include_framework=False),
        )
        assert transport.calls == 3
        assert result["plain"] == ["agent.a.private.input"]

    async def test_transient_errors_exhaust_loudly(self):
        transport = _FlakyTransport(99, TimeoutError("down"))
        with pytest.raises(ProvisioningError, match="retry"):
            await provision(
                transport, [_Node("a")],
                ProvisioningConfig(
                    retry_backoff_s=0.01, include_framework=False
                ),
            )
        assert transport.calls == 3  # bounded

    async def test_unauthorized_fails_immediately_no_retry(self):
        transport = _FlakyTransport(
            99, _named("TopicAuthorizationFailedError", "denied")
        )
        with pytest.raises(ProvisioningError, match="UNAUTHORIZED"):
            await provision(
                transport, [_Node("a")],
                ProvisioningConfig(
                    retry_backoff_s=0.01, include_framework=False
                ),
            )
        assert transport.calls == 1  # no retry on ACL problems

    async def test_existing_is_success(self):
        transport = _FlakyTransport(99, _named("TopicAlreadyExistsError"))
        result = await provision(
            transport, [_Node("a")],
            ProvisioningConfig(include_framework=False),
        )
        assert transport.calls == 1
        assert result["plain"] == ["agent.a.private.input"]


class TestReviewRegressions:
    def test_security_dict_mutation_cannot_bypass_validation(self):
        sec: dict = {}
        profile = ConnectionProfile("h:9", security=sec)
        sec["acks"] = 0  # mutate AFTER construction
        # the profile holds its OWN copy: the leaked key must be absent
        # from the security mapping the wire client parses
        assert "acks" not in profile.security

    def test_max_attempts_lower_bound(self):
        with pytest.raises(Exception):
            ProvisioningConfig(max_attempts=0)

    async def test_batch_exists_falls_back_per_topic(self):
        """An already-exists on the batch must not mask missing siblings."""

        class BatchExistsTransport:
            def __init__(self):
                self.created: list[str] = []

            async def ensure_topics(self, names, *, compacted=False):
                if len(names) > 1:
                    raise _named("TopicAlreadyExistsError", "t1 exists")
                if names[0] in self.created:
                    raise _named("TopicAlreadyExistsError")
                self.created.extend(names)

        class TwoTopicNode(_Node):
            def all_topics(self):
                return [f"agent.{self.name}.private.input",
                        f"agent.{self.name}.private.return"]

        transport = BatchExistsTransport()
        transport.created.append("agent.a.private.input")  # pre-existing
        result = await provision(
            transport, [TwoTopicNode("a")],
            ProvisioningConfig(include_framework=False),
        )
        assert "agent.a.private.return" in transport.created  # NOT masked
        assert len(result["plain"]) == 2
