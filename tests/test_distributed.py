"""Multi-host bring-up: initialize_multihost + mesh-fit guards.

The real-cluster behavior is tested with TWO actual processes coordinating
over localhost (jax multi-process on CPU): global device count spans both,
and a jitted reduction over a global mesh agrees on each host.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

from calfkit_tpu.inference.distributed import (
    MultihostInfo,
    assert_engine_fits,
    initialize_multihost,
)


class TestSingleHost:
    def test_noop_without_coordinates(self):
        """On a bare host with no cluster env, bring-up is a no-op and
        reports single-process truth."""
        info = initialize_multihost()
        assert info.num_processes == 1
        assert info.process_id == 0
        assert not info.is_multihost
        assert info.global_devices == info.local_devices


class TestMeshFit:
    def _info(self, **kw):
        defaults = dict(
            process_id=0, num_processes=2, local_devices=4, global_devices=8
        )
        defaults.update(kw)
        return MultihostInfo(**defaults)

    def test_fits(self):
        assert_engine_fits(self._info(), tp=4, dp=2)

    def test_over_ask_rejected(self):
        with pytest.raises(ValueError, match="needs 16 devices"):
            assert_engine_fits(self._info(), tp=8, dp=2)

    def test_multihost_partial_mesh_rejected(self):
        """A multi-host mesh must span every pod device: omitting another
        process's devices deadlocks at the first collective."""
        with pytest.raises(ValueError, match="span the whole pod"):
            assert_engine_fits(self._info(), tp=2, dp=1)

    def test_single_host_partial_mesh_allowed(self):
        info = self._info(num_processes=1, global_devices=8, local_devices=8)
        assert_engine_fits(info, tp=2, dp=1)  # 2 of 8 chips: legitimate

    def test_partial_coordinates_rejected_loudly(self):
        from calfkit_tpu.inference.distributed import initialize_multihost

        with pytest.raises(ValueError, match="set together"):
            initialize_multihost(process_id=0)

    def test_single_host_message_names_host(self):
        info = self._info(num_processes=1, global_devices=4, local_devices=4)
        with pytest.raises(ValueError, match="host has 4"):
            assert_engine_fits(info, tp=8, dp=1)


_CHILD = textwrap.dedent("""
    import sys
    sys.path.insert(0, {repo!r})
    import jax
    jax.config.update("jax_platforms", "cpu")
    from calfkit_tpu.inference.distributed import initialize_multihost

    pid = int(sys.argv[1])
    info = initialize_multihost({addr!r}, 2, pid)
    assert info.num_processes == 2 and info.is_multihost, info
    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()).reshape(2, -1), ("dp", "tp"))
    x = jax.device_put(
        jnp.arange(8, dtype=jnp.float32).reshape(2, 4),
        NamedSharding(mesh, P("dp", None)),
    )
    total = float(jax.jit(lambda a: a.sum())(x))
    print(f"RESULT {{pid}} {{info.global_devices}} {{total}}")
""")


class TestTwoProcesses:
    def test_two_process_global_mesh(self, tmp_path):
        """Two REAL processes coordinate over localhost: each sees the
        global device list (2 hosts x 2 devices) and a jitted global-mesh
        reduction returns the same answer on both."""
        with socket.socket() as probe:
            probe.bind(("127.0.0.1", 0))
            port = probe.getsockname()[1]
        script = tmp_path / "child.py"
        script.write_text(
            _CHILD.format(
                repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                addr=f"127.0.0.1:{port}",
            )
        )
        env = dict(
            os.environ,
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            JAX_PLATFORMS="cpu",
        )
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), str(pid)],
                env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True,
            )
            for pid in (0, 1)
        ]
        outs = []
        for proc in procs:
            try:
                out, err = proc.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                for p in procs:
                    p.kill()
                pytest.fail("two-process bring-up hung")
            assert proc.returncode == 0, err[-800:]
            outs.append(out)
        results = sorted(
            line.split()[1:] for out in outs for line in out.splitlines()
            if line.startswith("RESULT")
        )
        assert results == [["0", "4", "28.0"], ["1", "4", "28.0"]]
