"""Full framework stack over the REAL KafkaMesh code against the in-process
aiokafka fake: worker boot (provisioning, control plane tables, fan-out
stores), an agent+tool round trip with a parallel fan-out, step streaming,
and clean shutdown.  This is the closest this image can get to the
reference's ``-m kafka`` lane without a broker."""

import pytest  # noqa: F401 - fixtures come from conftest

# the shared kafka_fake_broker fixture (tests/conftest.py) installs the
# in-process aiokafka fake for each test and yields a fresh bootstrap id


class TestKafkaFakeEndToEnd:
    async def test_agent_fanout_roundtrip_over_kafka_mesh(self, kafka_fake_broker):
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import FunctionModelClient
        from calfkit_tpu.mesh.kafka import KafkaMesh
        from calfkit_tpu.models import ModelResponse, TextOutput, ToolCallOutput
        from calfkit_tpu.nodes import Agent, agent_tool
        from calfkit_tpu.worker import Worker

        @agent_tool
        def double(x: int) -> int:
            """D.

            Args:
                x: X.
            """
            return x * 2

        @agent_tool
        def triple(x: int) -> int:
            """T.

            Args:
                x: X.
            """
            return x * 3

        def model(messages, params):
            from calfkit_tpu.models.messages import ModelRequest, ToolReturnPart

            returns = sorted(
                str(p.content)
                for m in messages
                if isinstance(m, ModelRequest)
                for p in m.parts
                if isinstance(p, ToolReturnPart)
            )
            if not returns:
                # TWO calls in one turn: a durable fan-out batch through the
                # kafka-backed ktables store
                return ModelResponse(parts=[
                    ToolCallOutput(tool_call_id="c1", tool_name="double",
                                   args={"x": 10}),
                    ToolCallOutput(tool_call_id="c2", tool_name="triple",
                                   args={"x": 10}),
                ])
            return ModelResponse(parts=[TextOutput(text=" ".join(returns))])

        agent = Agent("kagent", model=FunctionModelClient(model),
                      tools=[double, triple], description="kafka-lane agent")

        mesh = KafkaMesh(kafka_fake_broker)
        async with Worker([agent, double, triple], mesh=mesh,
                          owns_transport=True):
            client = Client.connect(KafkaMesh(kafka_fake_broker))
            handle = await client.agent("kagent").start("go", timeout=30)
            step_kinds = []
            output = None
            async for event in handle.stream():
                step = getattr(event, "step", None)
                if step is not None:
                    step_kinds.append(step.kind)
                else:
                    output = event.output
            assert output == "20 30"
            assert step_kinds.count("tool_call") == 2
            assert step_kinds.count("tool_result") == 2
            # the live directory read through kafka-backed views
            cards = await client.mesh_directory.get_agents()
            assert [c.name for c in cards] == ["kagent"]
            await client.close()

    async def test_worker_restart_resumes_on_same_group(self, kafka_fake_broker):
        """Second worker incarnation on the same broker world serves new
        runs — consumer groups + committed offsets survive the restart."""
        from calfkit_tpu.client import Client
        from calfkit_tpu.engine import TestModelClient
        from calfkit_tpu.mesh.kafka import KafkaMesh
        from calfkit_tpu.nodes import Agent
        from calfkit_tpu.worker import Worker

        def make_agent():
            return Agent(
                "phoenix", model=TestModelClient(custom_output_text="alive"),
                description="restartable",
            )

        async with Worker([make_agent()], mesh=KafkaMesh(kafka_fake_broker),
                          owns_transport=True):
            client = Client.connect(KafkaMesh(kafka_fake_broker))
            r = await client.agent("phoenix").execute("one", timeout=30)
            assert r.output == "alive"
            await client.close()

        async with Worker([make_agent()], mesh=KafkaMesh(kafka_fake_broker),
                          owns_transport=True):
            client = Client.connect(KafkaMesh(kafka_fake_broker))
            r = await client.agent("phoenix").execute("two", timeout=30)
            assert r.output == "alive"
            await client.close()
