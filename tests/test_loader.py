"""HF checkpoint loader (r5: previously untested e2e).

Builds a REAL safetensors checkpoint on disk in HF Llama naming (the
transposed [out, in] projection layout), then pins:
- config.json → ModelConfig mapping,
- bf16 load: loaded params serve with logit parity against the same
  weights constructed directly,
- int8 / int4 host-side quantized load: quantized leaf structure +
  engine serves end to end from the loaded tree,
- sharded placement on a tp mesh.

Reference anchor: checkpointing-is-loading (SURVEY §5); the reference
has no local model path — this is the TPU-build's equivalent of its
provider-credential plumbing tests.
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from calfkit_tpu.inference import model as M
from calfkit_tpu.inference.config import preset
from calfkit_tpu.inference.loader import config_from_hf, load_params
from calfkit_tpu.inference.sharding import make_mesh, param_shardings

CFG = preset("debug")


@pytest.fixture(scope="module")
def checkpoint(tmp_path_factory):
    """A debug-sized HF-style checkpoint whose weights equal
    init_params(CFG, key(0)) — so loads can be compared elementwise."""
    from safetensors.numpy import save_file

    path = tmp_path_factory.mktemp("hf-ckpt")
    params = M.init_params(CFG, jax.random.key(0), dtype=jnp.float32)
    D, H, K, hd = CFG.d_model, CFG.n_heads, CFG.n_kv_heads, CFG.head_dim
    def c(arr: np.ndarray) -> np.ndarray:
        # safetensors serializes the underlying buffer: a transposed VIEW
        # would silently store the un-transposed bytes
        return np.ascontiguousarray(arr)

    tensors: dict[str, np.ndarray] = {
        "model.embed_tokens.weight": np.asarray(params["embed"], np.float32),
        "model.norm.weight": np.asarray(params["final_norm"], np.float32),
    }
    if "lm_head" in params:
        tensors["lm_head.weight"] = c(np.asarray(params["lm_head"], np.float32).T)
    layers = params["layers"]
    for i in range(CFG.n_layers):
        pre = f"model.layers.{i}."
        tensors[pre + "self_attn.q_proj.weight"] = c(
            np.asarray(layers["wq"][i], np.float32).reshape(D, H * hd).T
        )
        tensors[pre + "self_attn.k_proj.weight"] = c(
            np.asarray(layers["wk"][i], np.float32).reshape(D, K * hd).T
        )
        tensors[pre + "self_attn.v_proj.weight"] = c(
            np.asarray(layers["wv"][i], np.float32).reshape(D, K * hd).T
        )
        tensors[pre + "self_attn.o_proj.weight"] = c(
            np.asarray(layers["wo"][i], np.float32).reshape(H * hd, D).T
        )
        tensors[pre + "mlp.gate_proj.weight"] = c(np.asarray(
            layers["w_gate"][i], np.float32).T)
        tensors[pre + "mlp.up_proj.weight"] = c(np.asarray(
            layers["w_up"][i], np.float32).T)
        tensors[pre + "mlp.down_proj.weight"] = c(np.asarray(
            layers["w_down"][i], np.float32).T)
        tensors[pre + "input_layernorm.weight"] = np.asarray(
            layers["attn_norm"][i], np.float32)
        tensors[pre + "post_attention_layernorm.weight"] = np.asarray(
            layers["mlp_norm"][i], np.float32)
    save_file(tensors, str(path / "model.safetensors"))
    (path / "config.json").write_text(json.dumps({
        "vocab_size": CFG.vocab_size,
        "hidden_size": CFG.d_model,
        "num_hidden_layers": CFG.n_layers,
        "num_attention_heads": CFG.n_heads,
        "num_key_value_heads": CFG.n_kv_heads,
        "intermediate_size": CFG.d_ff,
        "rope_theta": CFG.rope_theta,
        "rms_norm_eps": CFG.norm_eps,
        "max_position_embeddings": CFG.max_seq_len,
        "tie_word_embeddings": CFG.tie_embeddings,
    }))
    return path, params


class TestConfigFromHF:
    def test_maps_every_field(self, checkpoint):
        path, _params = checkpoint
        config = config_from_hf(path)
        for attr in ("vocab_size", "d_model", "n_layers", "n_heads",
                     "n_kv_heads", "d_ff", "rope_theta", "norm_eps",
                     "max_seq_len", "tie_embeddings"):
            assert getattr(config, attr) == getattr(CFG, attr), attr


class TestLoadParams:
    def _logits(self, params):
        B, S = 2, 8
        toks = jax.random.randint(jax.random.key(3), (B, S), 3, CFG.vocab_size)
        pos = jnp.broadcast_to(jnp.arange(S), (B, S))
        lens = jnp.full((B,), S)
        cache = M.make_empty_cache(CFG, B, 32, dtype=jnp.float32)
        out, _ = M.forward(params, CFG, toks, pos, cache, lens)
        return np.asarray(out, np.float32)

    def test_bf16_load_matches_direct_params(self, checkpoint):
        path, params = checkpoint
        config = config_from_hf(path)
        shardings = param_shardings(config, make_mesh(tp=1, dp=1))
        loaded = load_params(path, config, shardings)
        # loaded weights pass through the HF transpose/reshape round trip
        # and back: logits must match thedirectly-constructed fp32 params to
        # bf16 tolerance
        want = self._logits(params)
        got = self._logits(loaded)
        np.testing.assert_allclose(got, want, rtol=0.05, atol=0.05)

    @pytest.mark.parametrize("quantize", ["int8", "int4"])
    async def test_quantized_load_serves(self, checkpoint, quantize):
        from calfkit_tpu.inference.config import RuntimeConfig
        from calfkit_tpu.inference.engine import InferenceEngine
        from calfkit_tpu.inference.quant import (
            align_quant_sharding_keys,
            is_quantized,
            is_quantized4,
            quantize_shardings,
        )

        path, _params = checkpoint
        config = config_from_hf(path)
        bits = 8 if quantize == "int8" else 4
        shardings = quantize_shardings(
            param_shardings(config, make_mesh(tp=1, dp=1)), bits=bits
        )
        loaded = load_params(path, config, shardings, quantize=quantize)
        wq = loaded["layers"]["wq"]
        assert (is_quantized if bits == 8 else is_quantized4)(wq)
        engine = InferenceEngine(
            config,
            RuntimeConfig(max_batch_size=2, max_seq_len=64, prefill_chunk=16,
                          decode_steps_per_dispatch=4, quantization=quantize),
            params=loaded,
        )
        await engine.start()
        out = [t async for t in engine.generate([1, 5, 9], max_new_tokens=6)]
        assert len(out) == 6
        await engine.stop()

    def test_tp_sharded_placement(self, checkpoint):
        path, _params = checkpoint
        config = config_from_hf(path)
        mesh = make_mesh(tp=2, dp=1)
        loaded = load_params(path, config, param_shardings(config, mesh))
        spec = loaded["layers"]["wq"].sharding.spec
        assert "tp" in tuple(spec), spec

    def test_bits_mismatch_fails_loudly(self, checkpoint):
        path, _params = checkpoint
        config = config_from_hf(path)
        shardings = param_shardings(config, make_mesh(tp=1, dp=1))
        # shardings NOT expanded for quantization but int4 load requested
        with pytest.raises((ValueError, AttributeError, KeyError)):
            load_params(path, config, shardings, quantize="int4")
