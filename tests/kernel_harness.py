"""Shared harness for kernel tests: deploy nodes on an InMemoryMesh the way
the Worker will, plus a scripted caller that collects replies."""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field

from calfkit_tpu import protocol
from calfkit_tpu.keying import partition_key
from calfkit_tpu.mesh import InMemoryMesh, Record
from calfkit_tpu.models import (
    CallFrame,
    Envelope,
    SessionContext,
    State,
    StepMessage,
    WorkflowState,
)
from calfkit_tpu.models.payload import ContentPart
from calfkit_tpu.models.session_context import new_id
from calfkit_tpu.nodes import FANOUT_STORE_KEY, KtablesFanoutBatchStore
from calfkit_tpu.nodes.base import BaseNodeDef

INBOX = "test.caller.inbox"


async def deploy(mesh: InMemoryMesh, *nodes: BaseNodeDef) -> None:
    for node in nodes:
        node.bind(mesh)
        if FANOUT_STORE_KEY not in node.resources:
            store = KtablesFanoutBatchStore(mesh, node.node_id)
            await store.start()
            node.resources[FANOUT_STORE_KEY] = store
        topics = list(node.input_topics()) + [node.return_topic()]
        await mesh.subscribe(topics, node.handler, group_id=node.name)


@dataclass
class Caller:
    """Collects replies + steps landing on the test inbox."""

    mesh: InMemoryMesh
    replies: list[tuple[dict, Envelope]] = field(default_factory=list)
    steps: list[StepMessage] = field(default_factory=list)

    async def start(self) -> None:
        await self.mesh.subscribe(
            [INBOX], self._on_record, group_id=None, from_latest=False, ordered=False
        )

    async def _on_record(self, record: Record) -> None:
        if record.headers.get(protocol.HDR_WIRE) == "step":
            self.steps.append(StepMessage.from_wire(record.value))
        else:
            self.replies.append((dict(record.headers), Envelope.from_wire(record.value)))

    async def call(
        self,
        target_topic: str,
        parts: list[ContentPart],
        *,
        route: str = "run",
        state: State | None = None,
        task_id: str | None = None,
        correlation_id: str | None = None,
    ) -> str:
        task = task_id or new_id()
        env = Envelope(
            context=SessionContext(state=state or State()),
            workflow=WorkflowState(
                frames=[
                    CallFrame(
                        target_topic=target_topic,
                        callback_topic=INBOX,
                        route=route,
                        payload=parts,
                        caller_kind="client",
                        caller_name="test",
                    )
                ]
            ),
        )
        await self.mesh.publish(
            target_topic,
            env.to_wire(),
            key=partition_key(task),
            headers={
                protocol.HDR_KIND: "call",
                protocol.HDR_WIRE: "envelope",
                protocol.HDR_ROUTE: route,
                protocol.HDR_TASK: task,
                protocol.HDR_CORRELATION: correlation_id or task,
                protocol.HDR_EMITTER: "client/test",
            },
        )
        return task

    async def wait_reply(self, n: int = 1, timeout: float = 5.0):
        deadline = asyncio.get_event_loop().time() + timeout
        while len(self.replies) < n:
            if asyncio.get_event_loop().time() > deadline:
                raise TimeoutError(
                    f"expected {n} replies, got {len(self.replies)}"
                )
            await asyncio.sleep(0.01)
        return self.replies[n - 1]
