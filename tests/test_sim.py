"""The fleet simulator + perf gate (ISSUE 11).

Four layers of coverage:

1. **seams** — the virtual event clock (ordering, ties, advance
   semantics), deterministic id minting, the scenario DSL (arrival
   processes, diurnal curve shape, scaling laws, check evaluation).
2. **runner** — one small scenario through the REAL
   mesh→worker→router path: completion, routing spread, prefix model,
   scripted kill/heal, lease churn against the real compacted table.
3. **determinism** — the acceptance law: the same scenario twice with
   the same seed is BYTE-identical (modulo the capture block); a
   different seed still passes every verdict.  The full pinned suite
   version is marked ``slow`` (CI's offline lane); a single-scenario
   version stays in tier-1.
4. **the gate** — ``scripts/perf_gate.py`` logic: baseline round-trip,
   tolerance bands, the seeded-regression seam (a worst-loaded policy
   MUST trip the gate), and the ``ck sim`` renderer.
"""

import asyncio
import importlib.util
import json
import os
import sys

import pytest

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from calfkit_tpu.sim import (  # noqa: E402
    Check,
    LeaseChurn,
    LoadPhase,
    ReplicaEvent,
    Scenario,
    ServiceSpec,
    SimReport,
    SimRunner,
    TenantSpec,
    VirtualClock,
    deterministic_ids,
    diurnal_phases,
    strip_capture,
)
from calfkit_tpu.sim.report import flatten_metrics, metric_at, percentile  # noqa: E402
from calfkit_tpu.sim.suite import PINNED_SUITE, SUITE_NAME, scaled_suite  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_perf_gate():
    """Import scripts/perf_gate.py WITHOUT its argv/re-exec main path."""
    os.environ.setdefault("PYTHONHASHSEED", "0")
    spec = importlib.util.spec_from_file_location(
        "perf_gate", os.path.join(REPO, "scripts", "perf_gate.py")
    )
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


_SMOKE_CACHE: dict = {}


def smoke_report():
    """One shared SMOKE run for every test that only READS a report —
    the suite re-runs it fresh only where a second, independent run is
    the point (the determinism oracle).  Keeps tier-1 cost flat."""
    if "report" not in _SMOKE_CACHE:
        _SMOKE_CACHE["report"] = asyncio.run(SimRunner(SMOKE).run())
    return _SMOKE_CACHE["report"]


SMOKE = Scenario(
    name="smoke",
    replicas=6,
    seed=5,
    phases=(LoadPhase(duration_s=30.0, rate_rps=4.0),),
    service=ServiceSpec(base_s=0.5, per_token_s=0.02, slots=2),
    tenants=(TenantSpec("t0", sessions=3), TenantSpec("t1", sessions=3)),
    checks=(
        Check("all_complete", "requests.completion_ratio", "==", 1.0),
        Check("no_faults", "requests.failed", "==", 0.0),
    ),
    gated=("requests.completed",),
)


# ---------------------------------------------------------------- seams
class TestVirtualEventClock:
    def test_schedule_fires_in_time_then_insertion_order(self):
        clock = VirtualClock(1000.0)
        fired = []
        clock.schedule(1002.0, lambda: fired.append("b"))
        clock.schedule(1001.0, lambda: fired.append("a"))
        clock.schedule(1002.0, lambda: fired.append("c"))  # tie: after b
        clock.advance(5.0)
        assert fired == ["a", "b", "c"]
        assert clock.now == 1005.0

    def test_callback_sees_its_own_timestamp(self):
        clock = VirtualClock(0.0)
        seen = []
        clock.schedule(3.0, lambda: seen.append(clock.now))
        clock.schedule(7.0, lambda: seen.append(clock.now))
        clock.advance(10.0)
        assert seen == [3.0, 7.0]

    def test_callbacks_can_schedule_relative_work(self):
        clock = VirtualClock(0.0)
        fired = []

        def first():
            fired.append(clock.now)
            clock.schedule(clock.now + 2.0, lambda: fired.append(clock.now))

        clock.schedule(1.0, first)
        clock.advance(10.0)
        assert fired == [1.0, 3.0]

    def test_advance_to_next_and_past_scheduling_clamps(self):
        clock = VirtualClock(100.0)
        fired = []
        clock.schedule(50.0, lambda: fired.append("past"))  # clamped to now
        assert clock.next_event_at == 100.0
        assert clock.advance_to_next() is True
        assert fired == ["past"]
        assert clock.advance_to_next() is False


class TestDeterministicIds:
    def test_seeded_and_restored(self):
        import uuid

        with deterministic_ids(9):
            a = [uuid.uuid4() for _ in range(3)]
        with deterministic_ids(9):
            b = [uuid.uuid4() for _ in range(3)]
        with deterministic_ids(10):
            c = [uuid.uuid4() for _ in range(3)]
        assert a == b
        assert a != c
        assert all(u.version == 4 for u in a)
        # restored: two live mints virtually never collide
        assert uuid.uuid4() != uuid.uuid4()


class TestScenarioDsl:
    def test_arrival_times_deterministic_and_phase_bounded(self):
        import random

        sc = Scenario(
            name="x", replicas=2,
            phases=(
                LoadPhase(10.0, 2.0),
                LoadPhase(5.0, 0.0),  # silent gap
                LoadPhase(10.0, 2.0),
            ),
        )
        a = list(sc.arrival_times(random.Random(3)))
        b = list(sc.arrival_times(random.Random(3)))
        assert a == b
        assert a == sorted(a)
        assert all(0.0 <= t < 25.0 for t in a)
        # nothing arrives inside the silent phase
        assert not [t for t in a if 10.0 <= t < 15.0]

    def test_diurnal_curve_shape(self):
        phases = diurnal_phases(
            hours=24.0, trough_rps=1.0, peak_rps=9.0, steps=24
        )
        assert len(phases) == 24
        assert sum(p.duration_s for p in phases) == 24 * 3600.0
        rates = [p.rate_rps for p in phases]
        # trough at the edges, peak mid-day, symmetric-ish
        assert rates[0] < rates[11] and rates[-1] < rates[12]
        assert max(rates) <= 9.0 and min(rates) >= 1.0

    def test_scaling_preserves_per_replica_load_and_verdicts(self):
        sc = Scenario(
            name="x", replicas=40, seed=1,
            phases=(LoadPhase(10.0, 8.0),),
            tenants=(TenantSpec("t", sessions=20),),
            leases=LeaseChurn(callers=1000),
            events=(ReplicaEvent(5.0, "kill", 30),),
            checks=(Check("pop", "leases.minted", ">=", 1000.0),),
        )
        small = sc.scaled(0.1)
        assert small.replicas == 4
        assert small.phases[0].rate_rps == pytest.approx(0.8)
        assert small.events[0].replica == 3  # clamped into the fleet
        assert small.tenants[0].sessions == 2
        assert small.leases.callers == 100
        assert small.checks[0].bound == pytest.approx(100.0)

    def test_check_ops_and_missing_metric_fails(self):
        check = Check("c", "a.b", "<=", 2.0)
        assert check.evaluate(2.0) and not check.evaluate(2.5)
        assert not check.evaluate(None)  # absent metric is NOT a pass
        with pytest.raises(ValueError):
            Check("c", "a.b", "~=", 1.0)
        with pytest.raises(ValueError):
            ReplicaEvent(1.0, "explode", 0)

    def test_metric_helpers(self):
        tree = {"a": {"b": 2, "flag": True, "s": "x"}, "n": 1.5}
        assert metric_at(tree, "a.b") == 2.0
        assert metric_at(tree, "a.missing") is None
        assert metric_at(tree, "a.flag") is None  # bools are not metrics
        flat = flatten_metrics(tree)
        assert flat == {"a.b": 2.0, "n": 1.5}
        assert percentile([], 0.95) == 0.0
        assert percentile([1.0, 2.0, 10.0], 0.95) == 10.0


# --------------------------------------------------------------- runner
class TestSimRunner:
    def test_smoke_scenario_real_path(self):
        report = smoke_report()
        assert report.passed
        offered = report.metric("requests.offered")
        assert offered and offered > 50
        assert report.metric("requests.completed") == offered
        served = report.metrics["routing"]["per_replica"]
        # every replica served traffic: the router spread the fleet
        assert len(served) == 6 and all(s > 0 for s in served)
        assert report.metric("prefix.hit_rate") > 0.5  # 6 sessions repeat
        assert report.metric("tokens.tokens_per_dispatch") == 8.0
        assert report.metric("time.makespan_s") < 60.0

    def test_kill_and_heal_with_failover(self):
        sc = Scenario(
            name="heal", replicas=4, seed=8,
            phases=(LoadPhase(duration_s=90.0, rate_rps=2.0),),
            policy="least-loaded",
            service=ServiceSpec(base_s=0.8, per_token_s=0.02, slots=2),
            failover=True,
            heartbeat_every_s=5.0,
            stale_after_s=15.0,
            events=(
                ReplicaEvent(20.0, "kill", 1),
                ReplicaEvent(60.0, "resume", 1),
            ),
            per_replica_report=False,
            checks=(
                Check("all", "requests.completion_ratio", "==", 1.0),
                Check("dead_dark", "routing.delivered_while_dead", "==", 0.0),
                Check("healed", "routing.delivered_after_heal", ">=", 1.0),
            ),
        )
        report = asyncio.run(SimRunner(sc).run())
        assert report.passed, [c for c in report.checks if not c.passed]
        assert report.metric("routing.failover_arrivals") >= 1

    def test_lease_churn_folds_real_table(self):
        sc = Scenario(
            name="leases", replicas=2, seed=4,
            phases=(LoadPhase(duration_s=60.0, rate_rps=0.5),),
            leases=LeaseChurn(
                callers=200, ttl_s=10.0, beat_every_s=8.0,
                min_life_s=5.0, max_life_s=30.0,
                clean_release_ratio=0.5,
            ),
            checks=(
                Check("all", "requests.completion_ratio", "==", 1.0),
                Check("minted", "leases.minted", "==", 200.0),
                Check("lapsed", "leases.lapsed", ">=", 1.0),
            ),
        )
        report = asyncio.run(SimRunner(sc).run())
        assert report.passed, [c for c in report.checks if not c.passed]
        stats = report.metrics["leases"]
        assert stats["table_records"] > 0
        # clean releases tombstone their table record
        assert stats["released"] > 0

    def test_cap_evicts_released_corpses_before_live_leases(self):
        """Review-caught regression guard (ISSUE 11): the amortized
        prune's O(1) LRU backstop must consume released tombstones
        before it can ever touch a LIVE lease — an evicted live lease
        reads never-seen = alive forever and permanently un-reaps its
        runs.  Released entries therefore park at the LRU front."""
        from calfkit_tpu import leases
        from calfkit_tpu.sim import virtual_clock
        from calfkit_tpu.sim.runner import fresh_lease_store

        with virtual_clock(), fresh_lease_store():
            cap = leases._BEAT_CAP
            for i in range(cap):
                leases.note_beat(f"live-{i:05d}", 30.0)
            for i in range(0, cap, 2):
                leases.release_lease(f"live-{i:05d}")
            # churn well past one amortization window of fresh inserts:
            # every eviction must land on a released corpse
            for i in range(cap // 2):
                leases.note_beat(f"fresh-{i:05d}", 30.0)
            store = leases.active_leases()
            assert len(store) <= cap
            evicted_live = [
                f"live-{i:05d}"
                for i in range(1, cap, 2)
                if f"live-{i:05d}" not in store
            ]
            assert not evicted_live, (
                f"{len(evicted_live)} live leases evicted while released "
                "corpses survived"
            )
            assert all(f"fresh-{i:05d}" in store for i in range(cap // 2))

    def test_lease_store_isolated_between_runs(self):
        from calfkit_tpu import leases

        before = dict(leases.active_leases())
        sc = Scenario(
            name="leases", replicas=2, seed=4,
            phases=(LoadPhase(duration_s=20.0, rate_rps=0.5),),
            leases=LeaseChurn(callers=50, min_life_s=5.0, max_life_s=10.0),
        )
        asyncio.run(SimRunner(sc).run())
        assert dict(leases.active_leases()) == before


class TestFailoverUncharge:
    """The simulator-caught bug (ISSUE 11): abandoning a dead placement
    must clear the router's least-request entry for the corpse — no
    terminal will ever fire the done-callback that normally clears it,
    and a healed replica carrying phantom in-flight load is starved by
    least-loaded routing for the whole TTL."""

    def test_failover_uncharges_the_corpse(self):
        from calfkit_tpu.client import Client
        from calfkit_tpu.fleet import FleetRouter
        from calfkit_tpu.fleet.failover import FailoverPolicy
        from calfkit_tpu.mesh import InMemoryMesh
        from calfkit_tpu.sim import (
            FleetTopology,
            SimEngineModel,
            settle,
            virtual_clock,
        )

        async def scenario() -> None:
            with deterministic_ids(3), virtual_clock() as clock:
                mesh = InMemoryMesh()
                service = ServiceSpec(base_s=50.0, per_token_s=0.0, slots=2)
                models = [
                    SimEngineModel(clock, index=i, service=service)
                    for i in range(2)
                ]
                topo = FleetTopology(
                    mesh, models, heartbeat_interval=1e6,
                    stale_multiplier=1.0,
                )
                async with topo:
                    router = FleetRouter(
                        mesh, "least-loaded", stale_after=15.0
                    )
                    client = Client.connect(mesh, router=router)
                    await router.start()
                    await topo.beat_all()
                    await settle(
                        lambda: len(router.registry.eligible("svc")) == 2,
                        interval=0, ticks=5000,
                    )
                    task = asyncio.ensure_future(
                        client.agent("svc").execute(
                            "corpse-uncharge probe",
                            timeout=3600,
                            failover=FailoverPolicy(
                                probe_interval=0.0, max_failovers=2
                            ),
                        )
                    )
                    # the tie-broken least-loaded pick: lowest replica key
                    victim = topo.index_of_lowest_key()
                    survivor = 1 - victim
                    await settle(
                        lambda: models[victim].active == 1,
                        interval=0, ticks=5000,
                    )
                    victim_key = topo.replica_key(victim)
                    assert router._outstanding(victim_key) == 1
                    topo.kill(victim)
                    clock.advance(16.0)  # stale, but its 50s service isn't due
                    # re-stamp the survivor (the corpse's beat is dropped
                    # by its dead transport — its stamp stays frozen)
                    await topo.beat_all()
                    await settle(
                        lambda: models[survivor].active == 1,
                        interval=0, ticks=20_000,
                        message="failover re-dispatch never landed",
                    )
                    # THE law: the corpse is uncharged the moment the
                    # supervisor abandons the placement — not at TTL
                    assert router._outstanding(victim_key) == 0
                    # walk time to the survivor's completion in sub-stale
                    # steps with beats between (one long advance would
                    # stale the survivor's advert and the supervisor
                    # would — correctly — declare IT dead too)
                    for _ in range(6):
                        clock.advance(10.0)
                        await topo.beat_all()
                        for _ in range(40):
                            await asyncio.sleep(0)
                    await settle(lambda: task.done(), interval=0, ticks=20_000)
                    result = await task
                    assert result.output is not None
                    assert router._outstanding(topo.replica_key(survivor)) == 0
                    await client.close()
                await mesh.stop()

        asyncio.run(scenario())


# ---------------------------------------------------------- determinism
class TestDeterminism:
    def test_same_seed_byte_identical_single_scenario(self):
        """Tier-1's fast determinism oracle: one scenario, twice."""
        a = smoke_report()
        b = asyncio.run(SimRunner(SMOKE).run())
        assert json.dumps(a.to_dict(), sort_keys=True) == json.dumps(
            b.to_dict(), sort_keys=True
        )

    def test_different_seed_differs_but_verdicts_hold(self):
        from dataclasses import replace

        a = smoke_report()
        b = asyncio.run(SimRunner(replace(SMOKE, seed=6)).run())
        assert a.metrics != b.metrics
        assert a.passed and b.passed

    @pytest.mark.slow
    def test_pinned_suite_byte_identical_and_seed_robust(self):
        """The ISSUE-11 acceptance law, full shape (scaled for CI): the
        whole pinned suite twice with the same seeds → byte-identical
        SIM.json modulo the capture block; every scenario re-seeded →
        verdicts still pass."""
        from dataclasses import replace

        async def run_suite(bump: int = 0) -> SimReport:
            report = SimReport(suite=SUITE_NAME)
            for scenario in scaled_suite(0.15):
                if bump:
                    scenario = replace(scenario, seed=scenario.seed + bump)
                report.scenarios.append(
                    await SimRunner(scenario).run()
                )
            return report

        first = asyncio.run(run_suite())
        second = asyncio.run(run_suite())
        doc_a = strip_capture(first.to_dict(capture={"captured_at": "A"}))
        doc_b = strip_capture(second.to_dict(capture={"captured_at": "B"}))
        assert json.dumps(doc_a, sort_keys=True) == json.dumps(
            doc_b, sort_keys=True
        )
        reseeded = asyncio.run(run_suite(bump=1000))
        assert reseeded.passed, [
            (s.name, [c for c in s.checks if not c.passed])
            for s in reseeded.scenarios
            if not s.passed
        ]


# -------------------------------------------------------------- the gate
class TestPerfGate:
    def test_baseline_round_trip_passes(self):
        gate = _load_perf_gate()
        report = SimReport(suite=SUITE_NAME)
        report.scenarios.append(smoke_report())
        baseline = gate.baseline_from(report)
        assert gate.compare_to_baseline(report, baseline) == []

    def test_tolerance_band_and_exact_metrics(self):
        gate = _load_perf_gate()
        report = SimReport(suite=SUITE_NAME)
        report.scenarios.append(smoke_report())
        baseline = gate.baseline_from(report)
        entry = baseline["scenarios"]["smoke"]["requests.completed"]
        # requests.completed is an EXACT metric: zero tolerance
        assert entry["rel_tol"] == 0.0 and entry["abs_tol"] == 0.0
        entry["value"] += 1
        problems = gate.compare_to_baseline(report, baseline)
        assert problems and "requests.completed" in problems[0]

    def test_missing_gated_metric_is_a_regression(self):
        gate = _load_perf_gate()
        report = SimReport(suite=SUITE_NAME)
        report.scenarios.append(smoke_report())
        baseline = gate.baseline_from(report)
        baseline["scenarios"]["smoke"].pop("requests.completed")
        assert gate.compare_to_baseline(report, baseline)
        assert gate.compare_to_baseline(
            report, {"scenarios": {}}
        )  # absent scenario = regression too

    def test_seeded_regression_trips_the_gate(self):
        """The acceptance demonstration: a deliberately degraded routing
        policy (worst-loaded placement) against a healthy baseline must
        FAIL the gate — on the skew verdict, the baseline band, or
        both."""
        gate = _load_perf_gate()
        scenario = Scenario(
            name="smoke",  # same name: compares against smoke's baseline
            replicas=SMOKE.replicas,
            seed=SMOKE.seed,
            phases=SMOKE.phases,
            service=SMOKE.service,
            tenants=SMOKE.tenants,
            checks=SMOKE.checks
            + (Check("skew", "routing.skew_p95_over_mean", "<=", 1.7),),
            gated=SMOKE.gated + ("routing.skew_p95_over_mean",),
        )
        healthy = SimReport(suite=SUITE_NAME)
        healthy.scenarios.append(asyncio.run(SimRunner(scenario).run()))
        assert healthy.passed
        baseline = gate.baseline_from(healthy)

        degraded = SimReport(suite=SUITE_NAME)
        degraded.scenarios.append(
            asyncio.run(
                SimRunner(scenario, policy=gate._WorstLoaded()).run()
            )
        )
        problems = gate.compare_to_baseline(degraded, baseline)
        assert problems, "a worst-loaded policy must trip the gate"
        # and the degradation is visible in the metric itself
        assert degraded.scenarios[0].metric(
            "routing.skew_p95_over_mean"
        ) > healthy.scenarios[0].metric("routing.skew_p95_over_mean")

    def test_committed_sim_artifact_matches_suite(self):
        """SIM.json at the repo root is the pinned suite's output: every
        pinned scenario present, every verdict green, capture block
        carries provenance."""
        with open(os.path.join(REPO, "SIM.json")) as f:
            document = json.load(f)
        assert document["suite"] == SUITE_NAME
        assert document["passed"] is True
        names = {s["name"] for s in document["scenarios"]}
        assert names == {s.name for s in PINNED_SUITE}
        for scenario in document["scenarios"]:
            assert scenario["passed"], scenario["name"]
        assert document["capture"].get("captured_at")

    def test_committed_baseline_covers_gated_metrics(self):
        with open(os.path.join(REPO, "SIM_BASELINE.json")) as f:
            baseline = json.load(f)
        for scenario in PINNED_SUITE:
            entry = baseline["scenarios"][scenario.name]
            assert set(entry) == set(scenario.gated)


# ------------------------------------------------- bench staleness stamp
class TestBenchStaleStamp:
    """ISSUE 11 satellite: a cache file stamped ``stale_reason`` can
    never again be reported as current, no matter what the sha diff
    says — and the committed r05 artifacts carry the stamp."""

    def test_stamped_cache_forces_stale(self, monkeypatch, capsys):
        sys.path.insert(0, REPO)
        import bench

        monkeypatch.delenv("JAX_PLATFORMS", raising=False)
        monkeypatch.setattr(
            bench, "_probe_accelerator",
            lambda timeout_s=120: (False, "no chip answered", "absent"),
        )
        # even if the code diff says "clean", the stamp wins
        monkeypatch.setattr(bench, "_cache_is_stale_code", lambda c: False)
        bench.main()
        out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["status"] == "stale"
        assert "STALE" in out["error"]

    def test_r05_artifacts_are_stamped(self):
        for name in ("BENCH_TPU_CACHE.json", "BENCH_r05.json"):
            with open(os.path.join(REPO, name)) as f:
                doc = json.load(f)
            assert doc["status"] == "stale", name
            reason = doc["stale_reason"]
            assert reason["code"] and reason["detail"], name


# ----------------------------------------------------------------- shim
class TestChaosShim:
    def test_legacy_imports_still_resolve(self):
        import tests._chaos as shim
        from calfkit_tpu import sim

        for name in (
            "VirtualClock", "virtual_clock", "ChaosScript", "BrokerChaos",
            "settle", "assert_engine_drained", "FleetTopology",
            "ReplicaTransport", "ServingStubModel", "StreamingStubModel",
            "BijectiveTokenizer",
        ):
            assert getattr(shim, name) is getattr(sim, name), name
        assert "DEPRECATED" in (shim.__doc__ or "")


# ------------------------------------------------------------------ CLI
class TestCkSim:
    def test_render_sim_table(self):
        from calfkit_tpu.cli.sim import render_sim_table

        report = SimReport(suite=SUITE_NAME)
        report.scenarios.append(smoke_report())
        doc = report.to_dict(capture={"captured_at": "T", "wall_s": 1.0})
        text = render_sim_table(doc)
        assert "SCENARIO" in text and "smoke" in text
        assert "pass" in text
        assert "not a gated metric" in text  # wall time is provenance only

        # failed checks always expand
        doc["scenarios"][0]["checks"][0]["passed"] = False
        doc["scenarios"][0]["passed"] = False
        text = render_sim_table(doc)
        assert "FAIL" in text and "all_complete" in text

    def test_ck_registers_sim(self):
        from calfkit_tpu.cli.main import main as ck

        assert "sim" in ck.commands


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-q"]))
