"""A three-persona debate over ONE shared transcript.

Every response carries its author, and each agent receives the transcript
re-rendered from its own point of view: its own turns verbatim, the other
panelists' turns as attributed user-visible text (``<optimist> ...``).  No
agent ever sees another's tool calls or internals — only their public
surface.
"""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu.models.messages import ModelResponse  # noqa: E402
from calfkit_tpu.nodes import Agent  # noqa: E402
from examples._common import say, scripted  # noqa: E402

_LINES = {
    "optimist": [
        "Four-day weeks lift morale — energy compounds into output.",
        "The pilot data backs me: output held steady while attrition fell.",
    ],
    "skeptic": [
        "Compressing five days into four just moves the stress around.",
        "One pilot isn't proof; coordination costs bite at scale.",
    ],
    "pragmatist": [
        "Run a two-team pilot with clear metrics before any rollout.",
        "Both of you are right: pilot more teams, measure coordination "
        "overhead explicitly, decide in a quarter.",
    ],
}


def _persona(name: str) -> Agent:
    def turn(messages, params):
        # how many times THIS persona has spoken in the visible transcript
        spoken = sum(isinstance(m, ModelResponse) for m in messages)
        lines = _LINES[name]
        return say(lines[min(spoken, len(lines) - 1)])(messages, params)

    return Agent(
        name,
        model=scripted(turn, name=f"{name}-model"),
        instructions=f"You are the {name} on a debate panel. Stay in character.",
        description=f"The {name} on the panel.",
    )


optimist = _persona("optimist")
skeptic = _persona("skeptic")
pragmatist = _persona("pragmatist")

PANEL = [optimist, skeptic, pragmatist]
