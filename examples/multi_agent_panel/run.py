"""Drive two debate rounds: each panelist extends the SAME transcript.

The caller owns the shared history — ``execute(..., message_history=...)``
sends it with each turn, and the returned state carries it back extended.
Author attribution on every response is what lets each agent's POV
projection tell "my turn" from "their turn".

Run:  python examples/multi_agent_panel/run.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu import Client, Worker  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402

from panel import PANEL  # noqa: E402

TOPIC = "Motion: this company should move to a four-day work week."


async def main() -> None:
    mesh = InMemoryMesh()
    async with Worker(PANEL, mesh=mesh, owns_transport=True):
        client = Client.connect(mesh)
        transcript = []
        print(f"{TOPIC}\n")
        for round_no in (1, 2):
            print(f"--- round {round_no}")
            for name in ("optimist", "skeptic", "pragmatist"):
                result = await client.agent(name).execute(
                    TOPIC if not transcript else "Respond to the panel so far.",
                    message_history=transcript,
                )
                transcript = result.state.message_history
                print(f"{name:>10}: {result.output}")
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
