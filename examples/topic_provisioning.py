"""Topic provisioning — inspect and create a node set's topics up front.

On brokers with auto-create disabled (hardened Kafka/Redpanda), producers
and consumers stall on topics that don't exist.  The provisioner derives
exactly which topics a node set touches and creates them idempotently, with
error classification (created / existing / unauthorized / retry) so an ACL
problem fails loudly instead of looking like a flaky broker.

This example shows:

* ``topics_for_nodes`` — which topics a topology references, WITHOUT
  contacting any broker (the agent contributes its tool's input topic on
  top of its own inboxes and publish topic);
* ``framework_topics_for_nodes`` — the compacted framework tables behind
  the same nodes (control plane + durable fan-out);
* programmatic ``provision()`` and its idempotency (a second pass is a
  no-op: racing workers are fine);
* the common path — every ``Worker`` provisions its nodes' topics at boot
  through the same classifying path; tune it with
  ``Worker(..., provisioning=ProvisioningConfig(...))``.

Run:  python examples/topic_provisioning.py

The same one-off provisioning is available from the CLI::

    ck topics provision examples/quickstart/weather_agent.py:weather_agent \
        --mesh tcp://localhost:7337
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402
from calfkit_tpu.nodes import Agent, agent_tool, consumer  # noqa: E402
from calfkit_tpu.provisioning import (  # noqa: E402
    ProvisioningConfig,
    framework_topics_for_nodes,
    provision,
    topics_for_nodes,
)


@agent_tool
def get_weather(city: str) -> str:
    """Get the weather for a city.

    Args:
        city: Which city.
    """
    return f"sunny in {city}"


weather_agent = Agent(
    "weather_agent",
    model=TestModelClient(),
    tools=[get_weather],
    description="Answers weather questions.",
)


@consumer(topics=["agent.weather_agent.publish"])
async def weather_sink(ctx) -> None:
    pass


NODES = [weather_agent, get_weather, weather_sink]


async def main() -> None:
    print("plain topics (derived offline, no broker contact):")
    for topic in topics_for_nodes(NODES):
        print(f"  {topic}")
    print("compacted framework tables:")
    for topic in framework_topics_for_nodes(NODES):
        print(f"  {topic}")

    mesh = InMemoryMesh()
    await mesh.start()
    config = ProvisioningConfig(max_attempts=5, retry_backoff_s=0.2)
    report = await provision(mesh, NODES, config)
    print(
        f"provisioned: {len(report['plain'])} plain + "
        f"{len(report['compacted'])} compacted"
    )
    # idempotent: a second pass (e.g. a racing worker) succeeds quietly
    await provision(mesh, NODES, config)
    print("second pass: ok (already-exists is success, not an error)")
    await mesh.stop()


if __name__ == "__main__":
    asyncio.run(main())
