"""Multi-agent example: a coordinator that messages a researcher and can
hand the conversation to a writer — discovery, messaging, and handoff in one
mesh.

Run:  python -m calfkit_tpu.cli.main dev run \\
          examples/multi_agent/research_team.py:TEAM --agent coordinator
"""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu import Agent  # noqa: E402
from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.nodes import Tools, agent_tool  # noqa: E402
from calfkit_tpu.peers import Handoff, Messaging  # noqa: E402


@agent_tool
def search_notes(query: str) -> list[str]:
    """Search the shared notebook.

    Args:
        query: What to look for.
    """
    return [f"note: {query} was discussed on Tuesday", f"note: {query} pending"]


researcher = Agent(
    "researcher",
    model=TestModelClient(custom_output_text="Research summary: all clear."),
    instructions="Dig into questions using the notebook.",
    tools=Tools(discover=True),
    description="Researches questions against the shared notebook.",
)

writer = Agent(
    "writer",
    model=TestModelClient(custom_output_text="Here is the polished write-up."),
    instructions="Write the final answer beautifully.",
    description="Writes polished final answers.",
)

coordinator = Agent(
    "coordinator",
    model=TestModelClient(custom_output_text="Delegating complete."),
    instructions="Coordinate: ask the researcher, then hand off to the writer.",
    peers=[Messaging("researcher"), Handoff("writer")],
    description="Routes work between the researcher and the writer.",
)


@coordinator.instructions_fn
def _dynamic(ctx) -> str:
    return (
        "Coordinate the team. The current task id is "
        f"{ctx.task_id[:8]}. Ask the researcher for facts; hand off to the "
        "writer for the final answer."
    )


TEAM = [coordinator, researcher, writer, search_notes]
