"""Expense approval: a request climbs a handoff chain until someone is
authorized to clear it.

Each approver has a spending limit.  Within the limit it approves; above it,
it hands the WHOLE conversation to the next rung — the final approver
answers the original caller directly, and every hop is visible in the run's
step stream.
"""

import os
import re
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu.nodes import Agent  # noqa: E402
from calfkit_tpu.peers import Handoff  # noqa: E402
from examples._common import all_user_text, call, say, scripted  # noqa: E402


def _amount(messages) -> float:
    """Largest dollar figure anywhere in the visible conversation (after a
    handoff the original request is an EARLIER message, not the latest)."""
    figures = re.findall(r"\$([\d,]+(?:\.\d+)?)", all_user_text(messages))
    return max((float(f.replace(",", "")) for f in figures), default=0.0)


def _approver_model(title: str, limit: float, next_rung: str | None):
    def turn(messages, params):
        amount = _amount(messages)
        if amount <= limit or next_rung is None:
            return say(
                f"Approved by the {title} (${amount:,.0f} is within the "
                f"${limit:,.0f} limit)."
            )(messages, params)
        return call("handoff_to_agent", agent_name=next_rung)(messages, params)

    return scripted(turn, name=f"{title}-model")


team_lead = Agent(
    "team_lead",
    model=_approver_model("team lead", 500, "director"),
    instructions="Approve expenses up to $500; escalate anything larger.",
    peers=[Handoff("director")],
    description="First-line expense approval (limit $500).",
)

director = Agent(
    "director",
    model=_approver_model("director", 5_000, "vp"),
    instructions="Approve expenses up to $5,000; escalate anything larger.",
    peers=[Handoff("vp")],
    description="Second-line expense approval (limit $5,000).",
)

vp = Agent(
    "vp",
    model=_approver_model("VP", 100_000, None),
    instructions="You are the final authority on expenses.",
    description="Final expense authority.",
)

CHAIN = [team_lead, director, vp]
