"""Run three expense requests up the approval chain.

Run:  python examples/expense_approval/run.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu import Client, Worker  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402

from agents import CHAIN  # noqa: E402


async def main() -> None:
    mesh = InMemoryMesh()
    async with Worker(CHAIN, mesh=mesh, owns_transport=True):
        client = Client.connect(mesh)
        for amount in (120, 3_200, 48_000):
            handle = await client.agent("team_lead").start(
                f"Requesting approval for a ${amount:,} expense "
                "(conference travel)."
            )
            hops = []
            async for event in handle.stream():
                step = getattr(event, "step", None)
                if step is not None and step.kind == "handoff":
                    hops.append(getattr(step, "to_agent", "?"))
                elif step is None:
                    chain = " -> ".join(["team_lead", *hops])
                    print(f"${amount:>6,}: [{chain}] {event.output}")
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
