"""Shared example helpers.

Every example is runnable straight from a checkout::

    python examples/<name>/run.py

Each file begins with a two-line ``sys.path`` bootstrap (the script's
directory — not the repo root — is what Python puts on ``sys.path``), then
imports these helpers.  Nothing here is framework machinery: real
deployments ``pip install`` the package and point agents at a real model
via ``JaxLocalModelClient``; examples use deterministic scripted models so
they run anywhere, instantly, with zero weights.
"""

from __future__ import annotations

from typing import Any, Callable

from calfkit_tpu.engine import FunctionModelClient
from calfkit_tpu.models.messages import (
    ModelMessage,
    ModelRequest,
    ModelResponse,
    TextOutput,
    ToolCallOutput,
    ToolReturnPart,
    UserPart,
)

TurnFn = Callable[[list[ModelMessage], Any], ModelResponse]


def scripted(*turns: TurnFn, name: str = "scripted-model") -> FunctionModelClient:
    """A deterministic model that plays ``turns`` in order.

    The turn index is the number of model responses already in the
    (POV-projected) history — i.e. how many times THIS agent has spoken in
    the conversation it can see.  The last turn repeats if the conversation
    outlives the script.
    """

    def fn(messages: list[ModelMessage], params: Any) -> ModelResponse:
        i = sum(isinstance(m, ModelResponse) for m in messages)
        return turns[min(i, len(turns) - 1)](messages, params)

    return FunctionModelClient(fn, name=name)


def say(text: str) -> TurnFn:
    """A turn that answers with plain text."""

    def turn(messages: list[ModelMessage], params: Any) -> ModelResponse:
        return ModelResponse(parts=[TextOutput(text=text)])

    return turn


def call(tool_name: str, **args: Any) -> TurnFn:
    """A turn that calls one tool."""

    def turn(messages: list[ModelMessage], params: Any) -> ModelResponse:
        return ModelResponse(
            parts=[_tool_call(tool_name, args, seq=0)]
        )

    return turn


def call_many(*calls: tuple[str, dict[str, Any]]) -> TurnFn:
    """A turn that issues several tool calls in ONE response (fan-out)."""

    def turn(messages: list[ModelMessage], params: Any) -> ModelResponse:
        return ModelResponse(
            parts=[_tool_call(n, a, seq=i) for i, (n, a) in enumerate(calls)]
        )

    return turn


def _tool_call(name: str, args: dict[str, Any], *, seq: int) -> ToolCallOutput:
    import uuid

    return ToolCallOutput(
        tool_call_id=f"tc_{uuid.uuid4().hex[:8]}_{seq}",
        tool_name=name,
        args=args,
    )


def last_user_text(messages: list[ModelMessage]) -> str:
    """The most recent user-visible prompt text in the projected history."""
    from calfkit_tpu.models.payload import render_parts_as_text

    for message in reversed(messages):
        if isinstance(message, ModelRequest):
            for part in reversed(message.parts):
                if isinstance(part, UserPart):
                    if isinstance(part.content, str):
                        return part.content
                    return render_parts_as_text(part.content)
    return ""


def all_user_text(messages: list[ModelMessage]) -> str:
    """Every user-visible text in the projected history, joined.

    After a handoff, the ORIGINAL user prompt is an earlier message and the
    handing-off agent's briefing is the latest — scan everything."""
    from calfkit_tpu.models.payload import render_parts_as_text

    chunks: list[str] = []
    for message in messages:
        if isinstance(message, ModelRequest):
            for part in message.parts:
                if isinstance(part, UserPart):
                    chunks.append(
                        part.content
                        if isinstance(part.content, str)
                        else render_parts_as_text(part.content)
                    )
    return "\n".join(chunks)


def tool_replies(messages: list[ModelMessage]) -> list[str]:
    """Every tool-return text visible in the history, oldest first."""
    out: list[str] = []
    for message in messages:
        if isinstance(message, ModelRequest):
            for part in message.parts:
                if isinstance(part, ToolReturnPart):
                    out.append(
                        part.content
                        if isinstance(part.content, str)
                        else str(part.content)
                    )
    return out
