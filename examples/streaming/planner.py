"""A trip planner whose intermediate work streams live to the caller.

Every hop of a run — preamble text, each tool call, each tool result, the
final answer — is minted into the run's step stream and can be watched via
``handle.stream()`` while the run executes.
"""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.nodes import Agent, agent_tool  # noqa: E402


@agent_tool
def find_flights(origin: str, destination: str) -> list[dict]:
    """Find flights between two cities.

    Args:
        origin: Departure city.
        destination: Arrival city.
    """
    return [
        {"flight": "CK101", "depart": "08:05", "price_usd": 240},
        {"flight": "CK205", "depart": "13:40", "price_usd": 185},
    ]


@agent_tool
def find_hotels(city: str, nights: int = 2) -> list[dict]:
    """Find hotels in a city.

    Args:
        city: Where to stay.
        nights: How many nights.
    """
    return [
        {"hotel": "The Foundry", "rate_usd": 150},
        {"hotel": "Hotel Meridian", "rate_usd": 210},
    ]


planner = Agent(
    "trip_planner",
    model=TestModelClient(
        custom_output_text="Itinerary: fly CK205 at 13:40 ($185), stay two "
        "nights at The Foundry ($150/night). Total ~$485."
    ),
    instructions="Plan trips using your flight and hotel tools.",
    tools=[find_flights, find_hotels],
    description="Plans trips with live progress streaming.",
)

NODES = [planner, find_flights, find_hotels]
