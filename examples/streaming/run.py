"""Watch a run's intermediate work stream live via ``handle.stream()``.

Run:  python examples/streaming/run.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu import Client, Worker  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402

from planner import NODES  # noqa: E402


async def main() -> None:
    mesh = InMemoryMesh()
    async with Worker(NODES, mesh=mesh, owns_transport=True):
        client = Client.connect(mesh)
        handle = await client.agent("trip_planner").start(
            "Plan me a weekend in Lisbon, flying from Berlin."
        )
        async for event in handle.stream():
            step = getattr(event, "step", None)
            if step is None:  # the terminal event: the run's result
                print(f"\nRESULT: {event.output}")
                continue
            if step.kind == "tool_call":
                print(f"  -> calling {step.tool_name}({str(step.args)[:60]})")
            elif step.kind == "tool_result":
                print(f"  <- {step.tool_name}: {str(step.content)[:68]}")
            elif getattr(step, "text", ""):
                print(f"  [{step.kind}] {step.text[:72]}")
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
