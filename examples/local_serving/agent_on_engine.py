"""An agent served by the LOCAL inference engine — the TPU-native path.

Every other example uses deterministic scripted models so CI needs no
weights; this one runs the REAL serving stack end to end on the debug
preset (random weights, byte tokenizer): client -> mesh -> agent ->
JaxLocalModelClient -> continuous-batching engine with paged KV and
automatic prefix caching.  The second turn's prompt re-sends the same
instructions + history, so its prefill reuses the first turn's KV pages
— watch ``prefix_reused_tokens`` climb.

On real hardware, swap ``preset("debug")`` for
``JaxLocalModelClient(checkpoint="/path/to/llama-hf-dir",
runtime=RuntimeConfig(tp=8, quantization="int8", ...))``.

Run:
    python examples/local_serving/agent_on_engine.py
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import jax  # noqa: E402

# pin only the DEFAULT: an explicit JAX_PLATFORMS (e.g. tpu on real
# hardware) wins — some images' sitecustomize ignores the env var, so
# the config.update mirrors whatever the env resolved to
jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

from calfkit_tpu import Agent, Client, InMemoryMesh, Worker  # noqa: E402
from calfkit_tpu.inference.client import JaxLocalModelClient  # noqa: E402
from calfkit_tpu.inference.config import RuntimeConfig, preset  # noqa: E402


async def main() -> None:
    model = JaxLocalModelClient(
        config=preset("debug", max_seq_len=512),
        runtime=RuntimeConfig(
            max_batch_size=2,
            max_seq_len=512,
            prefill_chunk=16,
            decode_steps_per_dispatch=4,
            kv_layout="paged",
            page_size=16,
            num_kv_pages=160,
            chunked_prefill=True,
            prefix_cache=True,
        ),
        max_new_tokens=8,
    )
    agent = Agent(
        name="local",
        model=model,
        instructions=(
            "You are served by the local TPU-native engine. This "
            "instruction block spans several KV pages so the second "
            "turn's prefix reuse is visible in the stats."
        ),
    )
    mesh = InMemoryMesh()
    async with Worker([agent], mesh=mesh):
        client = Client.connect(mesh)
        await model.start()
        engine = model._engine
        for turn in (1, 2):
            result = await client.agent("local").execute(
                "say anything", timeout=120
            )
            print(
                f"turn {turn}: output={len(str(result.output))} chars, "
                f"reused so far="
                f"{engine.stats.prefix_reused_tokens} tokens"
            )
        assert engine.stats.prefix_reused_tokens > 0
        print(
            f"LOCAL ENGINE SERVED 2 turns; prefix cache reused "
            f"{engine.stats.prefix_reused_tokens} prompt tokens on turn 2"
        )
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
