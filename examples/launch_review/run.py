"""Run a launch review: three teams consulted in parallel, one verdict.

Run:  python examples/launch_review/run.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu import Client, Worker  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402

from agents import REVIEW  # noqa: E402


async def main() -> None:
    mesh = InMemoryMesh()
    async with Worker(REVIEW, mesh=mesh, owns_transport=True):
        client = Client.connect(mesh)
        result = await client.agent("release_manager").execute(
            "Review release v2.9.0 for Friday's launch."
        )
        print(result.output)
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
