"""Launch review: parallel consultation, local synthesis.

Unlike a handoff, the release manager keeps the conversation: it fans out to
engineering, security, and legal in ONE model turn (three ``message_agent``
calls dispatched as a durable parallel batch), then reads all three replies
and synthesizes the go/no-go itself.
"""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.nodes import Agent, agent_tool  # noqa: E402
from calfkit_tpu.peers import Messaging  # noqa: E402
from examples._common import (  # noqa: E402
    call_many,
    last_user_text,
    say,
    scripted,
    tool_replies,
)


@agent_tool
def scan_dependencies(release: str) -> dict:
    """Scan a release's dependency tree for known CVEs.

    Args:
        release: The release tag to scan.
    """
    return {"release": release, "critical": 0, "high": 1,
            "note": "one high CVE, patched in the pinned build"}


engineering = Agent(
    "engineering",
    model=TestModelClient(
        custom_output_text="Engineering: CI is green, rollback tested. GO."
    ),
    instructions="Assess release readiness from the engineering side.",
    description="Assesses build/CI/rollback readiness.",
)

security = Agent(
    "security",
    model=TestModelClient(
        custom_output_text="Security: scan shows one patched high CVE. GO."
    ),
    instructions="Scan the release and assess security risk.",
    tools=[scan_dependencies],
    description="Scans releases for vulnerabilities.",
)

legal = Agent(
    "legal",
    model=TestModelClient(
        custom_output_text="Legal: licenses audited, export review clear. GO."
    ),
    instructions="Check licensing and compliance.",
    description="Checks licensing and compliance.",
)


def _fan_out(messages, params):
    ask = last_user_text(messages)
    return call_many(
        *(
            ("message_agent", {"agent_name": team, "message": ask})
            for team in ("engineering", "security", "legal")
        )
    )(messages, params)


def _synthesize(messages, params):
    replies = tool_replies(messages)
    verdict = "GO" if all("GO" in r for r in replies) else "NO-GO"
    lines = "\n".join(f"  - {r}" for r in replies)
    return say(f"Launch review: {verdict}\n{lines}")(messages, params)


release_manager = Agent(
    "release_manager",
    model=scripted(_fan_out, _synthesize, name="release-manager-model"),
    instructions=(
        "Consult engineering, security, and legal in parallel, then issue "
        "the go/no-go yourself."
    ),
    peers=[Messaging("engineering", "security", "legal")],
    description="Runs launch reviews: consults all teams, issues go/no-go.",
)

REVIEW = [release_manager, engineering, security, legal, scan_dependencies]
