"""Long prompts through the SERVING engine: the sequence-parallel lane.

Where ``ring_prefill.py`` drives the primitives directly, this demo uses the
production surface: ``RuntimeConfig(long_context=True)`` makes the engine
admit prompts that cannot fit a short-lane slot — ring prefill over an `sp`
mesh of all the engine's devices, context-parallel decode against the
still-sharded prefix — while ordinary short requests keep streaming through
the continuous-batching lane.

Run (8 virtual devices stand in for 8 chips):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context/engine_lane.py
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
import _bootstrap  # noqa: F401 - must run before jax device init

from calfkit_tpu.inference.config import RuntimeConfig, preset
from calfkit_tpu.inference.engine import InferenceEngine


async def main() -> None:
    config = preset("debug")
    engine = InferenceEngine(
        config,
        RuntimeConfig(
            max_batch_size=4,
            max_seq_len=64,          # the short lane's slot capacity
            prefill_chunk=16,
            decode_steps_per_dispatch=4,
            tp=2, dp=4,              # 8 devices; the sp lane spans them all
            long_context=True,       # oversized prompts -> sp lane
            long_new_cap=32,
            chunked_prefill=True,    # long admissions yield between chunks
        ),
    )
    await engine.start()
    # the sp lane spans ALL the engine's devices: dp x tp of the public mesh
    shape = dict(engine.mesh.shape)
    print(f"engine mesh {shape}; "
          f"sp lane over {shape['dp'] * shape['tp']} devices")

    async def short(i: int) -> list[int]:
        return [t async for t in engine.generate([3 + i, 4, 5], max_new_tokens=8)]

    # 180 tokens >> max_seq_len=64: takes the sequence-parallel lane,
    # interleaved with the short requests below
    long_prompt = [(7 * i + 1) % config.vocab_size for i in range(180)]

    async def long_run() -> list[int]:
        return [
            t async for t in engine.generate(long_prompt, max_new_tokens=12)
        ]

    long_out, *short_outs = await asyncio.gather(
        long_run(), short(0), short(1), short(2)
    )
    print(f"long ({len(long_prompt)}-token prompt): {long_out}")
    for i, out in enumerate(short_outs):
        print(f"short {i}: {out}")
    stats = engine.stats
    print(
        f"stats: long_requests={stats.long_requests} "
        f"long_dispatches={stats.long_dispatches} "
        f"short_decode_dispatches={stats.decode_dispatches} "
        f"prefill_tokens={stats.prefill_tokens}"
    )
    await engine.stop()


if __name__ == "__main__":
    asyncio.run(main())
