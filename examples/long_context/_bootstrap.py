"""Shared demo bootstrap: run on 8 virtual CPU devices from a checkout.

Import BEFORE jax: both long-context demos must work on a laptop, and infra
images often export JAX_PLATFORMS pointing at real accelerators (ambient env
is not user intent here — on real chips, drop this import and build the
Mesh over jax.devices() directly).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

os.environ["JAX_PLATFORMS"] = "cpu"
if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
