"""Long-context serving across chips: ring prefill + context-parallel decode.

A prompt too big for one chip's HBM prefills with the SEQUENCE sharded over
the device ring (K/V blocks rotate with ppermute while each chip keeps its
query shard), and decode continues straight through the still-sharded
prefix — partial attention per shard, merged exactly with two collectives.

Run (8 virtual devices stand in for 8 chips):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python examples/long_context/ring_prefill.py
"""

import os
import sys

sys.path.insert(0, os.path.dirname(__file__))
import _bootstrap  # noqa: F401 - must run before jax device init

import jax

import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from calfkit_tpu.inference import model as M
from calfkit_tpu.inference.config import preset
from calfkit_tpu.inference.ring_attention import (
    decode_with_sharded_prefix,
    prefill_sequence_parallel,
)


def main() -> None:
    devices = jax.devices()
    mesh = Mesh(np.array(devices), ("sp",))
    print(f"ring over {len(devices)} devices ({devices[0].platform})")

    config = preset(
        "debug", n_layers=2, n_heads=8, n_kv_heads=4, d_model=128,
        d_ff=256, max_seq_len=2048,
    )
    params = M.init_params(config, jax.random.key(0), dtype=jnp.float32)

    B, S, NEW = 2, 1024, 16
    tokens = jax.random.randint(jax.random.key(1), (B, S), 0, config.vocab_size)

    last_logits, (k, v) = prefill_sequence_parallel(params, config, tokens, mesh)
    print(f"prefilled {S} tokens/seq; KV stays sharded: {k.sharding.spec}")

    first = jnp.argmax(last_logits, axis=-1).astype(jnp.int32)
    generated = decode_with_sharded_prefix(
        params, config, first, (k, v), jnp.full((B,), S, jnp.int32),
        mesh, NEW,
    )
    print(f"decoded {NEW} tokens per sequence through the sharded prefix:")
    for b in range(B):
        print(f"  seq {b}: {np.asarray(generated[b]).tolist()}")


if __name__ == "__main__":
    main()
