"""Expert tools for the internal help desk."""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu.nodes import agent_tool  # noqa: E402


@agent_tool
def reset_password(username: str) -> str:
    """Reset a user's password and send temporary credentials.

    Args:
        username: The account to reset.
    """
    return f"Password for {username!r} reset; temporary credentials emailed."


@agent_tool
def invoice_status(invoice_id: str) -> dict:
    """Look up the payment status of an invoice.

    Args:
        invoice_id: The invoice number.
    """
    return {"invoice_id": invoice_id or "INV-1234", "status": "paid",
            "paid_on": "2026-07-01"}
