"""The help-desk team: a front desk that discovers expert teams at runtime.

The front desk names NO experts in code — ``Messaging(discover=True)`` /
``Handoff(discover=True)`` resolve against the live control plane each
turn, so deploying a new expert (see ``extra_expert.py``) makes it
reachable on the very next question, with no front-desk change.
"""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.models.messages import ModelResponse  # noqa: E402
from calfkit_tpu.nodes import Agent  # noqa: E402
from calfkit_tpu.peers import Handoff, Messaging  # noqa: E402
from examples._common import (  # noqa: E402
    call,
    last_user_text,
    say,
    scripted,
    tool_replies,
)
from tools import invoice_status, reset_password  # noqa: E402

it_expert = Agent(
    "it_expert",
    model=TestModelClient(
        custom_output_text="IT here — the password was reset and temporary "
        "credentials are on their way."
    ),
    instructions="You are the IT expert. Use your tools to fix accounts.",
    tools=[reset_password],
    description="Fixes accounts, passwords, and devices.",
)

billing_expert = Agent(
    "billing_expert",
    model=TestModelClient(
        custom_output_text="Billing here — that invoice was paid on July 1."
    ),
    instructions="You are the billing expert. Use your tools to check invoices.",
    tools=[invoice_status],
    description="Answers invoice and payment questions.",
)


def _route(messages, params):
    """Turn 1: pick an expert from the live directory by topic."""
    text = last_user_text(messages).lower()
    if "security" in text or "breach" in text:
        # a security question is handed off entirely: the expert answers
        # the caller directly and the front desk drops out
        return call("handoff_to_agent", agent_name="security_expert")(
            messages, params
        )
    target = "it_expert" if "password" in text else "billing_expert"
    return call(
        "message_agent",
        agent_name=target,
        message=last_user_text(messages),
    )(messages, params)


def _relay(messages, params):
    """Turn 2: relay the expert's reply to the user."""
    replies = tool_replies(messages)
    detail = replies[-1] if replies else "(no expert reply)"
    return say(f"Front desk: {detail}")(messages, params)


front_desk = Agent(
    "front_desk",
    model=scripted(_route, _relay, name="front-desk-router"),
    instructions=(
        "You are the help-desk front desk. Route each question to the "
        "right expert from the live directory; hand off entirely when the "
        "expert should own the conversation."
    ),
    peers=[Messaging(discover=True), Handoff(discover=True)],
    description="Routes help-desk questions to whichever experts are live.",
)

TEAM = [front_desk, it_expert, billing_expert, reset_password, invoice_status]
