"""Run the help desk end-to-end on the in-memory mesh.

Phase 1: the front desk messages a discovered expert and relays the answer.
Phase 2: a NEW expert worker joins the mesh at runtime; the next question is
handed off to it — the front desk's code never changed.

Run:  python examples/help_desk/run.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu import Client, Worker  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402

from agents import TEAM  # noqa: E402
from extra_expert import NODES as EXTRA  # noqa: E402


async def main() -> None:
    mesh = InMemoryMesh()
    async with Worker(TEAM, mesh=mesh, owns_transport=True):
        client = Client.connect(mesh)
        desk = client.agent("front_desk")

        result = await client.agent("front_desk").execute(
            "I forgot my password, can you help?"
        )
        print(f"[phase 1] {result.output}")

        # ---- deploy a brand-new expert while the mesh is live
        async with Worker(EXTRA, mesh=mesh):
            result = await desk.execute(
                "We may have a security breach on the build server!"
            )
            print(f"[phase 2] {result.output}")
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
