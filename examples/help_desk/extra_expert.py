"""A new expert, deployed later — reachable with zero front-desk changes.

The front desk uses ``discover=True``; the moment this worker's control-plane
advert lands, ``security_expert`` appears in the front desk's live directory
and handoffs to it start succeeding.
"""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.nodes import Agent  # noqa: E402

security_expert = Agent(
    "security_expert",
    model=TestModelClient(
        custom_output_text="Security here — the incident is contained; "
        "rotate your credentials and watch for the follow-up report."
    ),
    instructions="You are the security expert. Own every incident question.",
    description="Handles security incidents and breach questions.",
)

NODES = [security_expert]
