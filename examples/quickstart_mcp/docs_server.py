"""A tiny stdio MCP server: an offline "docs lookup" tool.

Newline-delimited JSON-RPC implementing the MCP subset the toolbox node
speaks (initialize / tools/list / tools/call).  In real deployments this
would be any off-the-shelf MCP server (a web-fetch server, a database
server, ...) — the agent's code is identical either way.
"""

import json
import sys

DOCS = {
    "worker": "Worker hosts nodes on a shared mesh connection; two-phase "
    "lifecycle (resource brackets, then serving brackets).",
    "handoff": "handoff_to_agent transfers the whole conversation; the "
    "target answers the caller directly.",
    "fanout": "Parallel tool calls dispatch as a durable batch; a worker "
    "crash mid-batch never loses completed slots.",
}

TOOLS = [
    {
        "name": "lookup",
        "description": "Look up a topic in the framework docs.",
        "inputSchema": {
            "type": "object",
            "properties": {"topic": {"type": "string"}},
            "required": ["topic"],
        },
    }
]


def reply(rpc_id, result) -> None:
    sys.stdout.write(
        json.dumps({"jsonrpc": "2.0", "id": rpc_id, "result": result}) + "\n"
    )
    sys.stdout.flush()


def main() -> None:
    for line in sys.stdin:
        try:
            message = json.loads(line)
        except ValueError:
            continue
        method = message.get("method")
        rpc_id = message.get("id")
        if method == "initialize":
            reply(rpc_id, {
                "protocolVersion": message["params"]["protocolVersion"],
                "capabilities": {"tools": {}},
                "serverInfo": {"name": "docs-mcp", "version": "0"},
            })
        elif method == "tools/list":
            reply(rpc_id, {"tools": TOOLS})
        elif method == "tools/call":
            args = message["params"].get("arguments", {})
            topic = str(args.get("topic", "")).lower()
            hit = next(
                (text for key, text in DOCS.items() if key in topic),
                f"No doc found for {topic!r}. Known: {sorted(DOCS)}",
            )
            reply(rpc_id, {"content": [{"type": "text", "text": hit}]})
        elif rpc_id is not None:
            reply(rpc_id, {})


if __name__ == "__main__":
    main()
