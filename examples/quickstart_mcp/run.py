"""Give an agent a live MCP tool it never imports.

The MCP server runs as its OWN node on the mesh (``MCPToolboxNode``); the
agent references it by name (``Toolbox("docs")``).  The toolbox advertises
its tools on the control plane, the agent's turn resolves them from the live
capability view, and each call crosses the mesh like any other tool call —
so the toolbox can live in a different process, or a different machine.

Run:  python examples/quickstart_mcp/run.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu import Client, Worker  # noqa: E402
from calfkit_tpu.mcp import MCPServerSpec, MCPToolboxNode, Toolbox  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402
from calfkit_tpu.nodes import Agent  # noqa: E402
from examples._common import call, say, scripted, tool_replies  # noqa: E402

_HERE = os.path.dirname(os.path.abspath(__file__))

docs_toolbox = MCPToolboxNode(
    MCPServerSpec(
        name="docs",
        command=[sys.executable, os.path.join(_HERE, "docs_server.py")],
    )
)


def _lookup(messages, params):
    # MCP tools arrive namespaced: <toolbox-node-id>__<tool-name>
    return call("toolbox.docs__lookup", topic="handoff")(messages, params)


def _answer(messages, params):
    return say(f"From the docs: {tool_replies(messages)[-1]}")(messages, params)


researcher = Agent(
    "docs_researcher",
    model=scripted(_lookup, _answer, name="docs-researcher-model"),
    instructions="Answer questions by looking things up in the docs toolbox.",
    tools=Toolbox("docs"),
    description="Answers questions from the framework docs via MCP.",
)


async def main() -> None:
    mesh = InMemoryMesh()
    async with Worker([researcher, docs_toolbox], mesh=mesh, owns_transport=True):
        client = Client.connect(mesh)
        result = await client.agent("docs_researcher").execute(
            "What does a handoff do?"
        )
        print(result.output)
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
