"""Structured output + parallel tool fan-out in one run.

The agent calls BOTH tools in one model turn (a durable fan-out batch: the
folds survive worker crashes), then returns a typed ``TripPlan`` — the
client gets a validated pydantic object, not prose.

Run:  python examples/structured_fanout/trip_planner.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from pydantic import BaseModel  # noqa: E402

from calfkit_tpu import Agent, Client, Worker  # noqa: E402
from calfkit_tpu.engine import FunctionModelClient  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402
from calfkit_tpu.models.messages import (  # noqa: E402
    ModelResponse,
    TextOutput,
    ToolCallOutput,
)
from calfkit_tpu.nodes import agent_tool  # noqa: E402


class TripPlan(BaseModel):
    city: str
    forecast: str
    budget_eur: int


@agent_tool
def check_weather(city: str) -> str:
    """Forecast for a city.

    Args:
        city: Where.
    """
    return f"sunny in {city}"


@agent_tool
def estimate_budget(city: str, days: int) -> int:
    """Rough budget in EUR.

    Args:
        city: Where.
        days: How long.
    """
    return 120 * days


def plan_model(messages, params):
    """A deterministic 'model': fan out both tools, then emit the plan.

    Swap for JaxLocalModelClient(...) to serve a real model on TPU.
    """
    last = messages[-1]
    returns = {
        p.tool_name: p.content
        for p in last.parts
        if getattr(p, "kind", "") == "tool_return"
    }
    if not returns:  # first turn: one model turn, TWO tool calls → fan-out
        return ModelResponse(parts=[
            ToolCallOutput(tool_call_id="w1", tool_name="check_weather",
                           args={"city": "Lisbon"}),
            ToolCallOutput(tool_call_id="b1", tool_name="estimate_budget",
                           args={"city": "Lisbon", "days": 4}),
        ])
    return ModelResponse(parts=[
        TextOutput(text="Here is the plan."),
        ToolCallOutput(
            tool_call_id="f1", tool_name="final_result",
            args={
                "city": "Lisbon",
                "forecast": str(returns["check_weather"]),
                "budget_eur": int(returns["estimate_budget"]),
            },
        ),
    ])


planner = Agent(
    "planner",
    model=FunctionModelClient(plan_model),
    tools=[check_weather, estimate_budget],
    output_type=TripPlan,
)


async def main() -> None:
    mesh = InMemoryMesh()
    async with Worker([planner, check_weather, estimate_budget], mesh=mesh,
                      owns_transport=True):
        client = Client.connect(mesh)
        gateway = client.agent("planner", output_type=TripPlan)
        handle = await gateway.start("Plan 4 days in Lisbon")
        async for event in handle.stream():
            kind = getattr(getattr(event, "step", None), "kind", "?")
            print(f"  [step] {kind}")
        result = await handle.result(timeout=30)
        plan = result.output
        assert isinstance(plan, TripPlan)
        print(f"PLAN: {plan.city}: {plan.forecast}, ~{plan.budget_eur} EUR")
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
