"""Raw request/reply over the mesh substrate — no nodes, no agents.

The framework's transports are usable standalone: this is the classic
RPC-over-pub/sub recipe (publish with a ``reply_to`` + correlation id,
demux replies by correlation id on one reply topic).  It is what the
Client's hub does under the hood, minus envelopes, state, and the fault
rail — useful for wiring a plain service into the same mesh your agents
run on.

Run:  python examples/rpc_worker.py
"""

import asyncio
import os
import sys
from uuid import uuid4

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402
from calfkit_tpu.mesh.transport import MeshTransport, Record  # noqa: E402


class RPCWorker:
    """Request/reply over any MeshTransport."""

    def __init__(self, mesh: MeshTransport, reply_topic: str):
        self._mesh = mesh
        self._reply_topic = reply_topic
        self._pending: dict[str, asyncio.Future[bytes]] = {}
        self._subscription = None

    async def start(self) -> None:
        self._subscription = await self._mesh.subscribe(
            [self._reply_topic], self._on_reply, group_id=None
        )

    async def stop(self) -> None:
        if self._subscription is not None:
            await self._subscription.stop()

    async def _on_reply(self, record: Record) -> None:
        future = self._pending.pop(record.headers.get("correlation-id", ""), None)
        if future is not None and not future.done():
            future.set_result(record.value)

    async def request(
        self, topic: str, data: bytes, *, timeout: float = 10.0
    ) -> bytes:
        correlation_id = str(uuid4())
        future: asyncio.Future[bytes] = asyncio.get_running_loop().create_future()
        self._pending[correlation_id] = future
        # keyed by correlation id: keyless records forfeit the per-key
        # ordering contract (the transport warns about them)
        await self._mesh.publish(
            topic,
            data,
            key=correlation_id.encode(),
            headers={
                "reply-to": self._reply_topic,
                "correlation-id": correlation_id,
            },
        )
        try:
            return await asyncio.wait_for(future, timeout)
        finally:
            self._pending.pop(correlation_id, None)


async def serve_uppercase(mesh: MeshTransport):
    """The service side: consume requests, publish replies to reply_to."""

    async def handle(record: Record) -> None:
        await mesh.publish(
            record.headers["reply-to"],
            record.value.upper(),
            key=record.key,
            headers={"correlation-id": record.headers["correlation-id"]},
        )

    return await mesh.subscribe(["svc.upper"], handle, group_id="upper-svc")


async def main() -> None:
    mesh = InMemoryMesh()
    await mesh.start()
    service = await serve_uppercase(mesh)

    rpc = RPCWorker(mesh, reply_topic=f"rpc.replies.{uuid4().hex[:8]}")
    await rpc.start()
    reply = await rpc.request("svc.upper", b"hello mesh rpc")
    print(reply.decode())

    await rpc.stop()
    await service.stop()
    await mesh.stop()


if __name__ == "__main__":
    asyncio.run(main())
