"""The quickstart: a weather agent with one tool, fully local.

Mirrors the reference's examples/quickstart weather_agent (BASELINE config 1)
— but where the reference calls a remote HTTPS model API, this runs a local
model client.  Swap ``EchoModelClient`` for ``JaxLocalModelClient(...)`` to
serve a real checkpoint on TPU; the agent code does not change.

Run:  python examples/quickstart/weather_agent.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu import Client, Worker  # noqa: E402
from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402
from calfkit_tpu.nodes import Agent, agent_tool  # noqa: E402


@agent_tool
def get_weather(city: str) -> dict:
    """Get the current weather for a city.

    Args:
        city: Name of the city to look up.
    """
    return {"city": city, "conditions": "sunny", "temp_c": 21.5}


weather_agent = Agent(
    "weather_agent",
    # TestModelClient calls each tool once then summarizes — deterministic,
    # no weights needed. For real inference:
    #   model=JaxLocalModelClient(checkpoint="path/to/llama", mesh_axes={"tp": 8})
    model=TestModelClient(),
    instructions="You are a weather assistant. Use get_weather for lookups.",
    tools=[get_weather],
    description="Answers weather questions using the get_weather tool.",
)


async def main() -> None:
    mesh = InMemoryMesh()
    async with Worker([weather_agent, get_weather], mesh=mesh, owns_transport=True):
        client = Client.connect(mesh)
        handle = await client.agent("weather_agent").start(
            "What's the weather in San Francisco?"
        )
        async for event in handle.stream():
            if hasattr(event, "step"):
                print(f"  [step] {event.step.kind}: "
                      f"{getattr(event.step, 'text', '') or getattr(event.step, 'tool_name', '')}")
            else:
                print(f"RESULT: {event.output}")
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
