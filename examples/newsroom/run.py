"""Run the newsroom: messaging fan-out, then handoff, in one run.

Run:  python examples/newsroom/run.py
"""

import asyncio
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu import Client, Worker  # noqa: E402
from calfkit_tpu.mesh import InMemoryMesh  # noqa: E402

from agents import NEWSROOM  # noqa: E402


async def main() -> None:
    mesh = InMemoryMesh()
    async with Worker(NEWSROOM, mesh=mesh, owns_transport=True):
        client = Client.connect(mesh)
        handle = await client.agent("editor").start(
            "Story tip: the rocket launch has slipped again."
        )
        async for event in handle.stream():
            step = getattr(event, "step", None)
            if step is not None:
                label = getattr(step, "tool_name", "") or getattr(step, "text", "")
                print(f"  [{step.kind}] {str(label)[:76]}")
            else:
                print(f"\nFINAL (from the writer, via handoff): {event.output}")
        await client.close()


if __name__ == "__main__":
    asyncio.run(main())
