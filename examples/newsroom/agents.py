"""The newsroom: messaging and handoff in one run.

An editor consults a researcher and a fact-checker over ``message_agent``
(their conversations stay isolated from the editor's), then hands the story
off to the writer — who answers the caller directly.
"""

import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.abspath(__file__)), "..", "..")
)

from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.nodes import Agent, agent_tool  # noqa: E402
from calfkit_tpu.peers import Handoff, Messaging  # noqa: E402
from examples._common import (  # noqa: E402
    call,
    call_many,
    last_user_text,
    scripted,
    tool_replies,
)


@agent_tool
def archive_search(topic: str) -> list[str]:
    """Search the paper's archive for prior coverage.

    Args:
        topic: What to search for.
    """
    return [f"2025-11-02: early report on {topic}",
            f"2026-03-17: follow-up on {topic}"]


researcher = Agent(
    "researcher",
    model=TestModelClient(
        custom_output_text="Research: two prior pieces exist; the key fact "
        "is the launch date moved to September."
    ),
    instructions="Dig up background from the archive.",
    tools=[archive_search],
    description="Researches story background from the archive.",
)

fact_checker = Agent(
    "fact_checker",
    model=TestModelClient(
        custom_output_text="Fact-check: the September date is confirmed by "
        "two sources. Clear to publish."
    ),
    instructions="Verify claims before publication.",
    description="Verifies claims before publication.",
)

writer = Agent(
    "writer",
    model=TestModelClient(
        custom_output_text="HEADLINE: Launch slips to September — what it "
        "means, in 400 carefully fact-checked words."
    ),
    instructions="Write the final story beautifully.",
    description="Writes the final story.",
)


def _consult(messages, params):
    """Turn 1: consult researcher AND fact-checker in one fan-out."""
    story = last_user_text(messages)
    return call_many(
        ("message_agent", {"agent_name": "researcher", "message": story}),
        ("message_agent", {"agent_name": "fact_checker",
                           "message": f"Verify the claims in: {story}"}),
    )(messages, params)


def _handoff(messages, params):
    """Turn 2: both replies are in — hand the story to the writer."""
    assert len(tool_replies(messages)) >= 2
    return call("handoff_to_agent", agent_name="writer")(messages, params)


editor = Agent(
    "editor",
    model=scripted(_consult, _handoff, name="editor-model"),
    instructions=(
        "You are the editor. Consult the researcher and the fact-checker, "
        "then hand the story off to the writer."
    ),
    peers=[Messaging("researcher", "fact_checker"), Handoff("writer")],
    description="Runs the newsroom: consults the desk, assigns the writer.",
)

NEWSROOM = [editor, researcher, fact_checker, writer, archive_search]
