"""Agents over the REAL Kafka wire protocol — zero external dependencies.

Production meshes are Kafka-compatible clusters; this example runs the
same shape locally: it spawns ``kafkad`` (the in-repo native broker
speaking the real Kafka wire protocol — RecordBatch v2, consumer groups,
offset commits), hosts an agent on a ``KafkaWireMesh`` worker connection,
and talks to it from a SEPARATE client connection.  Swap the bootstrap
string for a real Kafka/Redpanda cluster and nothing else changes.

Build the broker once with ``make -C native``.

Run:  python examples/kafka_mesh.py
"""

import asyncio
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)), ".."))

from calfkit_tpu import Client, Worker  # noqa: E402
from calfkit_tpu.engine import TestModelClient  # noqa: E402
from calfkit_tpu.mesh import KafkaWireMesh  # noqa: E402
from calfkit_tpu.mesh.kafka_wire import find_kafkad, spawn_kafkad  # noqa: E402
from calfkit_tpu.nodes import Agent, agent_tool  # noqa: E402


@agent_tool
def lookup_order(order_id: str) -> dict:
    """Look up an order's status.

    Args:
        order_id: The order to check.
    """
    return {"order_id": order_id, "status": "shipped", "eta_days": 2}


async def main() -> None:
    if find_kafkad() is None:
        print("kafkad not built — run `make -C native` first")
        return
    broker = spawn_kafkad(0)  # port 0: OS-assigned, reported on stdout
    bootstrap = f"127.0.0.1:{broker.kafkad_port}"
    print(f"kafkad up on {bootstrap} (real Kafka wire protocol)")
    try:
        # worker and client as SEPARATE broker connections — the
        # production topology, not an in-process shortcut
        worker_mesh = KafkaWireMesh(bootstrap)
        client_mesh = KafkaWireMesh(bootstrap)
        await client_mesh.start()

        agent = Agent(
            "order_desk",
            model=TestModelClient(
                custom_output_text="Order 742 has shipped; ETA 2 days."
            ),
            instructions="Answer order questions using the lookup tool.",
            tools=[lookup_order],
        )
        async with Worker(
            [agent, lookup_order], mesh=worker_mesh, owns_transport=True
        ):
            client = Client.connect(client_mesh)
            result = await client.agent("order_desk").execute(
                "Where is order 742?", timeout=60
            )
            print(f"RESULT over kafka: {result.output}")
            await client.close()
        await client_mesh.stop()
    finally:
        broker.terminate()
        broker.wait(timeout=5)


if __name__ == "__main__":
    asyncio.run(main())
